"""Long-context streaming: aggregate datasets LARGER THAN DEVICE MEMORY,
within one materialization or ACROSS micro-batches of a standing pipeline.

SURVEY §5 flags this as the piece to design fresh for TPU: "blocks-per-
shard streaming of partitions larger than HBM, donated-buffer chunked
scans". The design here:

- the input arrives as a STREAM of host chunks (a
  ``LocalDataFrameIterableDataFrame`` — the same streaming vehicle the
  reference feeds through Spark's ``mapInPandas``);
- per-segment accumulators (sum / count / min / max per group) live on
  device; each chunk is padded to a power-of-two bucket (bounding XLA
  retraces to O(log max-chunk)) and folded into the accumulators by ONE
  jitted update step with the accumulator buffers DONATED
  (``donate_argnums``), so XLA reuses their memory in place and peak
  device residency is O(chunk + num_groups), independent of the total
  row count;
- group keys use the mixed-radix binning of groupby.py; when a chunk's
  key range exceeds the current bin space the accumulators are RE-BASED
  onto the wider space on device (amortized: ranges stabilize after the
  first chunks). With ``pad_spans=True`` every key span is rounded up to
  a power of two, so moderate key growth lands INSIDE the padded space
  and neither rebases nor recompiles — the knob the continuous-execution
  driver (``fugue_tpu/stream``) turns on so a standing pipeline's update
  program compiles once and then only executes;
- accumulator dtypes follow the SOURCE columns (int64 sums/extrema stay
  exact int64; floats accumulate f64) and all-null groups finalize to
  NULL — the same conventions the bounded device path produces;
- the per-chunk pytree STRUCTURE is shape-stable: every payload column
  always carries a validity mask (all-True when no value is null), so a
  chunk that suddenly contains nulls — or is entirely null — folds
  through the already-compiled program instead of retracing;
- anything the bounded-memory path cannot honor (NULL keys, a key space
  beyond ``groupby._MAX_BINS``, an empty stream) raises
  :class:`StreamUnsupported`; the one-shot :func:`stream_aggregate`
  wrapper converts it to :class:`StreamFallback` carrying the already-
  consumed chunks plus the rest of the iterator, and the engine
  MATERIALIZES and re-runs on the bounded path — semantics never depend
  on the container type.

:class:`StreamingAggregator` is the stateful core: the serving-facing
micro-batch driver keeps ONE aggregator alive across micro-batches
(device-resident accumulators carried between materializations),
``snapshot()``/``from_snapshot()`` round-trip the state through the
exactly-once progress manifest, and ``traces`` counts XLA traces of the
update program — the "zero recompiles after the first micro-batch"
counter the continuous bench and tests assert on.

This is the TPU analog of an out-of-core groupby: a terabyte-scale keyed
reduction runs through a fixed HBM footprint.
"""

from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Tuple,
)

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pyarrow as pa

from fugue_tpu.jax_backend import groupby
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw

_SUPPORTED = ("sum", "count", "min", "max", "avg", "mean")


class StreamUnsupported(Exception):
    """This chunk cannot stream under bounded-path semantics (NULL group
    keys, key space beyond the bin cap, ...). One-shot callers fall back
    to the bounded path; the standing-pipeline driver surfaces it as a
    pipeline error (a tailed source with NULL keys is a data contract
    violation, not a container artifact)."""


class StreamFallback(Exception):
    """Streaming cannot honor bounded-path semantics for this input; the
    caller should materialize ``consumed + rest`` and use the bounded
    path."""

    def __init__(
        self, reason: str, consumed: List[pd.DataFrame], rest: Iterator[Any]
    ):
        super().__init__(reason)
        self.consumed = consumed
        self.rest = rest


class _Space:
    """Current mixed-radix key space: per-key (lo, hi) bounds."""

    def __init__(self, bounds: List[Tuple[int, int]]):
        self.bounds = bounds

    @property
    def total(self) -> int:
        t = 1
        for lo, hi in self.bounds:
            t *= hi - lo + 1
        return t

    def contains(self, other: List[Tuple[int, int]]) -> bool:
        return all(
            lo <= olo and ohi <= hi
            for (lo, hi), (olo, ohi) in zip(self.bounds, other)
        )

    def union(self, other: List[Tuple[int, int]]) -> "_Space":
        return _Space(
            [
                (min(lo, olo), max(hi, ohi))
                for (lo, hi), (olo, ohi) in zip(self.bounds, other)
            ]
        )

    def seg(self, cols: List[jnp.ndarray]) -> jnp.ndarray:
        # int32 is safe: total is capped at groupby._MAX_BINS (1<<22)
        combined = jnp.zeros(cols[0].shape, dtype=jnp.int32)
        for (lo, hi), c in zip(self.bounds, cols):
            span = hi - lo + 1
            combined = combined * jnp.int32(span) + (c - lo).astype(jnp.int32)
        return combined

    def decode(self, idx: np.ndarray) -> List[np.ndarray]:
        out: List[np.ndarray] = []
        strides: List[int] = []
        t = 1
        for lo, hi in reversed(self.bounds):
            strides.append(t)
            t *= hi - lo + 1
        strides.reverse()
        for (lo, hi), s in zip(self.bounds, strides):
            span = hi - lo + 1
            out.append((idx // s) % span + lo)
        return out


def _bucket_len(n: int) -> int:
    """Smallest power of two >= n (>= 256): bounds jit retraces."""
    b = 256
    while b < n:
        b <<= 1
    return b


def _pad_bounds(bounds: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """Round every key span up to a power of two (anchored at lo): key
    growth within the padded span neither rebases nor retraces. Padding
    slots never emit — finalize keeps occupied groups only."""
    out: List[Tuple[int, int]] = []
    for lo, hi in bounds:
        span = hi - lo + 1
        p = 1
        while p < span:
            p <<= 1
        out.append((lo, lo + p - 1))
    return out


def _acc_dtype(tp: pa.DataType) -> Any:
    if pa.types.is_floating(tp):
        return jnp.float64
    return jnp.int64


def _type_extreme(dtype: Any, is_min: bool) -> Any:
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if is_min else -jnp.inf
    info = jnp.iinfo(dtype)
    return info.max if is_min else info.min


class StreamingAggregator:
    """Per-group device accumulators fed chunk by chunk — the state unit
    carried WITHIN one materialization (the one-shot
    :func:`stream_aggregate`) and ACROSS micro-batches (the standing-
    pipeline driver keeps one instance alive between refreshes and
    checkpoints it through ``snapshot``).

    ``plans`` is a list of ``(out_name, func, src_col)`` with ``func``
    in :data:`_SUPPORTED`. ``traces`` counts XLA traces of the update
    program (the body only runs in Python while jax traces it), so
    "zero recompiles after micro-batch 1" is directly assertable.
    """

    def __init__(
        self,
        engine: Any,
        schema: Schema,
        keys: List[str],
        plans: List[Tuple[str, str, str]],
        pad_spans: bool = False,
    ):
        for _, func, _ in plans:
            assert_or_throw(
                func in _SUPPORTED,
                NotImplementedError(f"streaming aggregation {func}"),
            )
        self._engine = engine
        self._schema = schema
        self._keys = list(keys)
        self._plans = [tuple(p) for p in plans]
        self._pad_spans = pad_spans
        self._src_types: Dict[str, pa.DataType] = {}
        for _, _, src in plans:
            self._src_types[src] = schema[src].type
        self._space: Optional[_Space] = None
        self._acc: Optional[Dict[str, jnp.ndarray]] = None
        self._update_cache: Dict[int, Any] = {}
        self.traces = 0
        self.rebases = 0
        self.chunks_folded = 0
        self.rows_folded = 0

    # ---- observability ---------------------------------------------------
    @property
    def empty(self) -> bool:
        return self._space is None

    @property
    def num_groups_bound(self) -> int:
        """Allocated accumulator slots (occupied groups <= this)."""
        return 0 if self._space is None else self._space.total

    @property
    def key_bounds(self) -> Optional[List[Tuple[int, int]]]:
        """Current per-key (lo, hi) bin bounds, keys-ordered; None when
        no data folded yet — what retention eviction reasons over."""
        return None if self._space is None else list(self._space.bounds)

    def stats(self) -> Dict[str, int]:
        return {
            "traces": self.traces,
            "programs": len(self._update_cache),
            "rebases": self.rebases,
            "chunks": self.chunks_folded,
            "rows": self.rows_folded,
            "group_slots": self.num_groups_bound,
        }

    # ---- accumulator construction ----------------------------------------
    def _make_init(self, total: int) -> Dict[str, jnp.ndarray]:
        gov = getattr(self._engine, "_memory", None)
        if gov is not None:
            # accumulator (re)allocation goes through the governor's
            # pre-alloc gate: watermark spill may run first, and the
            # device.alloc fault site makes streaming accumulator OOM
            # deterministically testable. Upper bound: 8B per slot per
            # accumulator vector (count + up to 2 per plan). The tier
            # key honors the fault layer's host-degrade override so a
            # degraded re-run no longer matches a "device" fault spec.
            override = getattr(
                getattr(self._engine, "_tier_override", None), "mode", None
            )
            tier = "host" if override == "host" else "device"
            gov.pre_alloc(tier, total * 8 * (1 + 2 * len(self._plans)))
        accs: Dict[str, jnp.ndarray] = {
            "_count": jnp.zeros((total,), jnp.int64)
        }
        for name, func, src in self._plans:
            dt = _acc_dtype(self._src_types[src])
            if func in ("sum", "avg", "mean"):
                accs[f"s:{name}"] = jnp.zeros(
                    (total,), jnp.float64 if func != "sum" else dt
                )
                accs[f"c:{name}"] = jnp.zeros((total,), jnp.int64)
            elif func == "count":
                accs[f"c:{name}"] = jnp.zeros((total,), jnp.int64)
            elif func == "min":
                accs[f"m:{name}"] = jnp.full(
                    (total,), _type_extreme(dt, True), dtype=dt
                )
                accs[f"c:{name}"] = jnp.zeros((total,), jnp.int64)
            elif func == "max":
                accs[f"m:{name}"] = jnp.full(
                    (total,), _type_extreme(dt, False), dtype=dt
                )
                accs[f"c:{name}"] = jnp.zeros((total,), jnp.int64)
        return accs

    def _get_update(self, total: int) -> Any:
        if total in self._update_cache:
            return self._update_cache[total]
        plans = self._plans

        def _update(
            accs: Dict[str, jnp.ndarray],
            key_cols: Tuple[jnp.ndarray, ...],
            data: Dict[str, jnp.ndarray],
            masks: Dict[str, jnp.ndarray],
            row_valid: jnp.ndarray,
            bounds: Tuple[Tuple[int, int], ...],
        ) -> Dict[str, jnp.ndarray]:
            # the body executes in Python only while jax TRACES it:
            # this counter is therefore an exact XLA-(re)trace count
            self.traces += 1
            seg = _Space(list(bounds)).seg(list(key_cols))
            # padding rows get the out-of-range sentinel (dropped)
            seg = jnp.where(row_valid, seg, jnp.int32(total))
            out = dict(accs)
            out["_count"] = accs["_count"] + jax.ops.segment_sum(
                row_valid.astype(jnp.int64), seg, num_segments=total
            )
            for name, func, src in plans:
                v = data[src]
                m = masks.get(src)
                eff = row_valid if m is None else (m & row_valid)
                effc = jax.ops.segment_sum(
                    eff.astype(jnp.int64), seg, num_segments=total
                )
                if func in ("sum", "avg", "mean"):
                    adt = out[f"s:{name}"].dtype
                    out[f"s:{name}"] = accs[f"s:{name}"] + jax.ops.segment_sum(
                        jnp.where(eff, v, 0).astype(adt),
                        seg, num_segments=total,
                    )
                    out[f"c:{name}"] = accs[f"c:{name}"] + effc
                elif func == "count":
                    out[f"c:{name}"] = accs[f"c:{name}"] + effc
                elif func in ("min", "max"):
                    adt = out[f"m:{name}"].dtype
                    sentinel = _type_extreme(adt, func == "min")
                    filled = jnp.where(eff, v, sentinel).astype(adt)
                    red = (
                        jax.ops.segment_min
                        if func == "min"
                        else jax.ops.segment_max
                    )(filled, seg, num_segments=total)
                    out[f"m:{name}"] = (
                        jnp.minimum(accs[f"m:{name}"], red)
                        if func == "min"
                        else jnp.maximum(accs[f"m:{name}"], red)
                    )
                    out[f"c:{name}"] = accs[f"c:{name}"] + effc
            return out

        jitted = jax.jit(
            _update, static_argnames=("bounds",), donate_argnums=0
        )
        self._update_cache[total] = jitted
        return jitted

    def _rebase(
        self, old_space: _Space, new_space: _Space,
        accs: Dict[str, jnp.ndarray],
    ) -> Dict[str, jnp.ndarray]:
        """Scatter old accumulators into the widened segment space."""
        old_idx = np.arange(old_space.total)
        key_vals = old_space.decode(old_idx)
        new_seg = np.zeros(old_space.total, dtype=np.int64)
        for (lo, hi), kv in zip(new_space.bounds, key_vals):
            span = hi - lo + 1
            new_seg = new_seg * span + (kv - lo)
        fresh = self._make_init(new_space.total)
        out: Dict[str, jnp.ndarray] = {}
        seg_dev = jnp.asarray(new_seg)
        for k, v in accs.items():
            out[k] = fresh[k].at[seg_dev].set(v.astype(fresh[k].dtype))
        self.rebases += 1
        return out

    # ---- folding ---------------------------------------------------------
    def fold(self, pdf: pd.DataFrame) -> int:
        """Fold one host chunk into the device accumulators; returns the
        row count folded. An EMPTY chunk is a no-op (an idle micro-batch
        tick must not touch device state, let alone retrace). Raises
        :class:`StreamUnsupported` when bounded-path semantics cannot be
        honored for this chunk."""
        n = len(pdf)
        if n == 0:
            return 0
        if pdf[self._keys].isna().any().any():
            raise StreamUnsupported("NULL group keys")
        cb = [
            (int(pdf[k].min()), int(pdf[k].max())) for k in self._keys
        ]
        space = self._space
        if space is not None and space.contains(cb):
            cand = space
        else:
            raw = cb if space is None else space.union(cb).bounds
            padded = _pad_bounds(raw) if self._pad_spans else raw
            cand = _Space(padded)
            if (
                self._pad_spans
                and cand.total > groupby._MAX_BINS
                and _Space(list(raw)).total <= groupby._MAX_BINS
            ):
                cand = _Space(list(raw))  # padding overflowed: exact fit
        if cand.total > groupby._MAX_BINS:
            raise StreamUnsupported("key space too large")
        if space is None:
            self._space = cand
            self._acc = self._make_init(cand.total)
        elif cand is not space:
            self._acc = self._rebase(space, cand, self._acc)
            self._space = cand
        space = self._space
        update = self._get_update(space.total)
        bucket = _bucket_len(n)
        row_valid = jnp.asarray(np.arange(bucket) < n)

        def _padded(npv: np.ndarray, fill: Any = 0) -> jnp.ndarray:
            if len(npv) < bucket:
                out = np.full((bucket,), fill, dtype=npv.dtype)
                out[: len(npv)] = npv
                npv = out
            return jnp.asarray(npv)

        key_cols = tuple(
            _padded(
                np.asarray(pdf[k].to_numpy()).astype(np.int64, copy=False)
            )
            for k in self._keys
        )
        data: Dict[str, jnp.ndarray] = {}
        masks: Dict[str, jnp.ndarray] = {}
        for c in sorted(self._src_types):
            series = pdf[c]
            tp = self._src_types[c]
            want = np.float64 if pa.types.is_floating(tp) else np.int64
            valid = ~pd.isna(series).to_numpy()
            npv = series.to_numpy()
            if npv.dtype.kind == "f" and want is np.int64:
                # an int column that picked up nulls arrives as float
                # (pandas NaN promotion): mask the nulls, fold the rest
                # back through int64 so exact integer sums stay exact
                npv = np.nan_to_num(npv).astype(np.int64)
            elif npv.dtype.kind == "f":
                npv = np.nan_to_num(npv)
            elif npv.dtype.kind not in "iuf":
                # pandas nullable / object storage: realize through the
                # schema dtype with nulls zero-filled under the mask
                npv = (
                    series.fillna(0).to_numpy(dtype=want)
                    if not valid.all()
                    else series.to_numpy(dtype=want)
                )
            # ALWAYS carry a mask: the pytree structure stays identical
            # whether this chunk has nulls or not, so an all-null (or
            # first-null) chunk reuses the compiled program
            masks[c] = _padded(valid, False)
            data[c] = _padded(npv)
        self._acc = update(
            self._acc, key_cols, data, masks, row_valid,
            tuple(space.bounds),
        )
        self.chunks_folded += 1
        self.rows_folded += n
        return n

    def evict_leading_below(self, lo_new: int) -> int:
        """Drop all accumulator slots whose LEADING key is below
        ``lo_new`` — the standing pipeline's window-state retention:
        without eviction a windowed pipeline's window-id span grows
        monotonically with wall time until it exceeds the bin cap and
        every fold fails. The leading key is the most-significant radix,
        so its slots are CONTIGUOUS prefixes: eviction is one slice per
        accumulator vector (no scatter), and the narrowed space re-pads
        from the new lo on the next fold. Returns evicted slot count;
        an eviction past the whole space resets to empty."""
        if self._space is None:
            return 0
        lo, hi = self._space.bounds[0]
        if lo_new <= lo:
            return 0
        total = self._space.total
        span0 = hi - lo + 1
        stride = total // span0
        if lo_new > hi:
            evicted = total
            self._space = None
            self._acc = None
            return evicted
        offset = (lo_new - lo) * stride
        self._acc = {
            k: v[offset:] for k, v in (self._acc or {}).items()
        }
        self._space = _Space(
            [(lo_new, hi)] + list(self._space.bounds[1:])
        )
        return offset

    # ---- state checkpoint (exactly-once restart) -------------------------
    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable copy of the full accumulator state — what
        the standing pipeline's progress manifest commits per micro-
        batch, atomically together with the consumed-file set."""
        out: Dict[str, Any] = {
            "version": 1,
            "keys": list(self._keys),
            "plans": [list(p) for p in self._plans],
            "schema": str(self._schema),
            "pad_spans": self._pad_spans,
            "chunks": self.chunks_folded,
            "rows": self.rows_folded,
        }
        if self._space is None:
            out["bounds"] = None
            out["acc"] = {}
            return out
        out["bounds"] = [list(b) for b in self._space.bounds]
        acc: Dict[str, Any] = {}
        for k, v in (self._acc or {}).items():
            host = np.asarray(v)
            acc[k] = {"dtype": str(host.dtype), "data": host.tolist()}
        out["acc"] = acc
        return out

    @classmethod
    def from_snapshot(
        cls, engine: Any, snap: Dict[str, Any]
    ) -> "StreamingAggregator":
        """Rebuild an aggregator from :meth:`snapshot` — the restart
        path. The restored update program re-traces ONCE on the first
        fold of the new process (XLA state died with the old one)."""
        agg = cls(
            engine,
            Schema(snap["schema"]),
            list(snap["keys"]),
            [tuple(p) for p in snap["plans"]],
            pad_spans=bool(snap.get("pad_spans", False)),
        )
        agg.chunks_folded = int(snap.get("chunks", 0))
        agg.rows_folded = int(snap.get("rows", 0))
        bounds = snap.get("bounds")
        if bounds is None:
            return agg
        agg._space = _Space([tuple(b) for b in bounds])
        acc: Dict[str, jnp.ndarray] = {}
        for k, rec in (snap.get("acc") or {}).items():
            acc[k] = jnp.asarray(
                np.asarray(rec["data"], dtype=np.dtype(rec["dtype"]))
            )
        agg._acc = acc
        return agg

    # ---- finalize --------------------------------------------------------
    def finalize(
        self,
        key_filter: Optional[
            Callable[[Dict[str, np.ndarray]], np.ndarray]
        ] = None,
        key_transform: Optional[
            Dict[str, Tuple[Callable[[np.ndarray], np.ndarray], pa.DataType]]
        ] = None,
    ) -> Any:
        """Materialize the CURRENT accumulator state as a JaxDataFrame of
        ``keys + [out names]`` (occupied groups only; all-null groups
        finalize to NULL) — NON-destructive, so a standing pipeline
        refreshes its view and keeps folding. ``key_filter`` gets the
        decoded key vectors and returns a boolean keep-mask (watermark
        emission gates closed windows here); ``key_transform`` rewrites
        a key column's values/type on the way out (window id -> window
        start). Returns None when nothing is emittable (no data folded
        yet, or the filter kept nothing)."""
        from fugue_tpu.jax_backend.blocks import (
            JaxBlocks,
            JaxColumn,
            padded_len,
            row_sharding,
        )
        from fugue_tpu.jax_backend.dataframe import JaxDataFrame

        if self._space is None:
            return None
        host = {k: np.asarray(v) for k, v in self._acc.items()}  # type: ignore
        occupied = np.nonzero(host["_count"] > 0)[0]
        key_vals = self._space.decode(occupied)
        if key_filter is not None and len(occupied) > 0:
            keep = np.asarray(
                key_filter(dict(zip(self._keys, key_vals))), dtype=bool
            )
            occupied = occupied[keep]
            key_vals = [kv[keep] for kv in key_vals]
        if len(occupied) == 0:
            return None
        cols: Dict[str, Any] = {}
        fields = []
        mesh = self._engine.mesh
        ndev = int(mesh.devices.size)
        n = len(occupied)
        pad_n = padded_len(n, ndev)
        sharding = row_sharding(mesh)

        def _dev(arr: np.ndarray, dtype: Any) -> Any:
            out = np.zeros((pad_n,), dtype=dtype)
            out[:n] = arr
            return jax.device_put(jnp.asarray(out), sharding)

        for k, kv in zip(self._keys, key_vals):
            field = self._schema[k]
            if key_transform is not None and k in key_transform:
                fn, tp = key_transform[k]
                kv = fn(kv)
                field = pa.field(k, tp)
            cols[k] = JaxColumn(
                field.type, _dev(kv, field.type.to_pandas_dtype()),
                stats=(
                    (int(kv.min()), int(kv.max()))
                    if n and np.issubdtype(np.asarray(kv).dtype, np.integer)
                    else None
                ),
            )
            fields.append(field)
        for name, func, src in self._plans:
            cnt = (
                host[f"c:{name}"][occupied] if f"c:{name}" in host else None
            )
            if func == "sum":
                vals = host[f"s:{name}"][occupied]
                tp = (
                    pa.int64()
                    if not pa.types.is_floating(self._src_types[src])
                    else pa.float64()
                )
            elif func in ("avg", "mean"):
                vals = host[f"s:{name}"][occupied] / np.maximum(cnt, 1)
                tp = pa.float64()
            elif func == "count":
                vals = cnt
                tp = pa.int64()
            else:  # min / max
                vals = host[f"m:{name}"][occupied]
                tp = (
                    pa.int64()
                    if not pa.types.is_floating(self._src_types[src])
                    else pa.float64()
                )
            col = JaxColumn(tp, _dev(vals, tp.to_pandas_dtype()))
            if func != "count" and cnt is not None:
                mask_np = cnt > 0  # all-null group -> NULL (SQL semantics)
                if not mask_np.all():
                    col.mask = _dev(mask_np, np.bool_)
            cols[name] = col
            fields.append(pa.field(name, tp))
        out_schema = Schema(fields)
        return JaxDataFrame(JaxBlocks(n, cols, mesh), out_schema)


def stream_aggregate(
    engine: Any,
    chunks: Iterator[pd.DataFrame],
    schema: Schema,
    keys: List[str],
    plans: List[Tuple[str, str, str]],
) -> Any:
    """Fold a chunk stream into per-group accumulators on device — the
    one-shot (single materialization) entry the engine's aggregate path
    calls. Returns a JaxDataFrame of ``keys + [out names]``. Raises
    :class:`StreamFallback` when bounded-path semantics can't be honored
    (the caller materializes and re-runs)."""
    agg = StreamingAggregator(engine, schema, keys, plans)
    consumed: List[pd.DataFrame] = []
    it = iter(chunks)
    for pdf in it:
        consumed.append(pdf)
        try:
            agg.fold(pdf)
        except StreamUnsupported as ex:
            # the consumed buffer holds REFERENCES to the caller's
            # chunks, not copies: the bounded path re-reads them
            raise StreamFallback(str(ex), consumed, it)
    if agg.empty:
        raise StreamFallback("empty stream", consumed, it)
    res = agg.finalize()
    assert res is not None  # non-empty aggregator always emits
    return res


def materialize_fallback(
    fb: StreamFallback, schema: Schema
) -> pd.DataFrame:
    """Concatenate the consumed chunks + the rest of the stream into one
    pandas frame for the bounded path."""
    rest = [pdf for pdf in fb.rest]
    parts = [p for p in fb.consumed + rest if len(p) > 0]
    if not parts:
        return pd.DataFrame({n: pd.Series(dtype=object) for n in schema.names})
    return pd.concat(parts, ignore_index=True)
