"""Long-context streaming: aggregate datasets LARGER THAN DEVICE MEMORY.

SURVEY §5 flags this as the piece to design fresh for TPU: "blocks-per-
shard streaming of partitions larger than HBM, donated-buffer chunked
scans". The design here:

- the input arrives as a STREAM of host chunks (a
  ``LocalDataFrameIterableDataFrame`` — the same streaming vehicle the
  reference feeds through Spark's ``mapInPandas``);
- per-segment accumulators (sum / count / min / max per group) live on
  device; each chunk is padded to a power-of-two bucket (bounding XLA
  retraces to O(log max-chunk)) and folded into the accumulators by ONE
  jitted update step with the accumulator buffers DONATED
  (``donate_argnums``), so XLA reuses their memory in place and peak
  device residency is O(chunk + num_groups), independent of the total
  row count;
- group keys use the mixed-radix binning of groupby.py; when a chunk's
  key range exceeds the current bin space the accumulators are RE-BASED
  onto the wider space on device (amortized: ranges stabilize after the
  first chunks);
- accumulator dtypes follow the SOURCE columns (int64 sums/extrema stay
  exact int64; floats accumulate f64) and all-null groups finalize to
  NULL — the same conventions the bounded device path produces;
- anything the bounded-memory path cannot honor (NULL keys, a key space
  beyond ``groupby._MAX_BINS``, an empty stream) raises
  :class:`StreamFallback` carrying the already-consumed chunks plus the
  rest of the iterator, and the engine MATERIALIZES and re-runs on the
  bounded path — semantics never depend on the container type.

This is the TPU analog of an out-of-core groupby: a terabyte-scale keyed
reduction runs through a fixed HBM footprint.
"""

from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pyarrow as pa

from fugue_tpu.jax_backend import groupby
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw

_SUPPORTED = ("sum", "count", "min", "max", "avg", "mean")


class StreamFallback(Exception):
    """Streaming cannot honor bounded-path semantics for this input; the
    caller should materialize ``consumed + rest`` and use the bounded
    path."""

    def __init__(
        self, reason: str, consumed: List[pd.DataFrame], rest: Iterator[Any]
    ):
        super().__init__(reason)
        self.consumed = consumed
        self.rest = rest


class _Space:
    """Current mixed-radix key space: per-key (lo, hi) bounds."""

    def __init__(self, bounds: List[Tuple[int, int]]):
        self.bounds = bounds

    @property
    def total(self) -> int:
        t = 1
        for lo, hi in self.bounds:
            t *= hi - lo + 1
        return t

    def contains(self, other: List[Tuple[int, int]]) -> bool:
        return all(
            lo <= olo and ohi <= hi
            for (lo, hi), (olo, ohi) in zip(self.bounds, other)
        )

    def union(self, other: List[Tuple[int, int]]) -> "_Space":
        return _Space(
            [
                (min(lo, olo), max(hi, ohi))
                for (lo, hi), (olo, ohi) in zip(self.bounds, other)
            ]
        )

    def seg(self, cols: List[jnp.ndarray]) -> jnp.ndarray:
        # int32 is safe: total is capped at groupby._MAX_BINS (1<<22)
        combined = jnp.zeros(cols[0].shape, dtype=jnp.int32)
        for (lo, hi), c in zip(self.bounds, cols):
            span = hi - lo + 1
            combined = combined * jnp.int32(span) + (c - lo).astype(jnp.int32)
        return combined

    def decode(self, idx: np.ndarray) -> List[np.ndarray]:
        out: List[np.ndarray] = []
        strides: List[int] = []
        t = 1
        for lo, hi in reversed(self.bounds):
            strides.append(t)
            t *= hi - lo + 1
        strides.reverse()
        for (lo, hi), s in zip(self.bounds, strides):
            span = hi - lo + 1
            out.append((idx // s) % span + lo)
        return out


def _bucket_len(n: int) -> int:
    """Smallest power of two >= n (>= 256): bounds jit retraces."""
    b = 256
    while b < n:
        b <<= 1
    return b


def _acc_dtype(tp: pa.DataType) -> Any:
    if pa.types.is_floating(tp):
        return jnp.float64
    return jnp.int64


def _type_extreme(dtype: Any, is_min: bool) -> Any:
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if is_min else -jnp.inf
    info = jnp.iinfo(dtype)
    return info.max if is_min else info.min


def stream_aggregate(
    engine: Any,
    chunks: Iterator[pd.DataFrame],
    schema: Schema,
    keys: List[str],
    plans: List[Tuple[str, str, str]],  # (out_name, func, src_col)
) -> Any:
    """Fold a chunk stream into per-group accumulators on device.

    Returns a JaxDataFrame of ``keys + [out names]``. Raises
    :class:`StreamFallback` when bounded-path semantics can't be honored
    (the caller materializes and re-runs)."""
    from fugue_tpu.jax_backend.blocks import (
        JaxBlocks,
        JaxColumn,
        padded_len,
        row_sharding,
    )
    from fugue_tpu.jax_backend.dataframe import JaxDataFrame

    for _, func, _ in plans:
        assert_or_throw(
            func in _SUPPORTED,
            NotImplementedError(f"streaming aggregation {func}"),
        )
    src_types: Dict[str, pa.DataType] = {}
    for _, func, src in plans:
        src_types[src] = schema[src].type

    space: Optional[_Space] = None
    acc: Optional[Dict[str, jnp.ndarray]] = None
    update_cache: Dict[int, Any] = {}

    def _make_init(total: int) -> Dict[str, jnp.ndarray]:
        gov = getattr(engine, "_memory", None)
        if gov is not None:
            # accumulator (re)allocation goes through the governor's
            # pre-alloc gate: watermark spill may run first, and the
            # device.alloc fault site makes streaming accumulator OOM
            # deterministically testable. Upper bound: 8B per slot per
            # accumulator vector (count + up to 2 per plan). The tier
            # key honors the fault layer's host-degrade override so a
            # degraded re-run no longer matches a "device" fault spec.
            override = getattr(
                getattr(engine, "_tier_override", None), "mode", None
            )
            tier = "host" if override == "host" else "device"
            gov.pre_alloc(tier, total * 8 * (1 + 2 * len(plans)))
        accs: Dict[str, jnp.ndarray] = {
            "_count": jnp.zeros((total,), jnp.int64)
        }
        for name, func, src in plans:
            dt = _acc_dtype(src_types[src])
            if func in ("sum", "avg", "mean"):
                accs[f"s:{name}"] = jnp.zeros(
                    (total,), jnp.float64 if func != "sum" else dt
                )
                accs[f"c:{name}"] = jnp.zeros((total,), jnp.int64)
            elif func == "count":
                accs[f"c:{name}"] = jnp.zeros((total,), jnp.int64)
            elif func == "min":
                accs[f"m:{name}"] = jnp.full(
                    (total,), _type_extreme(dt, True), dtype=dt
                )
                accs[f"c:{name}"] = jnp.zeros((total,), jnp.int64)
            elif func == "max":
                accs[f"m:{name}"] = jnp.full(
                    (total,), _type_extreme(dt, False), dtype=dt
                )
                accs[f"c:{name}"] = jnp.zeros((total,), jnp.int64)
        return accs

    def _get_update(total: int) -> Any:
        if total in update_cache:
            return update_cache[total]

        def _update(
            accs: Dict[str, jnp.ndarray],
            key_cols: Tuple[jnp.ndarray, ...],
            data: Dict[str, jnp.ndarray],
            masks: Dict[str, jnp.ndarray],
            row_valid: jnp.ndarray,
            bounds: Tuple[Tuple[int, int], ...],
        ) -> Dict[str, jnp.ndarray]:
            seg = _Space(list(bounds)).seg(list(key_cols))
            # padding rows get the out-of-range sentinel (dropped)
            seg = jnp.where(row_valid, seg, jnp.int32(total))
            out = dict(accs)
            out["_count"] = accs["_count"] + jax.ops.segment_sum(
                row_valid.astype(jnp.int64), seg, num_segments=total
            )
            for name, func, src in plans:
                v = data[src]
                m = masks.get(src)
                eff = row_valid if m is None else (m & row_valid)
                effc = jax.ops.segment_sum(
                    eff.astype(jnp.int64), seg, num_segments=total
                )
                if func in ("sum", "avg", "mean"):
                    adt = out[f"s:{name}"].dtype
                    out[f"s:{name}"] = accs[f"s:{name}"] + jax.ops.segment_sum(
                        jnp.where(eff, v, 0).astype(adt),
                        seg, num_segments=total,
                    )
                    out[f"c:{name}"] = accs[f"c:{name}"] + effc
                elif func == "count":
                    out[f"c:{name}"] = accs[f"c:{name}"] + effc
                elif func in ("min", "max"):
                    adt = out[f"m:{name}"].dtype
                    sentinel = _type_extreme(adt, func == "min")
                    filled = jnp.where(eff, v, sentinel).astype(adt)
                    red = (
                        jax.ops.segment_min
                        if func == "min"
                        else jax.ops.segment_max
                    )(filled, seg, num_segments=total)
                    out[f"m:{name}"] = (
                        jnp.minimum(accs[f"m:{name}"], red)
                        if func == "min"
                        else jnp.maximum(accs[f"m:{name}"], red)
                    )
                    out[f"c:{name}"] = accs[f"c:{name}"] + effc
            return out

        jitted = jax.jit(
            _update, static_argnames=("bounds",), donate_argnums=0
        )
        update_cache[total] = jitted
        return jitted

    def _rebase(
        old_space: _Space, new_space: _Space, accs: Dict[str, jnp.ndarray]
    ) -> Dict[str, jnp.ndarray]:
        """Scatter old accumulators into the widened segment space."""
        old_idx = np.arange(old_space.total)
        key_vals = old_space.decode(old_idx)
        new_seg = np.zeros(old_space.total, dtype=np.int64)
        for (lo, hi), kv in zip(new_space.bounds, key_vals):
            span = hi - lo + 1
            new_seg = new_seg * span + (kv - lo)
        fresh = _make_init(new_space.total)
        out: Dict[str, jnp.ndarray] = {}
        seg_dev = jnp.asarray(new_seg)
        for k, v in accs.items():
            out[k] = fresh[k].at[seg_dev].set(v.astype(fresh[k].dtype))
        return out

    src_cols = sorted(src_types)
    consumed: List[pd.DataFrame] = []
    it = iter(chunks)
    for pdf in it:
        consumed.append(pdf)
        if len(pdf) == 0:
            continue
        if pdf[keys].isna().any().any():
            raise StreamFallback("NULL group keys", consumed, it)
        cb = [(int(pdf[k].min()), int(pdf[k].max())) for k in keys]
        if space is None:
            cand = _Space(cb)
        elif not space.contains(cb):
            cand = space.union(cb)
        else:
            cand = space
        if cand.total > groupby._MAX_BINS:
            raise StreamFallback("key space too large", consumed, it)
        if space is None:
            space = cand
            acc = _make_init(space.total)
        elif cand is not space:
            acc = _rebase(space, cand, acc)  # type: ignore[arg-type]
            space = cand
        update = _get_update(space.total)
        n = len(pdf)
        bucket = _bucket_len(n)
        row_valid = jnp.asarray(
            np.arange(bucket) < n
        )

        def _padded(npv: np.ndarray, fill: Any = 0) -> jnp.ndarray:
            if len(npv) < bucket:
                out = np.full((bucket,), fill, dtype=npv.dtype)
                out[: len(npv)] = npv
                npv = out
            return jnp.asarray(npv)

        key_cols = tuple(_padded(pdf[k].to_numpy()) for k in keys)
        data: Dict[str, jnp.ndarray] = {}
        masks: Dict[str, jnp.ndarray] = {}
        for c in src_cols:
            npv = pdf[c].to_numpy()
            if npv.dtype.kind == "f":
                valid = ~np.isnan(npv)
                if not valid.all():
                    masks[c] = _padded(valid, False)
                    npv = np.nan_to_num(npv)
            data[c] = _padded(npv)
        acc = update(
            acc, key_cols, data, masks, row_valid, tuple(space.bounds)
        )
        # the consumed buffer only matters until streaming commits; once
        # the first chunk folded successfully we could still need fallback
        # (later null keys / growth), so keep it — it holds REFERENCES to
        # the caller's chunks, not copies
    if space is None:
        raise StreamFallback("empty stream", consumed, it)

    # finalize on host: occupied groups only; all-null groups -> NULL
    host = {k: np.asarray(v) for k, v in acc.items()}  # type: ignore
    occupied = np.nonzero(host["_count"] > 0)[0]
    key_vals = space.decode(occupied)
    cols: Dict[str, Any] = {}
    fields = []
    mesh = engine.mesh
    ndev = int(mesh.devices.size)
    n = len(occupied)
    pad_n = padded_len(n, ndev)
    sharding = row_sharding(mesh)

    def _dev(arr: np.ndarray, dtype: Any) -> Any:
        out = np.zeros((pad_n,), dtype=dtype)
        out[:n] = arr
        return jax.device_put(jnp.asarray(out), sharding)

    for k, kv in zip(keys, key_vals):
        f = schema[k]
        cols[k] = JaxColumn(
            f.type, _dev(kv, f.type.to_pandas_dtype()),
            stats=(int(kv.min()), int(kv.max())) if n else (0, 0),
        )
        fields.append(f)
    for name, func, src in plans:
        cnt = host[f"c:{name}"][occupied] if f"c:{name}" in host else None
        if func == "sum":
            vals = host[f"s:{name}"][occupied]
            tp = (
                pa.int64()
                if not pa.types.is_floating(src_types[src])
                else pa.float64()
            )
        elif func in ("avg", "mean"):
            vals = host[f"s:{name}"][occupied] / np.maximum(cnt, 1)
            tp = pa.float64()
        elif func == "count":
            vals = cnt
            tp = pa.int64()
        else:  # min / max
            vals = host[f"m:{name}"][occupied]
            tp = (
                pa.int64()
                if not pa.types.is_floating(src_types[src])
                else pa.float64()
            )
        col = JaxColumn(tp, _dev(vals, tp.to_pandas_dtype()))
        if func != "count" and cnt is not None:
            mask_np = cnt > 0  # all-null group -> NULL (SQL, groupby.py:447)
            if not mask_np.all():
                col.mask = _dev(mask_np, np.bool_)
        cols[name] = col
        fields.append(pa.field(name, tp))
    out_schema = Schema(fields)
    return JaxDataFrame(JaxBlocks(n, cols, mesh), out_schema)


def materialize_fallback(
    fb: StreamFallback, schema: Schema
) -> pd.DataFrame:
    """Concatenate the consumed chunks + the rest of the stream into one
    pandas frame for the bounded path."""
    rest = [pdf for pdf in fb.rest]
    parts = [p for p in fb.consumed + rest if len(p) > 0]
    if not parts:
        return pd.DataFrame({n: pd.Series(dtype=object) for n in schema.names})
    return pd.concat(parts, ignore_index=True)
