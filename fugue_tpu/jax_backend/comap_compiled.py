"""Compiled comap: cotransform as ONE whole-shard jitted program.

The reference's comap (fugue/execution/execution_engine.py:1066-1118)
deserializes each key group and applies the cotransformer in a per-group
host loop — SURVEY §3.5's perf cliff, and the one place this framework
still paid it (zipped.py keeps that loop for host cotransformers). For a
jax-annotated cotransformer (``Dict[str, jax.Array]`` per member) the
TPU-first shape is the same one the map/groupby/join paths already use:

- every member's zip keys are co-factorized into ONE shared segment space
  (the join machinery's N-way generalization of
  ``relational.shared_factorize``);
- the user function runs ONCE, compiled, over whole mesh-sharded columns,
  with per-member ``_segment_ids`` in the shared space — per-key work
  becomes ``jax.ops.segment_*`` reductions instead of a Python loop;
- zip presence rules (inner/left_outer/...) become a per-segment ``alive``
  mask computed in-program: rows of dead segments are masked out of
  ``_row_valid`` and re-pointed at the out-of-range sentinel, so segment
  ops drop them with zero host syncs.

The cotransformer ABI (mirrors the map ABI, JaxMapEngine._compiled_map):
the function receives one dict per zipped member, each carrying

- its columns as arrays (string columns as int32 dictionary codes plus a
  static ``_<name>_dict`` decode table), ``_<name>_mask`` validity masks;
- ``_row_valid`` bool[padded_m]: True = real row in a LIVE segment;
- ``_nrows``: traced int32 count of those rows;
- ``_segment_ids`` int32[padded_m] in the SHARED space (sentinel
  ``_num_segments`` for dead/padding rows);
- ``_num_segments``: the STATIC shared segment-space size (same value in
  every member dict; some segments may be empty or dead).

Output dict semantics (by array length):

- ``num_segments``: one row per segment — the frame keeps only LIVE
  segments via its validity mask, count stays lazy (zero host syncs);
- member 0's padded length: row-aligned with member 0 (inherits its
  masked validity);
- anything else: include ``_nrows`` (one sync, prefix layout).

The same function runs unmodified on host engines: ``JaxArraysParam``
presents each logical partition as a one-segment member dict.
"""

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from fugue_tpu.collections.partition import PartitionSpec
from fugue_tpu.dataframe import ArrayDataFrame, DataFrame
from fugue_tpu.jax_backend import groupby
from fugue_tpu.jax_backend.blocks import (
    JaxBlocks,
    JaxColumn,
    is_device_type,
    jit_row_sharded,
    padded_len,
)
from fugue_tpu.jax_backend.relational import (
    _common_dtype,
    _merged_stats,
    harmonize_string_keys,
)
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


class HostPathRequired(Exception):
    """The zipped shape can't run compiled; the caller falls back to the
    host group loop (zipped.device_comap). The message is the fallback
    reason recorded by the engine's counter."""


def _harmonize_n(cs: List[JaxColumn], mesh: Any) -> List[JaxColumn]:
    """Re-encode N dictionary columns into one shared dictionary by
    left-folding the pairwise harmonizer: each step only APPENDS to the
    union dictionary, so earlier members' codes stay valid and just adopt
    the final table."""
    out = [cs[0]]
    for c in cs[1:]:
        base, remapped, _ = harmonize_string_keys(out[0], c, mesh)
        out[0] = base
        out.append(remapped)
    union = out[0].dictionary
    hi = max(len(union) - 1, 0)
    return [
        JaxColumn(c.pa_type, c.data, c.mask, union, (0, hi)) for c in out
    ]


def _concat_key_blocks_n(
    blocks_list: List[JaxBlocks], keys: List[str]
) -> Tuple[JaxBlocks, List[int]]:
    """All members' key columns stacked along the row axis (member 0 rows
    first) — the N-way form of relational.concat_key_blocks. Padding rows
    stay invalid, so factorization sees them as non-rows. Arrays are
    built inside one row-sharded jitted program (multihost-safe — see
    relational.concat_key_blocks)."""
    mesh = blocks_list[0].mesh
    ps = [b.padded_nrows for b in blocks_list]
    n = len(blocks_list)
    per_key: Dict[str, List[JaxColumn]] = {}
    for k in keys:
        cs = [b.columns[k] for b in blocks_list]
        if cs[0].is_string:
            cs = _harmonize_n(cs, mesh)
        per_key[k] = cs
    dts = {}
    for k, cs in per_key.items():
        dt = cs[0].data.dtype
        for c in cs[1:]:
            dt = _common_dtype(dt, c.data.dtype)
        dts[k] = dt
    masked = tuple(
        sorted(
            k
            for k, cs in per_key.items()
            if any(c.mask is not None for c in cs)
        )
    )

    key_names = tuple(sorted(per_key))

    def _prog(
        datas: List[Dict[str, Any]],
        masks: List[Dict[str, Any]],
        rvs: Tuple[Optional[Any], ...],
        nrs: Tuple[Any, ...],
    ) -> Tuple[Dict[str, Any], Dict[str, Any], Any]:
        # iterate NAMES only: closing over per_key would pin the first
        # call's device arrays inside the process-wide jit cache
        data = {
            k: jnp.concatenate(
                [datas[m][k].astype(dts[k]) for m in range(n)]
            )
            for k in key_names
        }
        mask = {
            k: jnp.concatenate(
                [
                    masks[m].get(k, jnp.ones((ps[m],), dtype=bool))
                    for m in range(n)
                ]
            )
            for k in masked
        }
        valid = jnp.concatenate(
            [
                groupby.materialize_validity(rvs[m], ps[m], nrs[m])
                for m in range(n)
            ]
        )
        return data, mask, valid

    prog = jit_row_sharded(
        mesh,
        (
            "concat_keys_n", tuple(ps), tuple(sorted(per_key)), masked,
            tuple(str(dts[k]) for k in sorted(dts)),
        ),
        _prog,
    )
    from fugue_tpu.jax_backend.execution_engine import _nrows_arg

    data, mask, row_valid = prog(
        [{k: cs[m].data for k, cs in per_key.items()} for m in range(n)],
        [
            {
                k: cs[m].mask
                for k, cs in per_key.items()
                if cs[m].mask is not None
            }
            for m in range(n)
        ],
        tuple(b.row_valid for b in blocks_list),
        tuple(_nrows_arg(b) for b in blocks_list),
    )
    cols: Dict[str, JaxColumn] = {}
    for k, cs in per_key.items():
        stats = cs[0]
        for c in cs[1:]:
            stats = JaxColumn(
                stats.pa_type, stats.data, None, None,
                _merged_stats(stats, c),
            )
        cols[k] = JaxColumn(
            cs[0].pa_type, data[k], mask.get(k), cs[0].dictionary,
            stats.stats,
        )
    combined = JaxBlocks(None, cols, mesh, row_valid=row_valid)
    return combined, ps


def _alive_rule(how: str, present: List[Any]) -> Any:
    """Per-segment liveness under the zip's presence rule — the compiled
    form of the host loop's membership tests (zipped.device_comap)."""
    if how == "inner":
        alive = present[0]
        for p in present[1:]:
            alive = alive & p
        return alive
    if how == "left_outer":
        return present[0]
    if how == "right_outer":
        return present[-1]
    # full_outer: any member present
    alive = present[0]
    for p in present[1:]:
        alive = alive | p
    return alive


def compiled_comap(
    engine: Any,
    zdf: Any,  # JaxZippedDataFrame (import cycle)
    fn: Callable,
    output_schema: Any,
    partition_spec: PartitionSpec,
    on_init: Optional[Callable],
) -> DataFrame:
    """Run a jax-annotated cotransformer compiled over the shared segment
    space, or raise :class:`HostPathRequired` with the reason."""
    from fugue_tpu.jax_backend.execution_engine import (
        _StringDictUnavailable,
        _is_dict_key,
        _nrows_arg,
        _pad_to,
    )
    from fugue_tpu.jax_backend.dataframe import JaxDataFrame

    out_schema = Schema(output_schema)
    how = zdf.how
    keys = list(zdf.keys)
    if zdf.zip_spec.presort or partition_spec.presort:
        # presort orders rows WITHIN a group; whole-shard segment programs
        # have no per-group row order, so honoring it needs the host loop
        raise HostPathRequired("comap presort requires host grouping")
    if not all(is_device_type(f.type) for f in out_schema.fields):
        raise HostPathRequired("comap output schema has host-only types")
    for s in (f.schema for f in zdf.frames):
        if not all(is_device_type(f.type) for f in s.fields):
            raise HostPathRequired("comap member has host-only columns")
    jdfs: List[JaxDataFrame] = [engine.to_df(f) for f in zdf.frames]
    blocks_list = [j.blocks for j in jdfs]
    mesh = blocks_list[0].mesh
    if any(b.mesh is not mesh and b.mesh != mesh for b in blocks_list):
        raise HostPathRequired("comap members on different meshes")
    if not all(b.all_on_device for b in blocks_list):
        raise HostPathRequired("comap member has host-resident columns")

    n_members = len(blocks_list)
    ps = [b.padded_nrows for b in blocks_list]
    if how == "cross":
        S = 1
        zero_prog = jit_row_sharded(
            mesh,
            ("comap_zero_segs", tuple(ps)),
            lambda: tuple(
                jnp.zeros((p,), dtype=jnp.int32) for p in ps
            ),
        )
        segs: List[Any] = list(zero_prog())
    else:
        combined, _ = _concat_key_blocks_n(blocks_list, keys)
        fr = groupby.factorize_keys(combined, keys)
        S = max(fr.num_segments, 1)
        bounds = []
        off = 0
        for p in ps:
            bounds.append((off, off + p))
            off += p
        # row-sharded split (eager slices are not multihost-safe)
        split = jit_row_sharded(
            mesh,
            ("comap_seg_split", tuple(ps)),
            lambda s: tuple(
                jax.lax.slice(s, (a,), (b,)) for a, b in bounds
            ),
        )
        segs = list(split(fr.seg))

    if S == ps[0]:
        # output length is the ONLY signal separating per-segment from
        # member-0-row-aligned results; when the two coincide the compiled
        # path could keep/drop the wrong rows — the host loop is always
        # correct (the ABI runs per group there), so use it
        raise HostPathRequired(
            "ambiguous output length: num_segments == member 0 padding"
        )

    array_args: Dict[str, Any] = {}
    static_args: List[Dict[str, Any]] = []
    col_names: List[List[str]] = []
    for m, b in enumerate(blocks_list):
        st: Dict[str, Any] = {}
        names: List[str] = []
        for name, col in b.columns.items():
            array_args[f"m{m}:{name}"] = col.data
            names.append(name)
            if col.mask is not None:
                array_args[f"m{m}:_{name}_mask"] = col.mask
            if col.dictionary is not None:
                st[f"_{name}_dict"] = col.dictionary
        array_args[f"m{m}:__seg"] = segs[m]
        static_args.append(st)
        col_names.append(names)
    rvs = tuple(b.row_valid for b in blocks_list)
    nrows_args = tuple(_nrows_arg(b) for b in blocks_list)
    stash: Dict[str, Any] = {}

    def _wrapped(
        aa: Dict[str, Any],
        rv_in: Tuple[Optional[Any], ...],
        nrows_in: Tuple[Any, ...],
    ) -> Any:
        member_dicts: List[Dict[str, Any]] = []
        valids = [
            groupby.materialize_validity(rv_in[m], ps[m], nrows_in[m])
            for m in range(n_members)
        ]
        seg_eff = [
            jnp.where(valids[m], aa[f"m{m}:__seg"], S)
            for m in range(n_members)
        ]
        if how == "cross":
            # cross zip is always ONE group, even over empty members
            alive = jnp.ones((S,), dtype=bool)
        else:
            present = [
                jax.ops.segment_sum(
                    valids[m].astype(jnp.int32), seg_eff[m], num_segments=S
                )
                > 0
                for m in range(n_members)
            ]
            alive = _alive_rule(how, present)
        cnt_alive = jnp.sum(alive).astype(jnp.int32)
        row_alive: List[Any] = []
        for m in range(n_members):
            ra = valids[m] & alive[jnp.clip(aa[f"m{m}:__seg"], 0, S - 1)]
            row_alive.append(ra)
            d: Dict[str, Any] = {}
            for name in col_names[m]:
                d[name] = aa[f"m{m}:{name}"]
                mk = aa.get(f"m{m}:_{name}_mask")
                if mk is not None:
                    d[f"_{name}_mask"] = mk
            d.update(static_args[m])
            d["_row_valid"] = ra
            d["_nrows"] = jnp.sum(ra).astype(jnp.int32)
            d["_segment_ids"] = jnp.where(ra, aa[f"m{m}:__seg"], S)
            d["_num_segments"] = S
            member_dicts.append(d)
        out = fn(*member_dicts)
        assert_or_throw(
            isinstance(out, dict),
            ValueError("jax cotransformer must return a dict of arrays"),
        )
        for k in [k for k in out if _is_dict_key(k)]:
            stash[k] = np.asarray(out.pop(k), dtype=object)
        cnt0 = jnp.sum(row_alive[0]).astype(jnp.int32)
        return out, alive, cnt_alive, row_alive[0], cnt0

    cache_key = (
        "comap", id(fn), how, S, tuple(ps), tuple(sorted(array_args)),
        tuple(
            (m, k, id(v))
            for m, st in enumerate(static_args)
            for k, v in sorted(st.items())
        ),
    )
    cache = getattr(engine, "_comap_cache", None)
    if cache is None:
        cache = {}
        engine._comap_cache = cache
    if cache_key not in cache:
        # abstract trace now: it fills the stash (fn-returned decode
        # tables pop out at trace time) BEFORE the string-output check,
        # and is cached with the executable so id-reuse cannot alias
        shaped = {
            k: jax.ShapeDtypeStruct(v.shape, v.dtype)
            for k, v in array_args.items()
        }
        rv_s = tuple(
            None if r is None else jax.ShapeDtypeStruct(r.shape, r.dtype)
            for r in rvs
        )
        nr_s = tuple(
            jax.ShapeDtypeStruct((), jnp.int32) for _ in nrows_args
        )
        try:
            jax.eval_shape(_wrapped, shaped, rv_s, nr_s)
        except HostPathRequired:
            raise
        except Exception as ex:
            # a function valid in the host's one-segment mode but not
            # jit-traceable (float()/item()/data-dependent branching)
            # belongs on the host group loop, not a trace crash
            raise HostPathRequired(
                f"cotransformer not jit-traceable ({type(ex).__name__})"
            )
        cache[cache_key] = (jax.jit(_wrapped), stash)
    jitted, dict_stash = cache[cache_key]
    # every string output needs an fn-returned decode table: co-reduced
    # codes are never an input passthrough across the member boundary
    for f in out_schema.fields:
        if pa.types.is_string(f.type) or pa.types.is_large_string(f.type):
            if f"_{f.name}_dict" not in dict_stash:
                raise _StringDictUnavailable(f.name)
    # past the last bail-out point: on_init runs exactly once per comap
    # (the host-loop fallback has its own call — review finding)
    if on_init is not None:
        on_init(0, _empty_dfs(zdf))
    out, alive, cnt_alive, rv0, cnt0 = jitted(array_args, rvs, nrows_args)

    first = -1
    for f in out_schema.fields:
        assert_or_throw(
            f.name in out,
            ValueError(f"jax cotransformer output missing column {f.name}"),
        )
        n = int(out[f.name].shape[0])
        if first < 0:
            first = n
        assert_or_throw(
            n == first,
            ValueError("jax cotransformer output columns differ in length"),
        )

    ndev = int(mesh.devices.size)
    row_valid_out: Optional[Any] = None
    nrows_out: Optional[int] = None
    nrows_dev_out: Optional[Any] = None
    cols: Dict[str, JaxColumn] = {}
    to_pad: Dict[str, Any] = {}
    alive_key = "__alive"
    while alive_key in out or any(
        f.name == alive_key for f in out_schema.fields
    ):
        alive_key += "_"  # never collide with a user output column
    if "_nrows" in out:
        nrows_out = int(out["_nrows"])  # explicit count: one sync
        # an over-reporting cotransformer would make garbage padding rows
        # real; match the host group loop's validation instead of
        # exporting them (ADVICE r5 #2)
        assert_or_throw(
            0 <= nrows_out <= first,
            ValueError(
                f"jax cotransformer reported _nrows={nrows_out} outside "
                f"[0, {first}] (its output column length)"
            ),
        )
        target = max(padded_len(nrows_out, ndev), padded_len(first, ndev))
    elif first == S:
        # per-segment output: live segments are the rows, count lazy
        target = padded_len(S, ndev)
        to_pad[alive_key] = alive
        nrows_dev_out = cnt_alive
    elif first == ps[0]:
        # row-aligned with member 0 (validity has dead-segment drops)
        target = ps[0]
        row_valid_out = rv0
        nrows_dev_out = cnt0
    else:
        raise ValueError(
            "jax cotransformer output length must be _num_segments "
            f"({S}), member 0's padded length ({ps[0]}), or come with "
            f"an explicit '_nrows' (got {first})"
        )
    for f in out_schema.fields:
        to_pad[f.name] = out[f.name]
        mk = out.get(f"_{f.name}_mask")
        if mk is not None:
            to_pad[f"_{f.name}_mask"] = mk
    # pad through ONE row-sharded program (eager concatenate/device_put
    # of process-spanning arrays is not multihost-safe)
    sig = tuple(
        (k, str(v.dtype), int(v.shape[0])) for k, v in sorted(to_pad.items())
    )

    def _pad_prog(arrs: Dict[str, Any]) -> Dict[str, Any]:
        return {k: _pad_to(v, target) for k, v in arrs.items()}

    padded = jit_row_sharded(
        mesh, ("comap_pad", target, sig), _pad_prog
    )(to_pad)
    if alive_key in padded:
        row_valid_out = padded[alive_key]
    for f in out_schema.fields:
        mask = padded.get(f"_{f.name}_mask")
        dictionary = None
        if f"_{f.name}_dict" in dict_stash and (
            pa.types.is_string(f.type)
            or pa.types.is_large_string(f.type)
        ):
            dictionary = dict_stash[f"_{f.name}_dict"]
        cols[f.name] = JaxColumn(
            f.type, padded[f.name], mask, dictionary, None
        )
    return JaxDataFrame(
        JaxBlocks(
            nrows_out,
            cols,
            mesh,
            row_valid=row_valid_out,
            nrows_dev=nrows_dev_out,
        ),
        out_schema,
    )


def _empty_dfs(zdf: Any) -> Any:
    from fugue_tpu.jax_backend.zipped import _make_dfs

    return _make_dfs(
        zdf.names, [ArrayDataFrame([], f.schema) for f in zdf.frames]
    )
