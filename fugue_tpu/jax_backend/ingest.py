"""Streamed parquet -> device ingest.

The eager path (``blocks.from_arrow``) decodes the WHOLE arrow table on
host, then ships every column in one ``device_put`` — decode and device
staging are serial, which is why end-to-end parquet pipelines were the
weakest bench config. This module pipelines the two phases:

- the parquet file is read as a stream of record batches
  (``fugue.jax.io.batch_rows`` rows each) through the engine's virtual
  filesystem, so the same code path streams from local disk,
  ``memory://`` or object storage;
- each device-kind column fills a host staging buffer laid out in MESH
  SHARD ORDER; the moment the decode frontier crosses a shard boundary,
  that shard's slice ships to its device with an async ``device_put``
  (per-shard staging) while the NEXT batches keep decoding on host;
- after the last batch, the per-device shards are assembled into one
  global row-sharded array via ``make_array_from_single_device_arrays``
  — no concat program, no extra copy.

String columns dictionary-encode per batch and remap through a running
global dictionary, so codes stream like any numeric column. Integer
stats (min/max) and the monotonic-uniqueness proof are tracked across
batches, matching the eager ingest's metadata exactly.

The result stays LAZY (``JaxDataFrame.from_lazy``): the streamed upload
runs only when a device op first touches ``blocks``; host-only chains
read back through the normal host decode instead.

Fallbacks return None (caller uses the eager path): multi-process
meshes (SPMD ingest needs every host to hold the same array),
hive-partitioned directories, schema-expression column specs, and
non-parquet formats.
"""

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from fugue_tpu.jax_backend import blocks as B
from fugue_tpu.schema import Schema

def _row_groups_surviving(
    md: Any, pruning: List[Any]
) -> Optional[List[int]]:
    """Row groups a conjunctive ``[col, op, literal]`` predicate cannot
    refute via the group's column statistics. Pruning with any SUBSET of
    a conjunction is sound (a refuted conjunct falsifies every row of
    the group; null rows fail comparisons anyway), and statistics-less
    columns simply keep the group. None = statistics unreadable, keep
    everything."""
    try:
        keep: List[int] = []
        for g in range(md.num_row_groups):
            rg = md.row_group(g)
            stats: Dict[str, Any] = {}
            for j in range(rg.num_columns):
                cmeta = rg.column(j)
                st = cmeta.statistics
                if st is not None and st.has_min_max:
                    stats[cmeta.path_in_schema] = st
            alive = True
            for name, op, val in pruning:
                st = stats.get(name)
                if st is None or not isinstance(val, (int, float)):
                    continue
                mn, mx = st.min, st.max
                if not isinstance(mn, (int, float)) or not isinstance(
                    mx, (int, float)
                ):
                    continue
                if (
                    (op == ">" and not mx > val)
                    or (op == ">=" and not mx >= val)
                    or (op == "<" and not mn < val)
                    or (op == "<=" and not mn <= val)
                    or (op == "==" and not (mn <= val <= mx))
                ):
                    alive = False
                    break
            if alive:
                keep.append(g)
        return keep
    except Exception:  # pragma: no cover - stats drift: keep everything
        return None


def try_stream_load(
    engine: Any,
    path: Any,
    format_hint: Optional[str],
    columns: Any,
    batch_rows: int,
    pruning: Optional[List[Any]] = None,
    **kwargs: Any,
) -> Optional[Any]:
    """Build a lazily-streaming JaxDataFrame for a parquet load, or None
    when the input needs the eager path. ``pruning`` (optimizer-attached
    conjunctive ``[col, op, literal]`` triples) skips row groups whose
    parquet statistics refute the predicate — advisory: the downstream
    filter re-applies the full condition."""
    from fugue_tpu.utils.io import infer_format

    if jax.process_count() > 1 or batch_rows <= 0 or len(kwargs) > 0:
        return None
    if isinstance(columns, str):
        return None  # schema-expression select+cast: host owns it
    paths = [path] if isinstance(path, str) else list(path)
    try:
        if infer_format(paths[0], format_hint or None) != "parquet":
            return None
    except NotImplementedError:
        return None
    fs = engine.fs
    files: List[str] = []
    for p in paths:
        if fs.isdir(p):
            children = [
                fs.join(p, f)
                for f in fs.listdir(p)
                if not f.startswith(".") and not f.startswith("_")
            ]
            if len(children) == 0 or any(fs.isdir(c) for c in children):
                return None  # empty or hive-partitioned: eager dataset read
            files.extend(sorted(children))
        else:
            if not fs.exists(p):
                return None  # eager path owns the error message
            files.append(p)

    # metadata pass: row count + arrow schema (+ row-group pruning),
    # no data pages touched
    total_rows = 0
    est_bytes = 0
    arrow_schema: Optional[pa.Schema] = None
    group_meta: List[Any] = []  # (file, [rows/group], [bytes/group], keep)
    for f in files:
        with fs.open_input_stream(f) as fp:
            pf = pq.ParquetFile(fp)
            md = pf.metadata
            if arrow_schema is None:
                arrow_schema = pf.schema_arrow
            elif pf.schema_arrow != arrow_schema:
                # heterogeneous part files (missing/reordered columns,
                # dtype drift): the eager dataset read owns null
                # promotion/unification semantics
                return None
            g_rows = [md.row_group(i).num_rows for i in range(md.num_row_groups)]
            g_bytes = [
                md.row_group(i).total_byte_size
                for i in range(md.num_row_groups)
            ]
            keep = _row_groups_surviving(md, pruning) if pruning else None
            group_meta.append((f, g_rows, g_bytes, keep))
    row_groups: Optional[Dict[str, List[int]]] = None
    if pruning and all(k is not None for _, _, _, k in group_meta):
        pruned_rows = sum(
            sum(rows[g] for g in keep) for _, rows, _, keep in group_meta
        )
        if pruned_rows > 0:
            # an all-groups-refuted load would need empty-frame device
            # shapes the streamed path doesn't model: fall back to the
            # unpruned stream (the filter drops every row anyway)
            row_groups = {f: list(keep) for f, _, _, keep in group_meta}
    for f, g_rows, g_bytes, _ in group_meta:
        sel = row_groups[f] if row_groups is not None else range(len(g_rows))
        total_rows += sum(g_rows[g] for g in sel)
        est_bytes += sum(g_bytes[g] for g in sel)
    assert arrow_schema is not None
    base_schema = arrow_schema
    # provisional placement only (admit=False): the binding admission
    # decision happens in load_blocks at materialization time
    mesh = engine._place(est_bytes, admit=False)[0]
    nrows = total_rows
    from fugue_tpu.jax_backend.dataframe import JaxDataFrame

    def plan(cols_sel: Optional[List[str]]) -> Any:
        """Build the lazy frame for a column selection from the ALREADY
        captured metadata (files, schema, row count) — re-planning a
        narrower select never re-lists the directory or re-reads parquet
        footers."""
        sel = None if cols_sel is None else list(cols_sel)
        a_schema = (
            base_schema
            if sel is None
            else pa.schema([base_schema.field(c) for c in sel])
        )
        schema = Schema(a_schema)

        def load_blocks() -> B.JaxBlocks:
            # re-consult placement AND admission at MATERIALIZATION time:
            # under the fault layer's host-tier degrade override
            # (thread-local, see JaxExecutionEngine.degraded_to_host) the
            # streamed upload must re-place onto the host mesh even
            # though the plan captured the device tier, and the memory
            # governor's watermark/spill decision must see the ledger as
            # it is NOW, not as it was at plan time
            mesh_now, tier = engine._place(est_bytes)
            gate = engine._memory.gate(tier, est_bytes)
            gate.before()
            loaded = _stream_to_blocks(
                fs,
                files,
                schema,
                mesh_now,
                nrows,
                batch_rows,
                sel,
                row_groups,
                first_batch_hook=_first_batch_hook(engine),
            )
            gate.after(loaded)
            return loaded

        def load_table() -> pa.Table:
            tables = []
            for f in files:
                groups = None if row_groups is None else row_groups[f]
                if groups is not None and len(groups) == 0:
                    continue  # every row group refuted: nothing to read
                with fs.open_input_stream(f) as fp:
                    if groups is None:
                        tables.append(pq.read_table(fp, columns=sel))
                    else:
                        tables.append(
                            pq.ParquetFile(fp).read_row_groups(
                                groups, columns=sel
                            )
                        )
            return tables[0] if len(tables) == 1 else pa.concat_tables(tables)

        def load_head(n: int) -> pa.Table:
            """First n rows only: stop reading batches the moment they're
            covered (head/peek on a lazy frame must not decode the file)."""
            batches = []
            remaining = n
            for f in files:
                if remaining <= 0:
                    break
                groups = None if row_groups is None else row_groups[f]
                if groups is not None and len(groups) == 0:
                    continue
                with fs.open_input_stream(f) as fp:
                    pf = pq.ParquetFile(fp)
                    for b in pf.iter_batches(
                        batch_size=max(min(batch_rows, max(n, 1)), 1),
                        columns=sel,
                        row_groups=groups,
                    ):
                        batches.append(b.slice(0, remaining))
                        remaining -= min(b.num_rows, remaining)
                        if remaining <= 0:
                            break
            return pa.Table.from_batches(batches, schema=a_schema)

        return JaxDataFrame.from_lazy(
            load_blocks, load_table, mesh, schema, nrows, load_head, plan
        )

    return plan(list(columns) if columns is not None else None)


def _first_batch_hook(engine: Any) -> Optional[Callable[[], None]]:
    """Pipelined first-batch dispatch (``fugue.jax.io.pipeline``): the
    moment the FIRST record batches are decoded, kick a background warm
    of the persistent-executable cache for this engine's plan signature
    — deserializing the consumer's compiled program overlaps the decode
    and staging of the remaining batches, so the first dispatch after
    assembly is execute-only instead of compile/load-bound. A no-op
    when no cache dir is configured or the warm already ran."""
    from fugue_tpu.constants import (
        FUGUE_CONF_JAX_IO_PIPELINE,
        typed_conf_get,
    )

    try:
        if not typed_conf_get(engine.conf, FUGUE_CONF_JAX_IO_PIPELINE):
            return None
        if not getattr(engine, "_exec_enabled", False):
            return None
    except Exception:  # pragma: no cover - conf-less engine stub
        return None
    return lambda: engine.warm_executables(background=True)


class _ShardStager:
    """Per-column staging buffer that ships each mesh shard to its device
    the moment decode fills it (device_put is async — the transfer
    overlaps the decode of later batches)."""

    def __init__(self, pad_n: int, ndev: int, dtype: Any, fill: Any,
                 devices: List[Any]):
        self.buf = np.full((pad_n,), fill, dtype=dtype)
        self.shard = pad_n // ndev
        self.devices = devices
        self.sent = 0  # number of shards already shipped
        self.parts: List[Any] = []

    def fill_to(self, end: int) -> None:
        """Rows [0, end) are final; ship every fully-decoded shard."""
        while (self.sent + 1) * self.shard <= end:
            lo = self.sent * self.shard
            hi = lo + self.shard
            self.parts.append(
                jax.device_put(self.buf[lo:hi], self.devices[self.sent])
            )
            self.sent += 1

    def finish(self) -> List[Any]:
        self.fill_to(len(self.buf))
        return self.parts


def _stream_to_blocks(
    fs: Any,
    files: List[str],
    schema: Schema,
    mesh: Any,
    nrows: int,
    batch_rows: int,
    columns: Any,
    row_groups: Optional[Dict[str, List[int]]] = None,
    first_batch_hook: Optional[Callable[[], None]] = None,
) -> B.JaxBlocks:
    B.ensure_x64()
    ndev = int(mesh.devices.size)
    pad_n = B.padded_len(nrows, ndev)
    sharding = B.row_sharding(mesh)
    devices = list(mesh.devices.flat)
    cols = list(columns) if columns is not None else None

    device_fields = [f for f in schema.fields if B.is_device_type(f.type)]
    host_chunks: Dict[str, List[pa.Array]] = {
        f.name: [] for f in schema.fields if not B.is_device_type(f.type)
    }
    stagers: Dict[str, _ShardStager] = {}
    mask_stagers: Dict[str, _ShardStager] = {}
    # string state: running global dictionary per column
    dicts: Dict[str, Dict[Any, int]] = {}
    # int stats / uniqueness tracked across batches
    stats: Dict[str, Tuple[int, int]] = {}
    monotonic: Dict[str, Any] = {}

    for f in device_fields:
        tp = f.type
        if pa.types.is_string(tp) or pa.types.is_large_string(tp):
            np_dtype: Any = np.int32
            dicts[f.name] = {}
        else:
            np_dtype = B._np_dtype_for(tp)
        stagers[f.name] = _ShardStager(pad_n, ndev, np_dtype, 0, devices)
        if pa.types.is_integer(tp) and 0 < nrows <= B._UNIQUE_CHECK_MAX:
            # falsified by data / masks below; gated on size like the
            # eager path — never pay the O(n) host check just to discard it
            monotonic[f.name] = True

    offset = 0
    for fname in files:
        groups = None if row_groups is None else row_groups.get(fname)
        if groups is not None and len(groups) == 0:
            continue  # every row group statistically refuted
        with fs.open_input_stream(fname) as fp:
            pf = pq.ParquetFile(fp)
            for batch in pf.iter_batches(
                batch_size=batch_rows, columns=cols, row_groups=groups
            ):
                n = batch.num_rows
                if n == 0:
                    continue
                for f in schema.fields:
                    arr = batch.column(batch.schema.get_field_index(f.name))
                    if f.name in host_chunks:
                        host_chunks[f.name].append(arr)
                        continue
                    _decode_into(
                        f.name, f.type, arr, offset, n,
                        stagers, mask_stagers, dicts, stats, monotonic,
                        pad_n, ndev, devices,
                    )
                offset += n
                end = offset
                for st in stagers.values():
                    st.fill_to(end)
                for st in mask_stagers.values():
                    st.fill_to(end)
                if first_batch_hook is not None:
                    # leading batches are decoded/staged: overlap the
                    # executable warm with the remaining stream
                    hook, first_batch_hook = first_batch_hook, None
                    try:
                        hook()
                    except Exception:  # pragma: no cover - warm is
                        pass  # best-effort, never an ingest error

    out_cols: Dict[str, B.JaxColumn] = {}
    for f in schema.fields:
        tp = f.type
        if f.name in host_chunks:
            chunks = host_chunks[f.name]
            combined = (
                pa.chunked_array(chunks, type=tp).combine_chunks()
                if len(chunks) > 0
                else pa.chunked_array([pa.array([], type=tp)]).combine_chunks()
            )
            out_cols[f.name] = B.JaxColumn(tp, combined)
            continue
        data = _assemble(stagers[f.name], (pad_n,), sharding)
        mask = (
            _assemble(mask_stagers[f.name], (pad_n,), sharding)
            if f.name in mask_stagers
            else None
        )
        if f.name in dicts:
            dictionary = np.empty((len(dicts[f.name]),), dtype=object)
            for v, code in dicts[f.name].items():
                dictionary[code] = v
            out_cols[f.name] = B.JaxColumn(
                tp, data, mask, dictionary,
                stats=(0, max(len(dictionary) - 1, 0)),
            )
            continue
        # membership, not truthiness: the stored value is the column's
        # LAST element, which may legitimately be 0/False
        unique = bool(
            mask is None
            and pa.types.is_integer(tp)
            and 0 < nrows <= B._UNIQUE_CHECK_MAX
            and f.name in monotonic
        )
        out_cols[f.name] = B.JaxColumn(
            tp, data, mask, stats=stats.get(f.name), unique=unique
        )
    return B.JaxBlocks(nrows, out_cols, mesh)


def _chunk_view(blocks: B.JaxBlocks, lo: int, hi: int) -> B.JaxBlocks:
    """A zero-copy row-range view of prefix-layout blocks: device
    columns slice lazily on device (the fetch worker materializes them),
    host columns slice their arrow storage. Decode semantics are then
    EXACTLY ``blocks.to_arrow`` on the view — the pipelined save cannot
    diverge from the one-shot conversion."""
    cols: Dict[str, B.JaxColumn] = {}
    for name, col in blocks.columns.items():
        if col.on_device:
            cols[name] = B.JaxColumn(
                col.pa_type,
                col.data[lo:hi],
                None if col.mask is None else col.mask[lo:hi],
                col.dictionary,
                stats=col.stats,
            )
        else:
            cols[name] = B.JaxColumn(col.pa_type, col.data.slice(lo, hi - lo))
    return B.JaxBlocks(hi - lo, cols, blocks.mesh)


def try_pipelined_save(
    engine: Any,
    jdf: Any,
    path: str,
    format_hint: Optional[str],
    mode: str,
    partition_cols: Any,
    batch_rows: int,
    kwargs: Dict[str, Any],
) -> bool:
    """Overlap row-group writes with the tail of compute: the result
    frame is fetched to host CHUNK BY CHUNK on a prefetch worker (device
    slice + transfer of chunk k+1 runs while chunk k parquet-encodes and
    writes), so the save's host encode no longer waits for the full
    device readback. Returns False when the target/frame needs one of
    the general paths (non-parquet, dir targets, append concat, masked
    layout, pending/lazy frames) — the caller then uses the eager save.
    Row content and order are identical by construction (parity-tested):
    each chunk decodes through the same ``blocks.to_arrow``."""
    from fugue_tpu.constants import (
        FUGUE_CONF_JAX_IO_PIPELINE,
        typed_conf_get,
    )
    from fugue_tpu.utils.io import infer_format

    if batch_rows <= 0 or partition_cols:
        return False
    try:
        if not typed_conf_get(engine.conf, FUGUE_CONF_JAX_IO_PIPELINE):
            return False
    except Exception:  # pragma: no cover - conf-less engine stub
        return False
    try:
        if infer_format(path, format_hint or None) != "parquet":
            return False
    except NotImplementedError:
        return False
    if mode not in ("overwrite", "error"):
        return False  # append reads + concats the old artifact: host path
    if jdf._blocks is None:
        return False  # pending/lazy frame: no device tail to overlap
    blocks = jdf._blocks
    if blocks.row_valid is not None or not blocks.nrows_known:
        return False  # masked layout compacts in to_arrow: one-shot path
    nrows = blocks.nrows
    if nrows <= 0:
        return False
    fs = engine.fs
    if fs.exists(path):
        if mode == "error":
            raise FileExistsError(path)
        if fs.isdir(path):
            return False  # dir targets need the pre-delete semantics
    schema = jdf.schema
    # same contract as utils/io.save_df: batch_rows is OUR streaming
    # knob, never a pyarrow writer kwarg (here the chunking already
    # bounds row groups at batch_rows)
    kwargs = {k: v for k, v in kwargs.items() if k != "batch_rows"}
    spans = [
        (lo, min(lo + batch_rows, nrows))
        for lo in range(0, nrows, batch_rows)
    ]
    from concurrent.futures import ThreadPoolExecutor

    def fetch(span: Tuple[int, int]) -> pa.Table:
        lo, hi = span
        return B.to_arrow(_chunk_view(blocks, lo, hi), schema)

    with ThreadPoolExecutor(
        1, thread_name_prefix="fugue-save-fetch"
    ) as pool:

        def write_all(fp: Any) -> None:
            writer = None
            try:
                fut = pool.submit(fetch, spans[0])
                for i in range(len(spans)):
                    table = fut.result()
                    if i + 1 < len(spans):
                        fut = pool.submit(fetch, spans[i + 1])
                    if writer is None:
                        writer = pq.ParquetWriter(
                            fp, table.schema, **kwargs
                        )
                    writer.write_table(table)
            finally:
                if writer is not None:
                    writer.close()

        fs.write_file_atomic(path, write_all)
    return True


def _assemble(stager: _ShardStager, shape: Tuple[int, ...], sharding: Any) -> Any:
    parts = stager.finish()
    # order the shards by each device's row range in the sharding
    idx_map = sharding.addressable_devices_indices_map(shape)
    by_dev = {d: p for d, p in zip(stager.devices, parts)}
    ordered = [by_dev[d] for d in idx_map.keys()]
    return jax.make_array_from_single_device_arrays(shape, sharding, ordered)


def _decode_into(
    name: str,
    tp: pa.DataType,
    arr: pa.Array,
    offset: int,
    n: int,
    stagers: Dict[str, _ShardStager],
    mask_stagers: Dict[str, _ShardStager],
    dicts: Dict[str, Dict[Any, int]],
    stats: Dict[str, Tuple[int, int]],
    monotonic: Dict[str, Any],
    pad_n: int,
    ndev: int,
    devices: List[Any],
) -> None:
    """Decode one record-batch column into the staging buffers (the
    per-batch mirror of blocks.from_arrow's whole-table decode)."""
    buf = stagers[name].buf
    if pa.types.is_string(tp) or pa.types.is_large_string(tp):
        enc = arr.dictionary_encode()
        codes_np = enc.indices.to_numpy(zero_copy_only=False)
        import pandas as pd

        valid = ~pd.isna(codes_np)
        local_codes = np.where(valid, np.nan_to_num(codes_np, nan=0), 0).astype(
            np.int64
        )
        gdict = dicts[name]
        remap = np.empty((len(enc.dictionary),), dtype=np.int32)
        for i, v in enumerate(enc.dictionary.to_pylist()):
            code = gdict.get(v)
            if code is None:
                code = len(gdict)
                gdict[v] = code
            remap[i] = code
        buf[offset:offset + n] = (
            remap[local_codes] if len(remap) > 0 else 0
        )
        _mask_write(name, valid, offset, n, arr.null_count > 0,
                    mask_stagers, pad_n, ndev, devices, stagers)
        return
    np_dtype = B._np_dtype_for(tp)
    null_count = arr.null_count
    values = B.decode_device_values(arr, tp)
    if null_count > 0:
        import pyarrow.compute as pc

        valid = pc.is_valid(arr).to_numpy(zero_copy_only=False)
        if values.dtype.kind == "f" and not np.issubdtype(np_dtype, np.floating):
            values = np.nan_to_num(values)
        filled = np.where(valid, values, 0).astype(np_dtype)
        _mask_write(name, valid.astype(np.bool_), offset, n, True,
                    mask_stagers, pad_n, ndev, devices, stagers)
        monotonic.pop(name, None)  # masked ints don't claim uniqueness
    else:
        filled = np.ascontiguousarray(values, dtype=np_dtype)
        if name in mask_stagers:  # earlier batches had nulls
            _mask_write(name, np.ones((n,), dtype=np.bool_), offset, n, True,
                        mask_stagers, pad_n, ndev, devices, stagers)
    buf[offset:offset + n] = filled
    s = B._int_like_stats(filled, tp)
    if s is not None:
        prev = stats.get(name)
        stats[name] = s if prev is None else (
            min(prev[0], s[0]), max(prev[1], s[1])
        )
    if name in monotonic and filled.dtype.kind in "iu" and n > 0:
        prev_last = monotonic[name]
        ok = bool((filled[1:] > filled[:-1]).all()) if n > 1 else True
        if prev_last is not True and prev_last is not False:
            ok = ok and filled[0] > prev_last
        if not ok:
            monotonic.pop(name, None)
        else:
            monotonic[name] = filled[-1]


def _mask_write(
    name: str,
    valid: np.ndarray,
    offset: int,
    n: int,
    has_nulls: bool,
    mask_stagers: Dict[str, _ShardStager],
    pad_n: int,
    ndev: int,
    devices: List[Any],
    stagers: Dict[str, _ShardStager],
) -> None:
    """Write a batch's validity into the column's mask stager, creating
    it on first need. A mask that appears MID-STREAM (first nulls in a
    late batch) backfills earlier rows as valid — but any already-shipped
    shard can't gain a mask, so creation is only allowed while no shard
    has shipped without one; otherwise the earlier shards' all-valid
    mask is reconstructed here before the new batch writes."""
    if name not in mask_stagers and not has_nulls:
        return
    st = mask_stagers.get(name)
    if st is None:
        st = _ShardStager(pad_n, ndev, np.bool_, False, devices)
        st.buf[:offset] = True  # earlier batches were fully valid
        # ship the backfilled shards the data stager already shipped so
        # both stagers stay in lockstep
        st.fill_to(stagers[name].sent * stagers[name].shard)
        mask_stagers[name] = st
    st.buf[offset:offset + n] = valid
