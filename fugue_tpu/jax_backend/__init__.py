"""TPU-native backend package. 64-bit dtype support (required for long/
timestamp column fidelity) is enabled by :func:`blocks.ensure_x64` when an
engine, mesh, or ingest path is first used — NOT as an import side effect,
so importing this package never mutates global jax config for unrelated
code."""

from fugue_tpu.jax_backend.dataframe import JaxDataFrame
from fugue_tpu.jax_backend.execution_engine import (
    JaxExecutionEngine,
    JaxMapEngine,
    JaxSQLEngine,
)
