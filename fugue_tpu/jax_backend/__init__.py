import os

import jax as _jax

# Data fidelity requires 64-bit dtypes (long columns, timestamp microseconds):
# without x64, device_put silently truncates int64 -> int32. Opt out only if
# you know every column fits 32 bits (e.g. pure-float32 TPU pipelines).
if os.environ.get("FUGUE_TPU_DISABLE_X64", "").lower() not in ("1", "true"):
    _jax.config.update("jax_enable_x64", True)

from fugue_tpu.jax_backend.dataframe import JaxDataFrame
from fugue_tpu.jax_backend.execution_engine import (
    JaxExecutionEngine,
    JaxMapEngine,
    JaxSQLEngine,
)
