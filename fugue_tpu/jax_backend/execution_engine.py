"""JaxExecutionEngine: the flagship TPU-native backend (BASELINE north star).

Structure parity: a sibling of fugue_spark/fugue_dask engines (reference
fugue_spark/execution_engine.py:336) — but TPU-first in design:

- dataframes are mesh-sharded device blocks (see blocks.py)
- select/filter/assign/aggregate lower to jit-compiled masked jnp programs
  and sort+segment reductions (no shuffle: XLA inserts ICI collectives)
- the map primitive has a compiled path for jax-annotated transformers
  (``Dict[str, jax.Array] -> Dict[str, jax.Array]``, whole-shard vectorized —
  the TPU-idiomatic transformer contract) and a host fallback with exact
  reference semantics for everything else
- relational ops that don't vectorize well yet (joins, set ops) run on the
  host arrow path, then re-device: correctness everywhere, speed where it
  counts; deeper device lowerings land in later rounds
"""

from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pyarrow as pa

from fugue_tpu.collections.partition import PartitionCursor, PartitionSpec
from fugue_tpu.column.expressions import ColumnExpr, _NamedColumnExpr
from fugue_tpu.column.sql import SelectColumns
from fugue_tpu.constants import FUGUE_CONF_JAX_PARTITIONS
from fugue_tpu.dataframe import (
    ArrowDataFrame,
    DataFrame,
    LocalDataFrame,
)
from fugue_tpu.execution.execution_engine import (
    ExecutionEngine,
    MapEngine,
    SQLEngine,
)
from fugue_tpu.execution.native_execution_engine import (
    NativeExecutionEngine,
    PandasMapEngine,
    PandasSQLEngine,
)
from fugue_tpu.jax_backend import expr_eval, groupby
from fugue_tpu.jax_backend.blocks import (
    JaxBlocks,
    JaxColumn,
    from_arrow,
    gather_indices,
    make_mesh,
    padded_len,
    row_sharding,
)
from fugue_tpu.jax_backend.dataframe import JaxDataFrame
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


class JaxMapEngine(MapEngine):
    """Map primitive: compiled whole-shard path for jax transformers, host
    loop fallback otherwise (role parity: SparkMapEngine's pandas-udf vs RDD
    path selection, reference fugue_spark/execution_engine.py:112-133)."""

    @property
    def is_distributed(self) -> bool:
        return True

    def map_dataframe(
        self,
        df: DataFrame,
        map_func: Callable[[PartitionCursor, LocalDataFrame], LocalDataFrame],
        output_schema: Any,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable[[int, DataFrame], Any]] = None,
        map_func_format_hint: Optional[str] = None,
    ) -> DataFrame:
        engine: "JaxExecutionEngine" = self.execution_engine  # type: ignore
        output_schema = Schema(output_schema)
        if map_func_format_hint == "jax":
            raw = self._extract_jax_func(map_func)
            jdf = engine.to_df(df)
            if raw is not None and self._device_mappable(
                jdf, output_schema, partition_spec
            ):
                return self._compiled_map(
                    jdf, raw, output_schema, partition_spec, on_init
                )
        # host fallback: exact reference semantics via the pandas map engine;
        # fugue.jax.default.partitions sets the split count when the spec
        # doesn't name one
        default_parts = engine.conf.get(FUGUE_CONF_JAX_PARTITIONS, 0)
        if (
            default_parts > 0
            and partition_spec.num_partitions == "0"
            and len(partition_spec.partition_by) == 0
        ):
            partition_spec = PartitionSpec(partition_spec, num=default_parts)
        host = PandasMapEngine(engine)
        res = host.map_dataframe(
            df, map_func, output_schema, partition_spec, on_init,
            map_func_format_hint,
        )
        return engine.to_df(res)

    def _extract_jax_func(self, map_func: Callable) -> Optional[Callable]:
        """Reach the raw user function through the transformer runner."""
        runner = getattr(map_func, "__self__", None)
        tf = getattr(runner, "transformer", None)
        wrapper = getattr(tf, "wrapper", None)
        if wrapper is not None and wrapper.input_code.startswith("j"):
            return wrapper.func
        return None

    def _device_mappable(
        self, df: JaxDataFrame, output_schema: Schema, spec: PartitionSpec
    ) -> bool:
        ok_in = all(
            c.on_device and not c.is_string for c in df.blocks.columns.values()
        )
        from fugue_tpu.jax_backend.blocks import is_device_type

        ok_out = all(
            is_device_type(f.type) and not pa.types.is_string(f.type)
            for f in output_schema.fields
        )
        return ok_in and ok_out

    def _compiled_map(
        self,
        df: JaxDataFrame,
        fn: Callable,
        output_schema: Schema,
        spec: PartitionSpec,
        on_init: Optional[Callable],
    ) -> DataFrame:
        """Whole-shard vectorized execution: the function sees the full
        (padded, mesh-sharded) columns as a dict of jax arrays; XLA fuses and
        auto-partitions; groups never leave the device.

        Rows are padded to the mesh size: ``_row_valid`` marks real rows and
        ``_nrows`` gives the true count. Groups are NOT contiguous; with
        partition keys, ``_segment_ids``/``_num_segments`` are provided for
        ``jax.ops.segment_*`` reductions (the TPU answer to per-group python
        loops) — padding rows carry segment id ``_num_segments`` so segment
        ops with ``num_segments=_num_segments`` drop them automatically."""
        engine: "JaxExecutionEngine" = self.execution_engine  # type: ignore
        blocks = df.blocks
        if on_init is not None:
            on_init(0, df)
        arrs: Dict[str, Any] = {}
        keys = [k for k in spec.partition_by]
        num = -1
        if len(keys) > 0:
            seg, _, num = groupby.factorize_keys(blocks, keys)
            arrs["_raw_seg"] = seg
        for name, col in blocks.columns.items():
            arrs[name] = col.data
            if col.mask is not None:
                arrs[f"_{name}_mask"] = col.mask
        # ONE jitted dispatch: scalars are closed over (static under trace);
        # eager per-op dispatch would round-trip a tunneled TPU per op
        nrows = blocks.nrows
        pad_n = blocks.padded_nrows
        array_args = {k: v for k, v in arrs.items() if hasattr(v, "shape")}
        scalar_args = {k: v for k, v in arrs.items() if not hasattr(v, "shape")}

        def _wrapped(aa: Dict[str, Any]) -> Any:
            full = {**aa, **scalar_args}
            row_valid = jnp.arange(pad_n) < nrows
            full["_row_valid"] = row_valid
            full["_nrows"] = nrows
            if num >= 0:
                # padding rows -> out-of-range segment: dropped by segment ops
                full["_segment_ids"] = jnp.where(
                    row_valid, full.pop("_raw_seg"), num
                )
                full["_num_segments"] = num
            return fn(full)

        out = engine._jit_cached(
            ("map", id(fn), nrows, pad_n, num,
             tuple(sorted(scalar_args.items()))), _wrapped
        )(array_args)
        assert_or_throw(
            isinstance(out, dict),
            ValueError("jax transformer must return a dict of arrays"),
        )
        ndev = int(blocks.mesh.devices.size)
        sharding = row_sharding(blocks.mesh)
        raw: Dict[str, Any] = {}
        first = -1
        for f in output_schema.fields:
            assert_or_throw(
                f.name in out,
                ValueError(f"jax transformer output missing column {f.name}"),
            )
            data = jnp.asarray(out[f.name])
            if first < 0:
                first = int(data.shape[0])
            assert_or_throw(
                int(data.shape[0]) == first,
                ValueError("jax transformer output columns differ in length"),
            )
            raw[f.name] = data
        if "_nrows" in out:
            out_rows = int(out["_nrows"])
        elif first == blocks.padded_nrows:
            out_rows = blocks.nrows  # same shape -> row-aligned output
        else:
            raise ValueError(
                "jax transformer changed the row count "
                f"({blocks.padded_nrows} -> {first}) without returning "
                "'_nrows'; include '_nrows' in the output dict"
            )
        target = padded_len(first, ndev)
        cols: Dict[str, JaxColumn] = {}
        for f in output_schema.fields:
            data = _pad_to(raw[f.name], target)
            mask = out.get(f"_{f.name}_mask")
            cols[f.name] = JaxColumn(
                f.type,
                jax.device_put(data, sharding),
                None
                if mask is None
                else jax.device_put(_pad_to(jnp.asarray(mask), target), sharding),
            )
        return JaxDataFrame(
            JaxBlocks(out_rows, cols, blocks.mesh), output_schema
        )


class JaxSQLEngine(PandasSQLEngine):
    """SQL facet: parse with the built-in front end; GROUP BY plans route
    back through JaxExecutionEngine.select -> device segment reductions."""

    @property
    def is_distributed(self) -> bool:
        return True


class JaxExecutionEngine(ExecutionEngine):
    """ExecutionEngine over a jax device mesh (single controller).

    Config keys: ``fugue.jax.default.partitions`` (logical split count for
    host-fallback maps; default = mesh size)."""

    def __init__(self, conf: Any = None, mesh: Any = None):
        super().__init__(conf)
        self._mesh = mesh if mesh is not None else make_mesh()
        # host sibling used for fallback relational ops
        self._native = NativeExecutionEngine(conf)

    @property
    def mesh(self) -> Any:
        return self._mesh

    @property
    def is_distributed(self) -> bool:
        return True

    def create_default_map_engine(self) -> MapEngine:
        return JaxMapEngine(self)

    def create_default_sql_engine(self) -> SQLEngine:
        return JaxSQLEngine(self)

    def get_current_parallelism(self) -> int:
        return int(self._mesh.devices.size)

    def to_df(self, df: Any, schema: Any = None) -> DataFrame:
        if isinstance(df, JaxDataFrame):
            assert_or_throw(
                schema is None, ValueError("schema must be None for JaxDataFrame")
            )
            return df
        if isinstance(df, DataFrame):
            assert_or_throw(
                schema is None, ValueError("schema must be None for DataFrame")
            )
            res = JaxDataFrame.from_table(
                df.as_local_bounded().as_arrow(type_safe=True),
                self._mesh,
                df.schema,
            )
            if df.has_metadata:
                res.reset_metadata(df.metadata)
            return res
        from fugue_tpu.collections.yielded import Yielded

        if isinstance(df, Yielded):
            return self.load_yielded(df)  # type: ignore
        local = self._native.to_df(df, schema)
        return JaxDataFrame.from_table(
            local.as_arrow(type_safe=True), self._mesh, local.schema
        )

    # ---- device-lowered column algebra ----------------------------------
    def select(
        self,
        df: DataFrame,
        cols: SelectColumns,
        where: Optional[ColumnExpr] = None,
        having: Optional[ColumnExpr] = None,
    ) -> DataFrame:
        jdf = self.to_df(df)
        resolved = cols.replace_wildcard(jdf.schema).assert_all_with_names()
        if self._can_select_on_device(jdf, resolved, where, having):
            out_schema = resolved.infer_schema(jdf.schema)
            filtered = jdf if where is None else self.filter(jdf, where)
            if not resolved.has_agg:
                return self._device_project(filtered, resolved, out_schema)  # type: ignore
            res = self._device_groupby_select(
                filtered, resolved, out_schema, having  # type: ignore
            )
            if res is not None:
                return res
        # fallback gets the ORIGINAL frame + where (avoid double filtering)
        return self.to_df(
            self._native.select(jdf.as_local_bounded(), cols, where, having)
        )

    def filter(self, df: DataFrame, condition: ColumnExpr) -> DataFrame:
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        if expr_eval.can_eval_on_device(condition, jdf.blocks):
            masked_cols = expr_eval.blocks_to_masked(jdf.blocks)
            pad_n = jdf.blocks.padded_nrows
            value, mask = expr_eval.eval_expr(
                masked_cols, condition, pad_n
            )
            keep = value.astype(jnp.bool_)
            if mask is not None:
                keep = keep & mask
            keep = keep & groupby.row_validity(jdf.blocks)
            idx = jnp.nonzero(keep)[0]
            return JaxDataFrame(
                gather_indices(jdf.blocks, idx, jdf.schema), jdf.schema
            )
        return self.to_df(self._native.filter(jdf.as_local_bounded(), condition))

    def assign(self, df: DataFrame, columns: List[ColumnExpr]) -> DataFrame:
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        if all(
            expr_eval.can_eval_on_device(c, jdf.blocks) for c in columns
        ):
            masked_cols = expr_eval.blocks_to_masked(jdf.blocks)
            pad_n = jdf.blocks.padded_nrows
            schema = jdf.schema
            new_cols = dict(jdf.blocks.columns)
            sharding = row_sharding(jdf.blocks.mesh)
            for c in columns:
                name = c.output_name
                tp = c.infer_type(schema) or (
                    schema[name].type if name in schema else None
                )
                assert_or_throw(tp is not None, ValueError(f"can't infer {c}"))
                v, m = expr_eval.eval_expr(masked_cols, c, pad_n)
                new_cols[name] = JaxColumn(
                    tp,
                    jax.device_put(v, sharding),
                    None if m is None else jax.device_put(m, sharding),
                )
                if name in schema:
                    schema = schema.alter(Schema([(name, tp)]))
                else:
                    schema = schema + Schema([(name, tp)])
            return JaxDataFrame(
                JaxBlocks(jdf.blocks.nrows, new_cols, jdf.blocks.mesh), schema
            )
        return self.to_df(self._native.assign(jdf.as_local_bounded(), columns))

    def aggregate(
        self,
        df: DataFrame,
        partition_spec: Optional[PartitionSpec],
        agg_cols: List[ColumnExpr],
    ) -> DataFrame:
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        keys = partition_spec.partition_by if partition_spec is not None else []
        res = self._try_device_aggregate(jdf, keys, agg_cols)
        if res is not None:
            return res
        return self.to_df(
            self._native.aggregate(
                jdf.as_local_bounded(), partition_spec, agg_cols
            )
        )

    # ---- device implementations of engine primitives --------------------
    def repartition(self, df: DataFrame, partition_spec: PartitionSpec) -> DataFrame:
        return self.to_df(df)  # sharding is fixed by the mesh

    def broadcast(self, df: DataFrame) -> DataFrame:
        return self.to_df(df)

    def persist(self, df: DataFrame, lazy: bool = False, **kwargs: Any) -> DataFrame:
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        if not lazy:
            for col in jdf.blocks.columns.values():
                if col.on_device:
                    col.data.block_until_ready()
        return jdf

    def join(
        self,
        df1: DataFrame,
        df2: DataFrame,
        how: str,
        on: Optional[List[str]] = None,
    ) -> DataFrame:
        return self._host_op(
            lambda a, b: self._native.join(a, b, how=how, on=on), df1, df2
        )

    def union(self, df1: DataFrame, df2: DataFrame, distinct: bool = True) -> DataFrame:
        return self._host_op(
            lambda a, b: self._native.union(a, b, distinct=distinct), df1, df2
        )

    def subtract(
        self, df1: DataFrame, df2: DataFrame, distinct: bool = True
    ) -> DataFrame:
        return self._host_op(
            lambda a, b: self._native.subtract(a, b, distinct=distinct), df1, df2
        )

    def intersect(
        self, df1: DataFrame, df2: DataFrame, distinct: bool = True
    ) -> DataFrame:
        return self._host_op(
            lambda a, b: self._native.intersect(a, b, distinct=distinct), df1, df2
        )

    def distinct(self, df: DataFrame) -> DataFrame:
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        blocks = jdf.blocks
        if blocks.all_on_device and blocks.nrows > 0:
            seg, first_idx, num = groupby.factorize_keys(
                blocks, jdf.schema.names
            )
            return JaxDataFrame(
                gather_indices(blocks, first_idx, jdf.schema), jdf.schema
            )
        return self.to_df(self._native.distinct(jdf.as_local_bounded()))

    def dropna(
        self,
        df: DataFrame,
        how: str = "any",
        thresh: Optional[int] = None,
        subset: Optional[List[str]] = None,
    ) -> DataFrame:
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        blocks = jdf.blocks
        names = subset if subset is not None else jdf.schema.names
        if all(blocks.columns[n].on_device for n in names):
            pad_n = blocks.padded_nrows
            valid_count = jnp.zeros((pad_n,), dtype=jnp.int32)
            for n in names:
                col = blocks.columns[n]
                v = (
                    jnp.ones((pad_n,), dtype=jnp.int32)
                    if col.mask is None
                    else col.mask.astype(jnp.int32)
                )
                valid_count = valid_count + v
            if thresh is not None:
                keep = valid_count >= thresh
            elif how == "any":
                keep = valid_count == len(names)
            else:  # all
                keep = valid_count > 0
            keep = keep & groupby.row_validity(blocks)
            idx = jnp.nonzero(keep)[0]
            return JaxDataFrame(
                gather_indices(blocks, idx, jdf.schema), jdf.schema
            )
        return self.to_df(
            self._native.dropna(
                jdf.as_local_bounded(), how=how, thresh=thresh, subset=subset
            )
        )

    def fillna(
        self, df: DataFrame, value: Any, subset: Optional[List[str]] = None
    ) -> DataFrame:
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        return self.to_df(
            self._native.fillna(jdf.as_local_bounded(), value=value, subset=subset)
        )

    def sample(
        self,
        df: DataFrame,
        n: Optional[int] = None,
        frac: Optional[float] = None,
        replace: bool = False,
        seed: Optional[int] = None,
    ) -> DataFrame:
        assert_or_throw(
            (n is None) != (frac is None),
            ValueError("one and only one of n and frac must be set"),
        )
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        total = jdf.blocks.nrows
        rng = np.random.default_rng(seed)
        count = n if n is not None else int(round(total * frac))  # type: ignore
        count = min(count, total) if not replace else count
        idx = rng.choice(total, size=count, replace=replace)
        return JaxDataFrame(
            gather_indices(jdf.blocks, jnp.asarray(np.sort(idx)), jdf.schema),
            jdf.schema,
        )

    def take(
        self,
        df: DataFrame,
        n: int,
        presort: str,
        na_position: str = "last",
        partition_spec: Optional[PartitionSpec] = None,
    ) -> DataFrame:
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        return self.to_df(
            self._native.take(
                jdf.as_local_bounded(), n, presort, na_position, partition_spec
            )
        )

    def load_df(
        self,
        path: Union[str, List[str]],
        format_hint: Any = None,
        columns: Any = None,
        **kwargs: Any,
    ) -> DataFrame:
        local = self._native.load_df(path, format_hint, columns, **kwargs)
        return self.to_df(local)

    def save_df(
        self,
        df: DataFrame,
        path: str,
        format_hint: Any = None,
        mode: str = "overwrite",
        partition_spec: Optional[PartitionSpec] = None,
        force_single: bool = False,
        **kwargs: Any,
    ) -> None:
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        self._native.save_df(
            jdf.as_local_bounded(), path, format_hint, mode, partition_spec,
            force_single, **kwargs,
        )

    def convert_yield_dataframe(self, df: DataFrame, as_local: bool) -> DataFrame:
        return df.as_local() if as_local else df

    # ---- helpers ---------------------------------------------------------
    def _host_op(self, func: Callable, *dfs: DataFrame) -> DataFrame:
        locals_ = [self.to_df(d).as_local_bounded() for d in dfs]
        return self.to_df(func(*locals_))

    def _can_select_on_device(
        self,
        jdf: JaxDataFrame,
        cols: SelectColumns,
        where: Optional[ColumnExpr],
        having: Optional[ColumnExpr],
    ) -> bool:
        if having is not None:
            return False  # having rewrite handled on host for now
        if cols.is_distinct:
            return False
        blocks = jdf.blocks
        if where is not None and not expr_eval.can_eval_on_device(where, blocks):
            return False
        if not cols.has_agg:
            return all(
                expr_eval.can_eval_on_device(c, blocks) for c in cols.all_cols
            )
        # aggregation: group keys must be simple device columns (string keys
        # allowed: they group by dictionary code)
        for k in cols.group_keys:
            if not isinstance(k, _NamedColumnExpr) or k.as_type is not None:
                return False
            col = blocks.columns.get(k.name)
            if col is None or not col.on_device:
                return False
        from fugue_tpu.column.expressions import _FuncExpr

        for a in cols.agg_funcs:
            if not isinstance(a, _FuncExpr) or len(a.args) != 1:
                return False
            if a.arg_distinct:
                return False
            if a.func.lower() not in (
                "min", "max", "sum", "avg", "mean", "count", "first", "last"
            ):
                return False
            arg = a.args[0]
            if isinstance(arg, _NamedColumnExpr) and arg.wildcard:
                continue
            if not expr_eval.can_eval_on_device(arg, blocks):
                return False
        return True

    def _device_project(
        self, jdf: JaxDataFrame, cols: SelectColumns, out_schema: Schema
    ) -> DataFrame:
        masked_cols = expr_eval.blocks_to_masked(jdf.blocks)
        pad_n = jdf.blocks.padded_nrows
        sharding = row_sharding(jdf.blocks.mesh)
        new_cols: Dict[str, JaxColumn] = {}
        for c, f in zip(cols.all_cols, out_schema.fields):
            v, m = expr_eval.eval_expr(masked_cols, c, pad_n)
            new_cols[f.name] = JaxColumn(
                f.type,
                jax.device_put(v, sharding),
                None if m is None else jax.device_put(m, sharding),
            )
        return JaxDataFrame(
            JaxBlocks(jdf.blocks.nrows, new_cols, jdf.blocks.mesh), out_schema
        )

    def _device_groupby_select(
        self,
        jdf: JaxDataFrame,
        cols: SelectColumns,
        out_schema: Schema,
        having: Optional[ColumnExpr],
    ) -> Optional[DataFrame]:
        keys = [k.name for k in cols.group_keys]  # type: ignore
        aggs = [(c.output_name, c) for c in cols.agg_funcs]
        res = self._try_device_aggregate(
            jdf, keys, [c for _, c in aggs], out_schema=out_schema,
            col_order=[c.output_name for c in cols.all_cols],
        )
        return res

    def _jit_cached(self, key: Any, fn: Callable) -> Callable:
        """Per-engine jit cache: logical programs (aggregate plans, map fns,
        filters) are keyed by structure so repeated queries reuse the
        compiled executable."""
        cache = getattr(self, "_jit_cache", None)
        if cache is None:
            cache = {}
            self._jit_cache = cache
        if key not in cache:
            cache[key] = jax.jit(fn)
        return cache[key]

    def _try_device_aggregate(
        self,
        jdf: JaxDataFrame,
        keys: List[str],
        agg_cols: List[ColumnExpr],
        out_schema: Optional[Schema] = None,
        col_order: Optional[List[str]] = None,
    ) -> Optional[DataFrame]:
        from fugue_tpu.column.expressions import _FuncExpr

        blocks = jdf.blocks
        for k in keys:
            col = blocks.columns.get(k)
            if col is None or not col.on_device:
                return None
        plans = []
        for c in agg_cols:
            if not isinstance(c, _FuncExpr) or len(c.args) != 1 or c.arg_distinct:
                return None
            if c.func.lower() not in (
                "min", "max", "sum", "avg", "mean", "count", "first", "last"
            ):
                return None
            arg = c.args[0]
            if isinstance(arg, _NamedColumnExpr) and arg.wildcard:
                plans.append((c.output_name, "count", None, c))
                continue
            if not expr_eval.can_eval_on_device(arg, blocks):
                return None
            plans.append((c.output_name, c.func.lower(), arg, c))
        if blocks.nrows == 0:
            # empty input: host path handles schema/empty conventions
            return None
        pad_n = blocks.padded_nrows
        nrows = blocks.nrows
        masked_cols = expr_eval.blocks_to_masked(blocks)
        if len(keys) > 0:
            seg, first_idx, num = groupby.factorize_keys(blocks, keys)
        else:
            seg = jnp.zeros((pad_n,), dtype=jnp.int64)
            first_idx = jnp.zeros((1,), dtype=jnp.int64)
            num = 1
        # resolve output types up front (needed inside the traced program)
        typed_plans = []
        for name, func, arg, expr in plans:
            tp = expr.infer_type(jdf.schema)
            if tp is None:
                return None
            typed_plans.append((name, func, arg, tp))
        out_pad = padded_len(num, int(blocks.mesh.devices.size))
        sharding = row_sharding(blocks.mesh)

        # ONE fused program: every agg + key gather + padding, single dispatch
        def _agg_program(
            mcols: Dict[str, Any],
            key_data: Dict[str, Any],
            key_masks: Dict[str, Any],
            seg_: Any,
            first_idx_: Any,
        ) -> Dict[str, Any]:
            valid_ = jnp.arange(pad_n, dtype=jnp.int32) < nrows
            outs: Dict[str, Any] = {}
            for k in keys:
                kd = key_data[k][first_idx_]
                km = key_masks.get(k)
                outs[f"k:{k}"] = _pad_to(kd, out_pad)
                if km is not None:
                    outs[f"km:{k}"] = _pad_to(km[first_idx_], out_pad)
            for name, func, arg, tp in typed_plans:
                if func == "count" and arg is None:
                    values: Any = jnp.ones((pad_n,), dtype=jnp.int32)
                    mask: Any = None
                else:
                    values, mask = expr_eval.eval_expr(mcols, arg, pad_n)
                v, m = groupby._segment_agg_impl(
                    func, values, mask, seg_, num, valid_
                )
                outs[f"a:{name}"] = _pad_to(_cast_agg_result(v, tp), out_pad)
                if m is not None:
                    outs[f"am:{name}"] = _pad_to(m, out_pad)
            return outs

        prog_key = (
            "agg",
            tuple((n, f, None if a is None else a.__uuid__(), str(t))
                  for n, f, a, t in typed_plans),
            tuple(keys), num, out_pad, pad_n, nrows,
        )
        key_data = {k: blocks.columns[k].data for k in keys}
        key_masks = {
            k: blocks.columns[k].mask
            for k in keys
            if blocks.columns[k].mask is not None
        }
        outs = self._jit_cached(prog_key, _agg_program)(
            masked_cols, key_data, key_masks, seg, first_idx
        )
        out_cols: Dict[str, JaxColumn] = {}
        schema_fields = [jdf.schema[k] for k in keys]
        for k in keys:
            src_col = blocks.columns[k]
            out_cols[k] = JaxColumn(
                src_col.pa_type,
                jax.device_put(outs[f"k:{k}"], sharding),
                None if f"km:{k}" not in outs else jax.device_put(
                    outs[f"km:{k}"], sharding
                ),
                src_col.dictionary,
            )
        for name, func, arg, tp in typed_plans:
            out_cols[name] = JaxColumn(
                tp,
                jax.device_put(outs[f"a:{name}"], sharding),
                None if f"am:{name}" not in outs else jax.device_put(
                    outs[f"am:{name}"], sharding
                ),
            )
            schema_fields.append(pa.field(name, tp))
        schema = Schema(schema_fields)
        if col_order is not None:
            schema = schema.extract(col_order)
            out_cols = {n: out_cols[n] for n in col_order}
        return JaxDataFrame(
            JaxBlocks(num, out_cols, blocks.mesh), schema
        )


def _pad_to(v: jnp.ndarray, target: int) -> jnp.ndarray:
    n = int(v.shape[0])
    if n == target:
        return v
    return jnp.concatenate([v, jnp.zeros((target - n,), dtype=v.dtype)])


def _cast_agg_result(v: jnp.ndarray, tp: pa.DataType) -> jnp.ndarray:
    target = tp.to_pandas_dtype()
    try:
        return v.astype(target)
    except Exception:  # pragma: no cover
        return v
