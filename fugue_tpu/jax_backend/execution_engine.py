"""JaxExecutionEngine: the flagship TPU-native backend (BASELINE north star).

Structure parity: a sibling of fugue_spark/fugue_dask engines (reference
fugue_spark/execution_engine.py:336) — but TPU-first in design:

- dataframes are mesh-sharded device blocks (see blocks.py)
- select/filter/assign/aggregate lower to jit-compiled masked jnp programs
  and segment reductions (no shuffle: XLA inserts ICI collectives)
- the map primitive has a compiled path for jax-annotated transformers
  (``Dict[str, jax.Array] -> Dict[str, jax.Array]``, whole-shard vectorized —
  the TPU-idiomatic transformer contract) and a host fallback with exact
  reference semantics for everything else
- **latency design**: on a network-tunneled TPU every host synchronization
  costs ~70ms and every eager (non-jit) op ~85ms, so the steady-state
  pipeline is a chain of cached jitted dispatches with ZERO intermediate
  readbacks — filter/dropna/distinct flip validity masks instead of
  gathering, group-by uses host-known key stats for static bin counts, row
  counts stay lazy device scalars, and the single sync happens at the host
  boundary (arrow export)
- relational ops run on device: joins/set-ops via shared key factorization
  (relational.py), zip/comap without serialization (zipped.py), fillna/
  take/sample as validity flips; long-context streams fold through donated
  accumulators (streaming.py); host fallbacks are COUNTED (``fallbacks``)
  so a silent 100x slowdown cannot hide
"""

import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pyarrow as pa

from fugue_tpu.collections.partition import PartitionCursor, PartitionSpec
from fugue_tpu.column.expressions import ColumnExpr, _NamedColumnExpr
from fugue_tpu.column.functions import VARIANCE_FUNCS
from fugue_tpu.column.sql import SelectColumns
from fugue_tpu.constants import (
    FUGUE_CONF_JAX_PARTITIONS,
    KEYWORD_PARALLELISM,
    KEYWORD_ROWCOUNT,
    typed_conf_get,
)
from fugue_tpu.dataframe import (
    ArrowDataFrame,
    DataFrame,
    LocalDataFrame,
)
from fugue_tpu.exceptions import DeviceLostError
from fugue_tpu.lake import format as _lake_io
from fugue_tpu.obs.trace import start_span
from fugue_tpu.testing.locktrace import tracked_lock
from fugue_tpu.testing.retrace import active_retrace_sentinel
from fugue_tpu.execution.execution_engine import (
    ExecutionEngine,
    MapEngine,
    SQLEngine,
)
from fugue_tpu.execution.native_execution_engine import (
    NativeExecutionEngine,
    PandasMapEngine,
    PandasSQLEngine,
)
from fugue_tpu.jax_backend import expr_eval, groupby, relational
from fugue_tpu.jax_backend.blocks import (
    JaxBlocks,
    JaxColumn,
    blocks_schema,
    ensure_x64,
    evacuate_blocks,
    from_arrow,
    gather_indices,
    make_mesh,
    padded_len,
    row_sharding,
)
from fugue_tpu.jax_backend.dataframe import JaxDataFrame
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


class JaxMapEngine(MapEngine):
    """Map primitive: compiled whole-shard path for jax transformers, host
    loop fallback otherwise (role parity: SparkMapEngine's pandas-udf vs RDD
    path selection, reference fugue_spark/execution_engine.py:112-133)."""

    @property
    def is_distributed(self) -> bool:
        return True

    def map_dataframe(
        self,
        df: DataFrame,
        map_func: Callable[[PartitionCursor, LocalDataFrame], LocalDataFrame],
        output_schema: Any,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable[[int, DataFrame], Any]] = None,
        map_func_format_hint: Optional[str] = None,
    ) -> DataFrame:
        engine: "JaxExecutionEngine" = self.execution_engine  # type: ignore
        output_schema = Schema(output_schema)
        if map_func_format_hint == "jax":
            raw = self._extract_jax_func(map_func)
            runner = getattr(map_func, "__self__", None)
            if raw is not None and getattr(runner, "ignore_errors", ()):
                # per-partition error swallowing can't run whole-shard:
                # the host loop owns that semantics (same rule as comap);
                # counted ONCE here, so skip the not-mappable counter
                engine._count_fallback(
                    "map", "ignore_errors needs the host partition loop"
                )
            else:
                jdf = engine.to_df(df)
                if raw is not None and self._device_mappable(
                    jdf, output_schema, partition_spec
                ):
                    try:
                        return self._compiled_map(
                            jdf, raw, output_schema, partition_spec, on_init
                        )
                    except _StringDictUnavailable as e:
                        engine._count_fallback(
                            "map",
                            f"string output '{e}' has no dictionary source",
                        )
                else:
                    engine._count_fallback(
                        "map", "jax-hinted transformer not device-mappable"
                    )
        # host fallback: exact reference semantics via the pandas map engine;
        # fugue.jax.default.partitions sets the split count when the spec
        # doesn't name one
        default_parts = typed_conf_get(engine.conf, FUGUE_CONF_JAX_PARTITIONS)
        if (
            default_parts > 0
            and partition_spec.num_partitions == "0"
            and len(partition_spec.partition_by) == 0
        ):
            partition_spec = PartitionSpec(partition_spec, num=default_parts)
        host = PandasMapEngine(engine)
        res = host.map_dataframe(
            df, map_func, output_schema, partition_spec, on_init,
            map_func_format_hint,
        )
        return engine.to_df(res)

    def _extract_jax_func(self, map_func: Callable) -> Optional[Callable]:
        """Reach the raw user function through the transformer runner."""
        runner = getattr(map_func, "__self__", None)
        tf = getattr(runner, "transformer", None)
        wrapper = getattr(tf, "wrapper", None)
        if wrapper is not None and wrapper.input_code.startswith("j"):
            return wrapper.func
        return None

    def _device_mappable(
        self, df: JaxDataFrame, output_schema: Schema, spec: PartitionSpec
    ) -> bool:
        """String columns ARE device-mappable: they enter the compiled-map
        ABI as int32 dictionary codes plus a static host-side decode table
        (``_<name>_dict``) — see :meth:`_compiled_map`."""
        from fugue_tpu.jax_backend.blocks import is_device_type

        if df.is_pending:
            # decide from the schema — don't materialize the device copy
            # just to discover the frame belongs on the host path
            ok_in = all(is_device_type(f.type) for f in df.schema.fields)
        else:
            ok_in = all(c.on_device for c in df.blocks.columns.values())
        ok_out = all(is_device_type(f.type) for f in output_schema.fields)
        return ok_in and ok_out

    def _compiled_map(
        self,
        df: JaxDataFrame,
        fn: Callable,
        output_schema: Schema,
        spec: PartitionSpec,
        on_init: Optional[Callable],
    ) -> DataFrame:
        """Whole-shard vectorized execution: the function sees the full
        (padded, mesh-sharded) columns as a dict of jax arrays; XLA fuses and
        auto-partitions; groups never leave the device.

        Contract (the TPU transformer ABI):

        - ``_row_valid`` bool[padded]: True = real row (padding AND
          filtered-out rows are False).
        - ``_nrows``: the true row count as a TRACED int32 scalar (it is
          data-dependent under the lazy-count design; use it in arithmetic
          / ``jnp.where``, not as a static shape).
        - with partition keys: ``_segment_ids`` int32[padded] (invalid rows
          carry the out-of-range sentinel ``_num_segments``, so segment ops
          with ``num_segments=_num_segments`` drop them automatically) and
          ``_num_segments`` — a STATIC python int segment-id space size
          (some segments may be empty; fine for segment_* reductions).
        - string columns: ``arrs[name]`` is the int32 dictionary CODES
          array (traced) and ``arrs[f"_{name}_dict"]`` the host decode
          table (np object array, STATIC — use it in host python, not in
          traced math). A string OUTPUT column must either pass codes
          through unchanged (it inherits the input's dictionary) or return
          a remapped ``_<name>_dict`` alongside its codes — the host-side
          dict remap + device gather pattern, so e.g. ``value.map(m)``
          costs O(|dictionary|) host work and zero device work.
        - output columns the same padded length as the input are row-aligned
          with it; to change the row count, include ``_nrows`` in the output
          dict (forces one host sync).
        """
        engine: "JaxExecutionEngine" = self.execution_engine  # type: ignore
        blocks = df.blocks
        if on_init is not None:
            on_init(0, df)
        keys = list(spec.partition_by)
        num_segments = -1
        seg: Optional[Any] = None
        if len(keys) > 0:
            fr = groupby.factorize_keys(blocks, keys)
            seg = fr.seg
            num_segments = fr.num_segments
        array_args: Dict[str, Any] = {}
        static_args: Dict[str, Any] = {}
        for name, col in blocks.columns.items():
            array_args[name] = col.data
            if col.mask is not None:
                array_args[f"_{name}_mask"] = col.mask
            if col.dictionary is not None:
                static_args[f"_{name}_dict"] = col.dictionary
        if seg is not None:
            array_args["_segment_ids"] = seg
        pad_n = blocks.padded_nrows
        stash: Dict[str, Any] = {}  # fn-returned decode tables (trace time)

        def _wrapped(
            aa: Dict[str, Any], row_valid: Optional[Any], nrows_s: Any
        ) -> Any:
            full = dict(aa)
            row_valid = groupby.materialize_validity(row_valid, pad_n, nrows_s)
            full["_row_valid"] = row_valid
            full["_nrows"] = nrows_s
            if num_segments >= 0:
                full["_num_segments"] = num_segments
            full.update(static_args)
            out = fn(full)
            if isinstance(out, dict):
                # dictionaries are host values: strip them from the traced
                # outputs into the program's stash (filled at trace time,
                # cached with the executable)
                for k in [k for k in out if _is_dict_key(k)]:
                    stash[k] = np.asarray(out.pop(k), dtype=object)
            return out

        jitted, passthrough, dict_stash = engine._map_program(
            (
                "map", id(fn), pad_n, num_segments, tuple(sorted(array_args)),
                tuple((k, id(v)) for k, v in sorted(static_args.items())),
            ),
            _wrapped,
            array_args,
            blocks,
            list(blocks.columns),
            stash,
        )
        # every string output must have a decode table before we commit to
        # the compiled result: fn-returned (stash) or inherited (passthrough)
        for f in output_schema.fields:
            if pa.types.is_string(f.type) or pa.types.is_large_string(f.type):
                if f"_{f.name}_dict" in dict_stash:
                    continue
                src = blocks.columns.get(passthrough.get(f.name, ""))
                if src is None or src.dictionary is None:
                    raise _StringDictUnavailable(f.name)
        out = jitted(
            array_args, blocks.row_valid, _nrows_arg(blocks)
        )
        assert_or_throw(
            isinstance(out, dict),
            ValueError("jax transformer must return a dict of arrays"),
        )
        ndev = int(blocks.mesh.devices.size)
        sharding = row_sharding(blocks.mesh)
        first = -1
        for f in output_schema.fields:
            assert_or_throw(
                f.name in out,
                ValueError(f"jax transformer output missing column {f.name}"),
            )
            data = out[f.name]
            if first < 0:
                first = int(data.shape[0])
            assert_or_throw(
                int(data.shape[0]) == first,
                ValueError("jax transformer output columns differ in length"),
            )
        row_valid_out: Optional[Any] = None
        nrows_out: Optional[int] = None
        nrows_dev_out: Optional[Any] = None
        if "_nrows" in out:
            # explicit count -> prefix layout over [0, _nrows). One sync;
            # only row-count-changing transformers pay it.
            nrows_out = int(out["_nrows"])
            target = max(padded_len(nrows_out, ndev), padded_len(first, ndev))
        elif first == pad_n:
            # same shape -> row-aligned: inherit the input's membership
            # (including a pending lazy count) with zero syncs
            row_valid_out = blocks.row_valid
            nrows_out = blocks._nrows
            nrows_dev_out = blocks._nrows_dev
            target = pad_n
        else:
            raise ValueError(
                "jax transformer changed the row count "
                f"({pad_n} -> {first}) without returning "
                "'_nrows'; include '_nrows' in the output dict"
            )
        cols: Dict[str, JaxColumn] = {}
        for f in output_schema.fields:
            data = _pad_to(out[f.name], target)
            mask = out.get(f"_{f.name}_mask")
            src_name = passthrough.get(f.name)
            psrc = blocks.columns.get(src_name) if src_name else None
            if (
                mask is None
                and psrc is not None
                and psrc.mask is not None
                and int(psrc.mask.shape[0]) == target
            ):
                # passthrough values keep their nulls unless the fn
                # returned an explicit mask: masked slots hold fill
                # garbage, so treating them as valid is never intended
                mask = psrc.mask
            stats = dictionary = None
            if f"_{f.name}_dict" in dict_stash and (
                pa.types.is_string(f.type) or pa.types.is_large_string(f.type)
            ):
                # fn-provided decode table wins over the inherited one
                dictionary = dict_stash[f"_{f.name}_dict"]
                src_name = None
            if src_name is not None and src_name in blocks.columns:
                src = blocks.columns[src_name]
                # jaxpr identity alone is not enough: a dict-encoded string
                # column's codes passed through to a non-string output field
                # must NOT carry the dictionary (to_arrow would decode codes
                # into the wrong type); stats only describe integer-like
                # value bounds (advisor r2, low)
                if src.dictionary is not None and (
                    pa.types.is_string(f.type)
                    or pa.types.is_large_string(f.type)
                ):
                    dictionary = src.dictionary
                if (
                    pa.types.is_integer(f.type)
                    or pa.types.is_boolean(f.type)
                    or pa.types.is_timestamp(f.type)
                    or pa.types.is_date32(f.type)
                ):
                    # any type whose device representation is integer-like
                    # keeps its (min,max) bounds — matches ingest's stats
                    stats = src.stats
            cols[f.name] = JaxColumn(
                f.type,
                jax.device_put(data, sharding),
                None
                if mask is None
                else jax.device_put(_pad_to(mask, target), sharding),
                dictionary,
                stats,
            )
        return JaxDataFrame(
            JaxBlocks(
                nrows_out,
                cols,
                blocks.mesh,
                row_valid=row_valid_out,
                nrows_dev=nrows_dev_out,
            ),
            output_schema,
        )


class JaxSQLEngine(PandasSQLEngine):
    """SQL facet: parse with the built-in front end and lower the query
    through the algebra bridge into DEVICE relational primitives — joins,
    set ops, GROUP BY aggregates, ORDER BY/LIMIT and DISTINCT all execute
    as jitted device programs (the role Spark SQL / DuckDB play for the
    reference's engines, ``/root/reference/fugue_duckdb/
    execution_engine.py:238-483``). Query shapes outside the bridge
    (window functions, non-equi joins, LIKE, correlated subqueries) run
    on the host SELECT runner with exact SQL semantics — each such
    fallback is counted."""

    @property
    def is_distributed(self) -> bool:
        return True

    def select(self, dfs: Any, statement: Any) -> DataFrame:
        from fugue_tpu.sql_frontend.algebra_bridge import (
            inline_scalar_subqueries,
            translate_query,
        )
        from fugue_tpu.sql_frontend.parser import parse_select

        engine: "JaxExecutionEngine" = self.execution_engine  # type: ignore
        sql = statement.construct(dialect=self.dialect)
        plan = None
        try:
            schemas = {name: list(df.schema.names) for name, df in dfs.items()}
            q = parse_select(sql)
            # uncorrelated scalar subqueries run as device plans NOW and
            # inline as literals (one scalar readback each); whatever
            # stays un-inlined makes the outer translate give up below
            inline_scalar_subqueries(
                q, schemas, lambda p: self._exec_plan(p, dfs, {})
            )
            plan = translate_query(q, schemas)
        except Exception:
            plan = None
        if plan is not None:
            try:
                return self._exec_plan(plan, dfs, {})
            except Exception:
                # semantics disagreement -> host runner is the oracle
                engine._count_fallback("sql_select", "device plan raised")
                return super().select(dfs, statement)
        engine._count_fallback("sql_select", "non-lowerable query shape")
        return super().select(dfs, statement)

    def _exec_plan(
        self, plan: Any, dfs: Any, done: Dict[int, DataFrame]
    ) -> DataFrame:
        # ``done`` memoizes by node identity: the translator shares one
        # Plan per CTE, so a CTE referenced twice executes once
        if id(plan) in done:
            return done[id(plan)]
        res = self._exec_plan_uncached(plan, dfs, done)
        done[id(plan)] = res
        return res

    def _exec_plan_uncached(
        self, plan: Any, dfs: Any, done: Dict[int, DataFrame]
    ) -> DataFrame:
        from fugue_tpu.sql_frontend import algebra_bridge as ab

        engine: "JaxExecutionEngine" = self.execution_engine  # type: ignore
        if isinstance(plan, ab.ScanPlan):
            lowered = {n.lower(): n for n in dfs.keys()}
            return engine.to_df(dfs[lowered[plan.table]])
        if isinstance(plan, ab.JoinPlan):
            return engine.join(
                self._exec_plan(plan.left, dfs, done),
                self._exec_plan(plan.right, dfs, done),
                how=plan.how,
                on=list(plan.on),
            )
        if isinstance(plan, ab.NotInJoinPlan):
            l_df: JaxDataFrame = engine.to_df(
                self._exec_plan(plan.left, dfs, done)
            )  # type: ignore[assignment]
            r_df: JaxDataFrame = engine.to_df(
                self._exec_plan(plan.right, dfs, done)
            )  # type: ignore[assignment]
            l_df, r_df = engine._align_meshes(l_df, r_df)
            assert_or_throw(
                relational.device_joinable(
                    l_df.blocks, r_df.blocks, [plan.key], [plan.key]
                ),
                ValueError("NOT IN key not device-resident"),
            )
            out = relational.not_in_join(
                engine, l_df.blocks, r_df.blocks, [plan.key]
            )
            return JaxDataFrame(out, l_df.schema)
        if isinstance(plan, ab.SetPlan):
            left = self._exec_plan(plan.left, dfs, done)
            right = self._exec_plan(plan.right, dfs, done)
            if plan.op == "union":
                return engine.union(left, right, distinct=plan.distinct)
            if plan.op == "except":
                return engine.subtract(left, right, distinct=plan.distinct)
            return engine.intersect(left, right, distinct=plan.distinct)
        if isinstance(plan, ab.WindowPlan):
            src: JaxDataFrame = engine.to_df(
                self._exec_plan(plan.source, dfs, done)
            )  # type: ignore[assignment]
            if plan.where is not None:
                src = engine.to_df(engine.filter(src, plan.where))  # type: ignore
            res = relational.device_window(
                engine, src.blocks, src.schema, plan.items
            )
            assert_or_throw(
                res is not None,
                ValueError("window columns not device-resident"),
            )
            wblocks, wschema = res  # type: ignore[misc]
            return JaxDataFrame(wblocks, wschema)
        assert_or_throw(
            isinstance(plan, ab.SelectPlan), ValueError(f"bad plan {plan}")
        )
        src = self._exec_plan(plan.source, dfs, done)
        if plan.cols is not None:
            out = engine.select(
                src, plan.cols, where=plan.where, having=plan.having
            )
        else:
            out = src
        if plan.distinct:
            out = engine.distinct(out)
        if plan.order_by or plan.limit is not None or plan.offset is not None:
            out = self._exec_sort(out, plan)
        return out

    def _exec_sort(self, df: DataFrame, plan: Any) -> DataFrame:
        engine: "JaxExecutionEngine" = self.execution_engine  # type: ignore
        jdf: JaxDataFrame = engine.to_df(df)  # type: ignore
        sorts = [
            (name, asc, None if nulls is None else (nulls == "FIRST"))
            for name, asc, nulls in plan.order_by
        ]
        out = relational.device_sort(
            engine, jdf.blocks, jdf.schema, sorts,
            limit=plan.limit, offset=plan.offset,
        )
        assert_or_throw(
            out is not None,
            ValueError("sort column not device-resident"),
        )
        return JaxDataFrame(out, jdf.schema)

    # ---- table catalog: DEVICE-resident hot tables ----------------------
    # The shared process-wide catalog keeps the PERSISTED JaxDataFrame
    # itself instead of a host arrow copy: a table saved once stays on
    # its device tier across load_table calls (the serving daemon's hot
    # sessions never re-ingest), is the memory governor's spillable
    # population (persist marks it), and under pressure moves tiers IN
    # PLACE — the catalog reference follows automatically. Entries from
    # other engines (host arrow tuples) still load through the parent.
    def save_table(
        self,
        df: DataFrame,
        table: str,
        mode: str = "overwrite",
        partition_spec: Any = None,
        **kwargs: Any,
    ) -> None:
        from fugue_tpu.execution.native_execution_engine import (
            _TABLE_CATALOG,
        )

        assert_or_throw(
            mode in ("overwrite", "error"),
            NotImplementedError(f"save mode {mode}"),
        )
        if mode == "error":
            assert_or_throw(
                table not in _TABLE_CATALOG,
                ValueError(f"table {table} exists"),
            )
        engine: "JaxExecutionEngine" = self.execution_engine  # type: ignore
        _TABLE_CATALOG[table] = engine.persist(engine.to_df(df))

    def load_table(self, table: str, **kwargs: Any) -> DataFrame:
        from fugue_tpu.execution.native_execution_engine import (
            _TABLE_CATALOG,
        )

        entry = _TABLE_CATALOG.get(table)
        if isinstance(entry, DataFrame):
            return self.execution_engine.to_df(entry)
        return super().load_table(table, **kwargs)


class JaxExecutionEngine(ExecutionEngine):
    """ExecutionEngine over a jax device mesh (single controller).

    **Two-tier placement.** The engine owns TWO meshes: the accelerator
    mesh (``jax.devices()``) and a host mesh over the CPU backend
    (``jax.devices("cpu")``). Every op runs the same jitted programs on
    whichever mesh a frame's blocks live on — XLA compiles per backend.
    Ingest places a frame by a bandwidth-aware policy
    (``fugue.jax.placement``): on ``auto`` (default), frames smaller than
    ``fugue.jax.placement.min_device_bytes`` stay on the host tier, because
    for a one-shot query the host<->accelerator link transfer dominates any
    compute win — the same reason the reference routes small/IO-bound work
    to its NativeExecutionEngine rather than a cluster (reference
    fugue/execution/native_execution_engine.py:171-419 is the engine that
    wins those workloads). ``device`` / ``host`` pin the tier; engines
    constructed with an explicit ``mesh=`` are always pinned to it.

    Config keys: ``fugue.jax.default.partitions`` (logical split count for
    host-fallback maps; default = mesh size), ``fugue.jax.placement``,
    ``fugue.jax.placement.min_device_bytes``, ``fugue.optimize.cache.dir``
    (persistent compiled-executable cache; the deprecated
    ``fugue.jax.compile.cache`` key aliases it)."""

    def __init__(self, conf: Any = None, mesh: Any = None):
        super().__init__(conf)
        ensure_x64()
        # fugue.jax.devices carves the engine's mesh out of a slice of
        # the pod (how each fleet replica owns its own devices); an
        # explicitly passed mesh always wins
        self._mesh = (
            mesh
            if mesh is not None
            else make_mesh(_devices_from_conf(self.conf))
        )
        self._mesh_pinned = mesh is not None
        self._host_mesh = self._mesh if mesh is not None else _host_mesh_like(
            self._mesh
        )
        # host sibling used for fallback relational ops
        self._native = NativeExecutionEngine(conf)
        # host-fallback observability: op name -> count. Silent fallbacks
        # are silent 100x slowdowns (verdict r2); every host round-trip on
        # an op with a device path increments this and logs at info, so
        # tests/benches can assert a pipeline stayed on device. Since
        # ISSUE 8 the storage is a labeled counter family on the
        # engine's metrics registry — the `fallbacks` property is the
        # unchanged back-compat dict view over it.
        self._m_fallbacks = self.metrics.counter(
            "fugue_engine_fallbacks_total",
            "host fallbacks and memory-governance events per op "
            "(engine.fallbacks back-compat surface)",
            ["op"],
        )
        # jit program-cache hit/miss counters (surfaces on /v1/status
        # and /v1/metrics); children pre-resolved: the increment on the
        # dispatch hot path is one lock + add
        _m_compile = self.metrics.counter(
            "fugue_engine_compile_cache_total",
            "engine jit program-cache lookups by result",
            ["result"],
        )
        self._compile_hits = _m_compile.labels(result="hit")
        self._compile_misses = _m_compile.labels(result="miss")
        # process-wide plan cache (ISSUE 10): compiled program handles
        # are shared across engine instances under a signature folding
        # platform + mesh devices + fugue.jax.* conf, so a fresh engine
        # running a repeated query skips XLA compilation entirely.
        # These counters are EXACT lookup results (hit = a handle was
        # reused, miss = a new program was jitted), unlike the
        # per-dispatch compile_cache heuristic.
        from fugue_tpu.optimize.cache import (
            engine_plan_signature,
            get_plan_cache,
        )
        from fugue_tpu.optimize.exec_cache import (
            ExecutableDiskCache,
            resolve_cache_dir,
        )

        _m_plan = self.metrics.counter(
            "fugue_engine_plan_cache_total",
            "process-wide plan-cache lookups by tier and result "
            "(memory = shared jit handles, disk = persisted executables)",
            ["tier", "result"],
        )
        self._plan_hits = _m_plan.labels(tier="memory", result="hit")
        self._plan_misses = _m_plan.labels(tier="memory", result="miss")
        self._disk_hits = _m_plan.labels(tier="disk", result="hit")
        self._disk_misses = _m_plan.labels(tier="disk", result="miss")
        self._disk_evicts = _m_plan.labels(tier="disk", result="evict")
        self._disk_corrupt = _m_plan.labels(tier="disk", result="corrupt")
        self._plan_cache = get_plan_cache()
        self._plan_cache.configure(self.conf)
        self._plan_sig = engine_plan_signature(self)
        # DISK tier under the plan cache (ISSUE 11): AOT-serialized
        # executables under fugue.optimize.cache.dir (or its deprecated
        # fugue.jax.compile.cache alias) — a fresh PROCESS running a
        # cached program skips XLA entirely. Disabled (empty dir) = the
        # dispatch hot path never touches any of this.
        self._exec_cache = ExecutableDiskCache(
            self, resolve_cache_dir(self.conf, self.log)
        )
        self._exec_enabled = self._exec_cache.enabled
        self._m_deserialize = self.metrics.histogram(
            "fugue_engine_exec_cache_deserialize_seconds",
            "disk-tier executable deserialize latency",
        )
        _m_persist = self.metrics.counter(
            "fugue_engine_exec_cache_persist_total",
            "disk-tier executable persist outcomes",
            ["result"],
        )
        self._persist_ok = _m_persist.labels(result="ok")
        self._persist_err = _m_persist.labels(result="error")
        # retrace-sentinel violations per program (the runtime twin of
        # the FJX jit-hazard lint plane): only ever incremented while
        # the debug sentinel is armed — a standing zero in production
        self._m_retrace = self.metrics.counter(
            "fugue_engine_retrace_sentinel_total",
            "jitted programs that exceeded the armed retrace sentinel's "
            "trace budget (fugue.debug.retrace_sentinel.max_traces)",
            ["program"],
        )
        # compile/execute/disk-load wall clock split of every jitted
        # dispatch since construction — the daemon's time_to_first_query
        # phase report reads deltas of this
        self._dispatch_secs_lock = tracked_lock(
            "jax.engine.JaxExecutionEngine._dispatch_secs_lock"
        )
        self._dispatch_secs = {
            "compile": 0.0, "execute": 0.0, "disk_load": 0.0,
        }
        self.metrics.add_collector(self._collect_memory_gauges)
        # segment-reduction strategy observability, mirroring fallbacks:
        # strategy name -> times an aggregate program ran on it ("generic"
        # = the unpacked per-agg path). Benches report this per config so
        # the crossover selector's choices are visible, not guessed.
        self._strategy_counts: Dict[str, int] = {}
        # shuffle-repartition observability (the fugue_shuffle_ family):
        # per-op program runs split by overlap mode, transported-byte
        # estimates, and dispatch wall clock. EXPLAIN ANALYZE surfaces
        # deltas of the shuffle_counts view over these.
        self._m_shuffle_ops = self.metrics.counter(
            "fugue_shuffle_ops_total",
            "all-to-all shuffle-repartitioned programs per op, split by "
            "whether the collective/compute overlap split was traced",
            ["op", "overlap"],
        )
        self._m_shuffle_bytes = self.metrics.counter(
            "fugue_shuffle_bytes_total",
            "estimated bytes moved through padded all-to-all exchanges "
            "per op (static shape estimate, counts the full padded "
            "send buffers)",
            ["op"],
        )
        self._m_shuffle_secs = self.metrics.counter(
            "fugue_shuffle_seconds_total",
            "dispatch wall clock of shuffle-repartitioned programs per "
            "op (async dispatch time; the collective itself overlaps "
            "downstream compute)",
            ["op"],
        )
        # (fn, arg avals) of jitted programs as they run, for AOT
        # cost_analysis (see program_cost_analysis). Recording is DISARMED
        # until reset_program_log() so the per-dispatch aval capture never
        # taxes workloads that don't profile (review finding)
        self._program_log: Dict[Any, Tuple[Callable, Any]] = {}
        self._program_log_armed = False
        # per-THREAD placement override: the fault-tolerance layer re-runs
        # a device-OOM task under degraded_to_host() — thread-local so one
        # degraded task in a parallel runner doesn't demote its siblings
        self._tier_override = threading.local()
        # proactive device-memory governance: byte ledger + admission
        # control + LRU spill-to-host (memory.py). Disabled unless
        # fugue.jax.memory.budget_bytes/.budget_fraction is set.
        from fugue_tpu.jax_backend.memory import MemoryGovernor

        self._memory = MemoryGovernor(self)
        # device-fault recovery state (recover_from_device_loss): live
        # frame registry for the evacuation sweep (weak — the registry
        # must never pin a frame's device memory), the devices retired
        # so far (device OBJECTS: numeric ids collide across backends),
        # and how many degrade-rebuild cycles ran
        self._live_frames: Any = weakref.WeakSet()
        self._lost_devices: set = set()
        self._device_recoveries = 0
        # task-granular dispatch serialization for SHARED-engine use (the
        # serving daemon): XLA's CPU backend runs cross-device collectives
        # through a per-execution rendezvous on a shared thread pool — two
        # concurrently dispatched programs with collectives can starve
        # each other's participants and deadlock. Reentrant, so a serial
        # in-thread workflow nests freely.
        self._dispatch_lock = tracked_lock(
            "jax.engine.JaxExecutionEngine._dispatch_lock", reentrant=True
        )

    @property
    def fallbacks(self) -> Dict[str, int]:
        """Read-only snapshot of the host-fallback/governance counters
        since construction (or `reset_fallbacks`). Cited by the static
        analyzer's cost pass when predicting host behavior. A dict view
        over the registry's ``fugue_engine_fallbacks_total`` family."""
        return self._m_fallbacks.as_int_dict()

    def reset_fallbacks(self) -> None:
        self._m_fallbacks.clear()

    def _bump_fallback_counter(self, name: str, kind: str, detail: str) -> None:
        """The ONE increment path behind every fallback-surface counter:
        host fallbacks and memory-governance events share the same
        metric family, the same info log shape, and therefore the same
        assertions in tests/benches."""
        self._m_fallbacks.labels(op=name).inc()
        self.log.info(
            "fugue_tpu.jax %s: %s%s",
            kind,
            name,
            f" ({detail})" if detail else "",
        )

    @property
    def compile_cache_stats(self) -> Dict[str, int]:
        """Jit program-cache hit/miss counts since construction — the
        compile-amortization signal ``/v1/status`` reports."""
        return {
            "hits": int(self._compile_hits.value),
            "misses": int(self._compile_misses.value),
        }

    @property
    def plan_cache_stats(self) -> Dict[str, int]:
        """EXACT program-handle lookup counts against the process-wide
        plan cache (hit = compiled handle reused — from this engine or a
        previous same-signature one; miss = a new program was jitted).
        ``/v1/status`` reports these as ``compile_cache`` instead of the
        per-dispatch jax-cache-growth heuristic above."""
        return {
            "hits": int(self._plan_hits.value),
            "misses": int(self._plan_misses.value),
        }

    @property
    def exec_cache_stats(self) -> Dict[str, Any]:
        """The DISK tier's counters: per-shape executable loads by
        result (hit/miss/evict/corrupt) plus persist outcomes. All
        zeros when no cache dir is configured."""
        return {
            "enabled": self._exec_enabled,
            "dir": self._exec_cache.base_uri,
            "hits": int(self._disk_hits.value),
            "misses": int(self._disk_misses.value),
            "evictions": int(self._disk_evicts.value),
            "corrupt": int(self._disk_corrupt.value),
            "persisted": int(self._persist_ok.value),
            "persist_failures": int(self._persist_err.value),
        }

    @property
    def dispatch_time_stats(self) -> Dict[str, float]:
        """Wall-clock split of every jitted dispatch since construction:
        ``compile`` (dispatches that paid an XLA compile), ``execute``
        (compile-free dispatches) and ``disk_load`` (executable
        deserialize time) — the cold-start phase accounting the serving
        daemon's ``time_to_first_query`` report reads."""
        with self._dispatch_secs_lock:
            return dict(self._dispatch_secs)

    def _add_dispatch_secs(self, kind: str, secs: float) -> None:
        with self._dispatch_secs_lock:
            self._dispatch_secs[kind] += secs

    def _collect_memory_gauges(self) -> None:
        """Scrape-time collector: the PR 4 memory ledger's live/peak
        bytes per tier as labeled gauges (zeros when ungoverned)."""
        snap = self._memory.snapshot()
        live = self.metrics.gauge(
            "fugue_engine_memory_bytes",
            "live device-memory ledger bytes per tier",
            ["tier"],
        )
        peak = self.metrics.gauge(
            "fugue_engine_memory_peak_bytes",
            "peak device-memory ledger bytes per tier",
            ["tier"],
        )
        for tier, v in (snap.get("tiers") or {}).items():
            live.labels(tier=tier).set(v)
        for tier, v in (snap.get("peak") or {}).items():
            peak.labels(tier=tier).set(v)
        self.metrics.gauge(
            "fugue_engine_memory_budget_bytes",
            "configured device-memory budget (0 = ungoverned)",
        ).labels().set(snap.get("budget_bytes") or 0)

    def _count_fallback(self, op: str, why: str = "") -> None:
        self._bump_fallback_counter(op, "host fallback", why)

    def _count_memory_event(self, name: str, detail: str = "") -> None:
        """Memory-governance events ride the fallback counter surface
        (``mem_admit_host``/``mem_pressure``/``mem_spill``/
        ``mem_oom_feedback``) so tests and benches assert governance ran
        the same way they assert a pipeline stayed on device."""
        self._bump_fallback_counter(name, "memory governance", detail)

    @property
    def task_execution_lock(self) -> Any:
        """Engine-wide reentrant dispatch lock (see the base property):
        concurrent workflows sharing this engine serialize their DEVICE
        work at task granularity while their host-side phases overlap."""
        return self._dispatch_lock

    @property
    def memory_governor(self) -> Any:
        """The engine's :class:`~fugue_tpu.jax_backend.memory.MemoryGovernor`
        — the serving daemon claims session tables for their tenant and
        scopes job registrations through it."""
        return self._memory

    @property
    def memory_stats(self) -> Dict[str, Any]:
        """Snapshot of the device-memory governor: budget, per-tier live
        and peak ledger bytes, and event counters. ``enabled`` is False
        (and everything zero) unless ``fugue.jax.memory.budget_bytes`` or
        ``.budget_fraction`` is configured."""
        return self._memory.snapshot()

    def note_device_oom(self, ex: BaseException) -> None:
        """Called by the fault layer when a RESOURCE_EXHAUSTED slipped
        past admission: feed the measured allocation size back into the
        ledger (budget clamps to observed capacity, pressure is
        relieved) before the reactive host-tier degrade runs."""
        self._memory.note_oom(ex)

    @property
    def strategy_counts(self) -> Dict[str, int]:
        """Segment-reduction strategy counters since construction (or
        ``reset_strategy_counts``) — which kernel each aggregate ran on."""
        return dict(self._strategy_counts)

    def reset_strategy_counts(self) -> None:
        self._strategy_counts.clear()

    def _count_strategy(self, name: str) -> None:
        self._strategy_counts[name] = self._strategy_counts.get(name, 0) + 1

    @property
    def shuffle_counts(self) -> Dict[str, int]:
        """Shuffle-repartition counters since construction, flattened for
        the profiler's counter surface: per-op program runs (``aggregate``,
        ``join``), ``<op>_overlap`` runs that traced the double-buffered
        split, ``<op>_bytes`` transported-byte estimates, and ``<op>_ms``
        cumulative dispatch wall clock."""
        out: Dict[str, int] = {}
        for (op, overlap), v in self._m_shuffle_ops.as_int_dict().items():
            out[op] = out.get(op, 0) + v
            if overlap == "1":
                out[f"{op}_overlap"] = out.get(f"{op}_overlap", 0) + v
        for op, v in self._m_shuffle_bytes.as_int_dict().items():
            if v:
                out[f"{op}_bytes"] = v
        for op, secs in self._m_shuffle_secs.as_dict().items():
            ms = int(secs * 1000.0)
            if ms:
                out[f"{op}_ms"] = ms
        return out

    def _count_shuffle(
        self, op: str, nbytes: int, secs: float, overlap: bool
    ) -> None:
        self._m_shuffle_ops.labels(
            op=op, overlap="1" if overlap else "0"
        ).inc()
        self._m_shuffle_bytes.labels(op=op).inc(max(0, int(nbytes)))
        self._m_shuffle_secs.labels(op=op).inc(max(0.0, float(secs)))

    def reset_program_log(self) -> None:
        """Arm program recording and forget prior signatures (scopes
        program_cost_analysis to the ops run after this call)."""
        self._program_log.clear()
        self._program_log_armed = True

    def program_cost_analysis(self) -> Dict[str, Any]:
        """XLA ``cost_analysis()`` of the engine programs that ran since
        ``reset_program_log``: per-program flops and bytes accessed plus
        totals. This is the compiler's own traffic accounting — the number
        the roofline block divides by device time to report achieved GB/s
        against platform peak (ISSUE r6: a bytes-touched guess can only
        lower-bound it; XLA's real traffic proves or disproves fusion).
        Reading the analysis DISARMS recording again, so one profiling
        pass never taxes the rest of the engine's lifetime."""
        self._program_log_armed = False
        out: Dict[str, Any] = {"programs": {}, "flops": 0.0, "bytes_accessed": 0.0}
        for key, (fn, avals) in list(self._program_log.items()):
            try:
                ca = jax.jit(fn).lower(*avals).compile().cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if len(ca) > 0 else {}
                flops = float(ca.get("flops", 0.0))
                nbytes = float(ca.get("bytes accessed", 0.0))
            except Exception:  # pragma: no cover - backend w/o analysis
                continue
            name = str(key[0]) if isinstance(key, tuple) and key else str(key)
            slot = out["programs"].setdefault(
                name, {"flops": 0.0, "bytes_accessed": 0.0, "count": 0}
            )
            slot["flops"] += flops
            slot["bytes_accessed"] += nbytes
            slot["count"] += 1
            out["flops"] += flops
            out["bytes_accessed"] += nbytes
        return out

    @property
    def mesh(self) -> Any:
        return self._mesh

    @property
    def host_mesh(self) -> Any:
        """The host (CPU backend) tier's mesh; equals :attr:`mesh` when the
        engine is pinned or the default platform already is CPU."""
        return self._host_mesh

    @property
    def supports_host_degrade(self) -> bool:
        """A device-OOM task can re-run on the host tier when the engine
        actually has two tiers (not pinned to one mesh)."""
        return not self._mesh_pinned and self._host_mesh is not self._mesh

    def degraded_to_host(self) -> Any:
        """Force THIS thread's ingest placement onto the host (CPU) mesh —
        the graceful-degradation venue for a task whose device allocation
        failed (RESOURCE_EXHAUSTED). Thread-local: concurrent sibling
        tasks keep their accelerator placement."""
        from contextlib import contextmanager

        @contextmanager
        def _ctx():
            prev = getattr(self._tier_override, "mode", None)
            self._tier_override.mode = "host"
            try:
                yield self
            finally:
                self._tier_override.mode = prev

        return _ctx()

    # ---- device-fault recovery -------------------------------------------
    @property
    def lost_devices(self) -> Tuple[int, ...]:
        """Ids of the devices this engine has retired after hardware
        faults (empty on a healthy engine)."""
        return tuple(sorted(int(d.id) for d in self._lost_devices))

    @property
    def surviving_device_count(self) -> int:
        """Devices in the CURRENT mesh — after a degraded-mesh rebuild
        this is the survivor count the serve plane's ``degraded`` health
        state reports."""
        return int(self._mesh.devices.size)

    @property
    def is_degraded(self) -> bool:
        """True once any device has been lost and the engine rebuilt
        onto the survivors."""
        return len(self._lost_devices) > 0

    @property
    def device_recoveries(self) -> int:
        """Completed degrade-rebuild cycles (the `device_lost_recovery`
        counter's underlying engine state)."""
        return self._device_recoveries

    def recover_from_device_loss(self, ex: BaseException) -> bool:
        """Rebuild the engine onto the surviving devices after ``ex``
        (a DEVICE_LOST-classified XLA error; see workflow/fault.py).

        The dead devices are parsed out of the error text, or probed
        when the error names none. Then, under the dispatch lock: the
        memory governor retires the dead pools and marks stranded ledger
        entries lost, a fresh mesh is built from the survivors, the plan
        signature is recomputed (a 4-device program must never serve the
        3-device mesh), and every live frame is swept — evacuated via an
        arrow round trip when its shards are still readable, re-read
        from lineage (lazy load plan / checkpoint artifact / pinned
        lake version) when not, or marked lost so only its OWNING query
        fails (at the ``to_df`` touch point) while the process and every
        other session survive.

        Returns True when a rebuild happened — the retry executor then
        counts ``device_lost_recovery`` and re-runs the task under the
        normal backoff budget. False (recovery disabled, pinned mesh,
        no identifiable corpse, no survivors, or ``max_losses``
        exhausted) fails the task with the original error."""
        from fugue_tpu.constants import (
            FUGUE_CONF_JAX_RECOVERY_ENABLED,
            FUGUE_CONF_JAX_RECOVERY_MAX_LOSSES,
        )
        from fugue_tpu.jax_backend.distributed import (
            parse_lost_devices,
            probe_devices,
        )

        if not self.conf.get(FUGUE_CONF_JAX_RECOVERY_ENABLED, True):
            return False
        if self._mesh_pinned:
            # an explicitly passed mesh: the caller owns device topology
            return False
        mesh = self._mesh
        by_id = {int(d.id): d for d in mesh.devices.flat}
        named = [i for i in parse_lost_devices(str(ex)) if i in by_id]
        if named:
            lost = [by_id[i] for i in named]
        else:
            alive = set(probe_devices(mesh))
            lost = [d for d in mesh.devices.flat if d not in alive]
        if len(lost) == 0 or len(lost) >= len(by_id):
            return False
        max_losses = int(
            self.conf.get(FUGUE_CONF_JAX_RECOVERY_MAX_LOSSES, 0)
        )
        if max_losses > 0 and len(self._lost_devices) + len(lost) > max_losses:
            return False
        with self._dispatch_lock:
            survivors = [d for d in mesh.devices.flat if d not in lost]
            single_tier = self._host_mesh is mesh
            new_mesh = make_mesh(survivors)
            self._lost_devices.update(lost)
            self._memory.retire_devices([int(d.id) for d in lost])
            self._mesh = new_mesh
            if single_tier:
                self._host_mesh = new_mesh
            # plan/exec cache signatures fold the mesh devices
            from fugue_tpu.optimize.cache import engine_plan_signature

            self._plan_sig = engine_plan_signature(self)
            self._device_recoveries += 1
            outcomes = {"evacuated": 0, "rematerialized": 0, "lost": 0}
            for blocks in list(self._live_frames):
                res = self._recover_blocks(blocks)
                if res in outcomes:
                    outcomes[res] += 1
            self._count_memory_event(
                "device_lost_recovery",
                f"lost {sorted(int(d.id) for d in lost)} -> "
                f"{len(survivors)} survivors; "
                f"{outcomes['evacuated']} evacuated, "
                f"{outcomes['rematerialized']} rematerialized, "
                f"{outcomes['lost']} unrecoverable",
            )
        return True

    def _mesh_is_stale(self, mesh: Any) -> bool:
        if not self._lost_devices:
            return False
        return any(d in self._lost_devices for d in mesh.devices.flat)

    def _recover_blocks(self, blocks: Optional[JaxBlocks]) -> str:
        """One frame's recovery: ``"ok"`` (untouched by the loss),
        ``"evacuated"`` (arrow round trip onto the degraded mesh, same
        JaxBlocks identity so every holder heals), ``"rematerialized"``
        (re-read from lineage), or ``"lost"``."""
        from fugue_tpu.testing.faults import fault_point

        if blocks is None:
            return "ok"
        if not blocks.lost and not self._mesh_is_stale(blocks.mesh):
            return "ok"
        if not blocks.lost:
            try:
                # chaos hook: a plan here simulates shards that died
                # WITH the device, forcing the lineage/lost path
                fault_point("device.lost", "evacuate")
                evacuate_blocks(blocks, self._mesh)
                self._memory.register(blocks, "device")
                return "evacuated"
            except Exception as e:
                self.log.warning("block evacuation failed: %s", e)
        loader = blocks.lineage
        if loader is not None:
            try:
                from fugue_tpu.jax_backend.blocks import replace_blocks

                table = loader()
                fresh = from_arrow(
                    table.select(list(blocks.columns.keys())),
                    blocks_schema(blocks),
                    self._mesh,
                )
                replace_blocks(blocks, fresh)
                self._memory.register(blocks, "device")
                return "rematerialized"
            except Exception as e:
                self.log.warning(
                    "lineage rematerialization failed: %s", e
                )
        blocks.lost = True
        return "lost"

    def _track_frame(self, df: JaxDataFrame) -> None:
        """Recovery touch point for every frame entering an engine op:
        remember live blocks for the evacuation sweep, re-point
        pending/lazy placement stranded on a retired mesh, heal
        materialized frames on the spot, and fail unrecoverable ones
        with :class:`DeviceLostError` — the owning query dies; the
        process (and every other session) survives."""
        blocks = df._blocks
        if blocks is None:
            if self._lost_devices:
                if df._pending is not None and self._mesh_is_stale(
                    df._pending[1]
                ):
                    df._pending = (df._pending[0], self._mesh)
                if df._lazy is not None and self._mesh_is_stale(
                    df._lazy.mesh
                ):
                    df._lazy = df._lazy._replace(mesh=self._mesh)
            return
        if blocks.lost or self._mesh_is_stale(blocks.mesh):
            if self._recover_blocks(blocks) == "lost":
                raise DeviceLostError(
                    f"frame [{df.schema}] lost its device shards "
                    f"(devices {self.lost_devices}) and has no "
                    "recoverable lineage (lazy load plan, checkpoint "
                    "artifact, or pinned lake version)",
                    lost_devices=self.lost_devices,
                    frames=(str(df.schema),),
                )
        self._live_frames.add(blocks)

    def _attach_load_lineage(
        self, df: DataFrame, loader: Callable[[], pa.Table]
    ) -> None:
        """Storage-backed frames carry their reload plan as recovery
        lineage: if a device dies holding their shards, the rebuild
        re-reads the artifact onto the degraded mesh instead of failing
        the query (see :meth:`recover_from_device_loss`)."""
        if isinstance(df, JaxDataFrame):
            df._lineage_loader = loader

    def _ingest_mesh(self, nbytes: int) -> Any:
        """Placement policy: which mesh a newly ingested frame lands on."""
        return self._place(nbytes)[0]

    def _place(self, nbytes: int, admit: bool = True) -> Tuple[Any, str]:
        """Placement + admission: the bandwidth policy picks the default
        tier; the memory governor may redirect a device-tier newcomer
        whose footprint alone exceeds the budget onto the host tier. The
        returned tier label is LOGICAL — on single-mesh engines (CPU
        tests, pinned meshes) both tiers share one mesh but the ledger
        still governs them separately. ``admit=False`` is the
        provisional, side-effect-free form for plan-time placement
        (streamed loads re-place — and admit for real — at
        materialization)."""
        tier = self._default_tier(nbytes)
        if admit:
            tier = self._memory.admit(int(nbytes), tier)
        return (self._host_mesh if tier == "host" else self._mesh), tier

    def _default_tier(self, nbytes: int) -> str:
        if self._mesh_pinned:
            return "device"
        if getattr(self._tier_override, "mode", None) == "host":
            return "host"
        from fugue_tpu.constants import (
            FUGUE_CONF_JAX_MIN_DEVICE_BYTES,
            FUGUE_CONF_JAX_PLACEMENT,
        )

        mode = str(self.conf.get(FUGUE_CONF_JAX_PLACEMENT, "auto")).lower()
        if mode == "device":
            return "device"
        if mode == "host":
            return "host"
        if self._host_mesh is self._mesh:
            # single physical tier: the transfer-cost threshold is moot
            return "device"
        threshold = int(
            self.conf.get(FUGUE_CONF_JAX_MIN_DEVICE_BYTES, 256 * 1024 * 1024)
        )
        return "device" if nbytes >= threshold else "host"

    def _align_meshes(
        self, j1: JaxDataFrame, j2: JaxDataFrame
    ) -> Tuple[JaxDataFrame, JaxDataFrame]:
        """Binary relational ops need both frames on one mesh. Move the
        pending/smaller frame onto the other's mesh (one transfer of the
        smaller side — the same cost model as a broadcast join)."""
        m1, m2 = j1.mesh, j2.mesh
        if m1 is m2 or m1 == m2:
            return j1, j2

        def _weight(j: JaxDataFrame) -> int:
            # pending frames are cheapest to move (no device copy exists)
            if j.is_pending:
                return -1
            return j.blocks.padded_nrows

        if _weight(j1) <= _weight(j2):
            return self._move_to_mesh(j1, m2), j2
        return j1, self._move_to_mesh(j2, m1)

    def _move_to_mesh(self, j: JaxDataFrame, mesh: Any) -> JaxDataFrame:
        res = JaxDataFrame.from_table(
            j.as_arrow(), mesh, j.schema
        )
        if j.has_metadata:
            res.reset_metadata(j.metadata)
        return res

    @property
    def is_distributed(self) -> bool:
        return True

    def create_default_map_engine(self) -> MapEngine:
        return JaxMapEngine(self)

    def create_default_sql_engine(self) -> SQLEngine:
        return JaxSQLEngine(self)

    def get_current_parallelism(self) -> int:
        return int(self._mesh.devices.size)

    def to_df(self, df: Any, schema: Any = None) -> DataFrame:
        from fugue_tpu.jax_backend.zipped import JaxZippedDataFrame

        if isinstance(df, JaxZippedDataFrame):
            return df  # co-partition handle: consumed by comap only
        if isinstance(df, JaxDataFrame):
            assert_or_throw(
                schema is None, ValueError("schema must be None for JaxDataFrame")
            )
            # device-fault touch point: register live blocks for the
            # recovery sweep, heal frames stranded on a retired device,
            # and fail unrecoverable ones with DeviceLostError
            self._track_frame(df)
            # LRU recency for the governor's spill ordering: a frame
            # flowing through an engine op is in active use
            self._memory.touch(df._blocks)
            return df
        if isinstance(df, DataFrame):
            assert_or_throw(
                schema is None, ValueError("schema must be None for DataFrame")
            )
            table = df.as_local_bounded().as_arrow(type_safe=True)
            res = self._governed_frame(table, df.schema)
            if df.has_metadata:
                res.reset_metadata(df.metadata)
            return res
        from fugue_tpu.collections.yielded import Yielded

        if isinstance(df, Yielded):
            return self.load_yielded(df)  # type: ignore
        local = self._native.to_df(df, schema)
        table = local.as_arrow(type_safe=True)
        return self._governed_frame(table, local.schema)

    def _governed_frame(self, table: pa.Table, schema: Schema) -> JaxDataFrame:
        """Ingest entry point for host tables: placement + admission on
        the dtype-widened device-footprint estimate, with the governor's
        admission ticket attached so the lazy upload is gated (and its
        real byte count registered) at materialization time."""
        from fugue_tpu.jax_backend.memory import estimate_table_device_bytes

        est = estimate_table_device_bytes(table)
        mesh, tier = self._place(est)
        res = JaxDataFrame.from_table(table, mesh, schema)
        res._mem_gate = self._memory.gate(tier, est)
        return res

    # ---- device-lowered column algebra ----------------------------------
    def select(
        self,
        df: DataFrame,
        cols: SelectColumns,
        where: Optional[ColumnExpr] = None,
        having: Optional[ColumnExpr] = None,
    ) -> DataFrame:
        jdf = self.to_df(df)
        resolved = cols.replace_wildcard(jdf.schema).assert_all_with_names()
        if self._can_select_on_device(jdf, resolved, where, having):
            try:
                out_schema = resolved.infer_schema(jdf.schema)
                filtered = jdf if where is None else self.filter(jdf, where)
                if not resolved.has_agg:
                    return self._device_project(filtered, resolved, out_schema)  # type: ignore
                res = self._device_groupby_select(
                    filtered, resolved, out_schema, having  # type: ignore
                )
                if res is not None:
                    return res
            except NotImplementedError:
                # size-capped lowerings (dynamic-LIKE LUTs, composed
                # CONCAT dictionaries) surface at build time: host owns
                pass
        # fallback gets the ORIGINAL frame + where (avoid double filtering)
        self._count_fallback("select")
        return self.to_df(
            self._native.select(jdf.as_local_bounded(), cols, where, having)
        )

    def filter(self, df: DataFrame, condition: ColumnExpr) -> DataFrame:
        """Mask-only filter: ONE cached jitted dispatch flips row validity;
        columns (and their stats) are untouched, the row count becomes a
        lazy device scalar. No gather, no host sync."""
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        if expr_eval.can_eval_on_device(
            condition, jdf.blocks
        ) and not expr_eval.is_string_result(condition, jdf.blocks):
            blocks = jdf.blocks
            pad_n = blocks.padded_nrows
            dicts = expr_eval.dicts_of(blocks)

            def _filter_prog(
                mcols: Dict[str, Any], row_valid: Optional[Any], nrows_s: Any
            ) -> Tuple[Any, Any]:
                row_valid = groupby.materialize_validity(
                    row_valid, pad_n, nrows_s
                )
                value, mask = expr_eval.eval_expr(
                    mcols, condition, pad_n, dicts
                )
                keep = value.astype(jnp.bool_)
                if mask is not None:
                    keep = keep & mask
                keep = keep & row_valid
                return keep, jnp.sum(keep).astype(jnp.int32)

            try:
                keep, cnt = self._jit_cached(
                    ("filter", condition.__uuid__(), pad_n,
                     expr_eval.dict_fingerprint(blocks)), _filter_prog
                )(
                    expr_eval.blocks_to_masked(blocks),
                    blocks.row_valid,
                    _nrows_arg(blocks),
                )
                return JaxDataFrame(
                    JaxBlocks(
                        None,
                        dict(blocks.columns),
                        blocks.mesh,
                        row_valid=keep,
                        nrows_dev=cnt,
                    ),
                    jdf.schema,
                )
            except NotImplementedError:
                pass  # size-capped lowering surfaced at build time
        self._count_fallback("filter")
        return self.to_df(self._native.filter(jdf.as_local_bounded(), condition))

    def assign(self, df: DataFrame, columns: List[ColumnExpr]) -> DataFrame:
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        blocks = jdf.blocks
        if all(expr_eval.can_eval_on_device(c, blocks) for c in columns):
            pad_n = blocks.padded_nrows
            dicts = expr_eval.dicts_of(blocks)
            schema = jdf.schema
            plans: List[Tuple[str, Any, ColumnExpr]] = []
            for c in columns:
                name = c.output_name
                tp = c.infer_type(schema) or (
                    schema[name].type if name in schema else None
                )
                assert_or_throw(tp is not None, ValueError(f"can't infer {c}"))
                plans.append((name, tp, c))
                if name in schema:
                    schema = schema.alter(Schema([(name, tp)]))
                else:
                    schema = schema + Schema([(name, tp)])

            def _assign_prog(mcols: Dict[str, Any]) -> Dict[str, Any]:
                outs: Dict[str, Any] = {}
                for name, _tp, c in plans:
                    v, m = expr_eval.eval_expr(mcols, c, pad_n, dicts)
                    outs[f"v:{name}"] = v
                    if m is not None:
                        outs[f"m:{name}"] = m
                return outs

            outs = self._jit_cached(
                ("assign", tuple(c.__uuid__() for c in columns), pad_n,
                 expr_eval.dict_fingerprint(blocks)),
                _assign_prog,
            )(expr_eval.blocks_to_masked(blocks))
            sharding = row_sharding(blocks.mesh)
            new_cols = dict(blocks.columns)
            for name, tp, c in plans:
                # bare column references keep their dictionary/stats
                # (same rule as _device_project)
                src = (
                    blocks.columns.get(c.name)
                    if isinstance(c, _NamedColumnExpr) and c.as_type is None
                    else None
                )
                dict_r = (
                    src.dictionary
                    if src is not None
                    else (
                        expr_eval.result_dictionary(c, blocks)
                        if pa.types.is_string(tp)
                        else None
                    )
                )
                data = outs[f"v:{name}"]
                stats = src.stats if src is not None else None
                if dict_r is not None and src is None:
                    data, dict_r, stats = expr_eval.finalize_string_result(
                        data, dict_r
                    )
                new_cols[name] = JaxColumn(
                    tp,
                    jax.device_put(data, sharding),
                    None
                    if f"m:{name}" not in outs
                    else jax.device_put(outs[f"m:{name}"], sharding),
                    dict_r,
                    stats,
                )
            return JaxDataFrame(blocks_with_columns(blocks, new_cols), schema)
        self._count_fallback("assign")
        return self.to_df(self._native.assign(jdf.as_local_bounded(), columns))

    def aggregate(
        self,
        df: DataFrame,
        partition_spec: Optional[PartitionSpec],
        agg_cols: List[ColumnExpr],
    ) -> DataFrame:
        keys = partition_spec.partition_by if partition_spec is not None else []
        # long-context path: an ITERABLE input streams through donated
        # device accumulators chunk by chunk — the dataset never needs to
        # fit in device (or host) memory at once (see streaming.py)
        res = self._try_stream_aggregate(df, keys, agg_cols)
        if res is not None:
            return res
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        res = self._try_device_aggregate(jdf, keys, agg_cols)
        if res is not None:
            return res
        self._count_fallback("aggregate")
        return self.to_df(
            self._native.aggregate(
                jdf.as_local_bounded(), partition_spec, agg_cols
            )
        )

    # ---- device implementations of engine primitives --------------------
    def repartition(self, df: DataFrame, partition_spec: PartitionSpec) -> DataFrame:
        """Mesh sharding is fixed (rows are row-sharded over devices), so
        repartition is a device ROW REORDER: after it, contiguous even
        chunks of the frame equal the requested partitioning — hash groups
        equal-key rows together, rand applies a seeded permutation. The
        host map fallback's contiguous splitter then yields exactly the
        intended membership (reference fugue_spark/_utils/partition.py)."""
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        algo = partition_spec.algo
        if algo not in ("hash", "rand"):
            return jdf  # default/even/coarse: sharding already uniform
        blocks = jdf.blocks
        by = [
            k
            for k in (partition_spec.partition_by or jdf.schema.names)
            if k in blocks.columns
        ]
        if not all(blocks.columns[k].on_device for k in by):
            return jdf
        num = partition_spec.get_num_partitions(
            **{
                KEYWORD_ROWCOUNT: lambda: blocks.nrows,
                KEYWORD_PARALLELISM: lambda: self.get_current_parallelism(),
            }
        )
        if algo == "hash":
            if num <= 1:
                return jdf
            fr = groupby.factorize_keys(blocks, by)
            seg = np.asarray(fr.seg)
            part = seg % num
            valid = np.asarray(blocks.validity())
            # order by (partition id, key id) so equal keys stay contiguous
            # even when distinct keys collide into one partition; invalid
            # rows sort last via the out-of-range sentinels (int64 literals
            # would WRAP in the int32 seg dtype under NEP50)
            idx = np.lexsort(
                (np.where(valid, seg, seg.max() + 1),
                 np.where(valid, part, num))
            )[: int(valid.sum())]
        else:  # rand
            valid = np.asarray(blocks.validity())
            vidx = np.nonzero(valid)[0]
            idx = vidx[np.random.default_rng(42).permutation(len(vidx))]
        from fugue_tpu.jax_backend.blocks import gather_indices

        return JaxDataFrame(
            gather_indices(blocks, jnp.asarray(idx), jdf.schema), jdf.schema
        )

    def broadcast(self, df: DataFrame) -> DataFrame:
        return self.to_df(df)

    def persist(self, df: DataFrame, lazy: bool = False, **kwargs: Any) -> DataFrame:
        from fugue_tpu.jax_backend.zipped import JaxZippedDataFrame

        if isinstance(df, JaxZippedDataFrame):
            return df
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        if not lazy:
            from fugue_tpu.jax_backend.blocks import residency_arrays

            # EVERY device array: column data, column masks AND row_valid
            # — a mask left out of the fetch can lazily stage over the
            # relay after persist returns (ADVICE r5 #1)
            arrs = residency_arrays(jdf.blocks)
            with start_span("engine.device_sync", op="persist"):
                jax.block_until_ready(arrs)
            if arrs:
                # relayed TPU backends ack block_until_ready before the
                # bytes are resident; only a derived-value fetch proves
                # the staging finished (one full-pass reduction + one
                # scalar readback — persist means "materialize NOW")
                from fugue_tpu.jax_backend.blocks import on_mesh

                with on_mesh(jdf.blocks.mesh):
                    # sum in native dtype (bool masks sum to int32), cast
                    # the SCALAR: a full-array float32 cast would
                    # transiently copy the frame
                    float(
                        jnp.stack(
                            [
                                jnp.sum(a).astype(jnp.float32)
                                for a in arrs
                            ]
                        ).sum()
                    )
        if not jdf.is_pending:
            # persisted frames are the spillable population of the memory
            # governor's LRU (registered here if ingest didn't)
            self._memory.mark_persisted(jdf.blocks)
        return jdf

    def zip(
        self,
        dfs: Any,
        how: str = "inner",
        partition_spec: Optional[PartitionSpec] = None,
        temp_path: Optional[str] = None,
        to_file_threshold: int = -1,
    ) -> DataFrame:
        """Device zip: RECORDS the co-partition (member frames + keys) in a
        JaxZippedDataFrame instead of pickling partitions into blob rows
        and unioning them (the reference design this replaces:
        execution_engine.py:969-1360; SURVEY §3.5 'the piece to
        re-architect on TPU'). comap then assembles key groups from one
        columnar export per member — serialize_df is never called.
        Disable with ``fugue.jax.device_zip=false``."""
        from fugue_tpu.constants import FUGUE_CONF_JAX_DEVICE_ZIP
        from fugue_tpu.jax_backend.zipped import JaxZippedDataFrame

        hownorm = how.lower().replace(" ", "_")
        if self.conf.get(FUGUE_CONF_JAX_DEVICE_ZIP, True) and hownorm in (
            "inner", "left_outer", "right_outer", "full_outer", "cross",
        ):
            assert_or_throw(len(dfs) > 0, ValueError("can't zip 0 dataframes"))
            spec = partition_spec or PartitionSpec()
            keys: List[str] = list(spec.partition_by)
            # members stay AS THEY ARE (device or local): comap exports to
            # pandas anyway, so converting local frames to device here would
            # be an upload immediately followed by a download
            members: List[DataFrame] = list(dfs.values())
            if len(keys) == 0 and hownorm != "cross":
                keys = [
                    n
                    for n in members[0].schema.names
                    if all(n in m.schema for m in members)
                ]
                assert_or_throw(
                    len(keys) > 0, ValueError("no common keys to zip by")
                )
            if hownorm == "cross":
                assert_or_throw(
                    len(keys) == 0, ValueError("cross zip can't have keys")
                )
            names = list(dfs.keys()) if dfs.has_dict else [""] * len(dfs)
            key_schema = Schema([members[0].schema[k] for k in keys])
            return JaxZippedDataFrame(
                members, names, hownorm, keys, key_schema, spec
            )
        self._count_fallback("zip", "device zip disabled or exotic zip type")
        return super().zip(
            dfs, how=how, partition_spec=partition_spec,
            temp_path=temp_path, to_file_threshold=to_file_threshold,
        )

    def comap(
        self,
        df: DataFrame,
        map_func: Callable,
        output_schema: Any,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable] = None,
    ) -> DataFrame:
        from fugue_tpu.jax_backend.comap_compiled import (
            HostPathRequired,
            compiled_comap,
        )
        from fugue_tpu.jax_backend.zipped import (
            JaxZippedDataFrame,
            device_comap,
        )

        if isinstance(df, JaxZippedDataFrame):
            raw = self._extract_cotransform_jax_func(map_func, len(df.frames))
            if raw is not None:
                runner = getattr(map_func, "__self__", None)
                if getattr(runner, "ignore_errors", ()):
                    # per-group error swallowing needs the host group loop
                    self._count_fallback(
                        "comap", "ignore_errors needs the host group loop"
                    )
                else:
                    try:
                        return compiled_comap(
                            self, df, raw, output_schema, partition_spec,
                            on_init,
                        )
                    except HostPathRequired as e:
                        self._count_fallback("comap", str(e))
                    except _StringDictUnavailable as e:
                        self._count_fallback(
                            "comap",
                            f"string output '{e}' has no decode table",
                        )
            return device_comap(
                self, df, map_func, output_schema, partition_spec, on_init
            )
        return super().comap(
            df, map_func, output_schema, partition_spec, on_init
        )

    def _extract_cotransform_jax_func(
        self, map_func: Callable, n_members: int
    ) -> Optional[Callable]:
        """The raw user function behind a jax-annotated cotransformer: one
        ``Dict[str, jax.Array]`` parameter per zipped member, dict output."""
        runner = getattr(map_func, "__self__", None)
        tf = getattr(runner, "transformer", None)
        wrapper = getattr(tf, "wrapper", None)
        if (
            wrapper is not None
            and wrapper.input_code == "j" * n_members
            and wrapper.output_code == "j"
        ):
            return wrapper.func
        return None

    def join(
        self,
        df1: DataFrame,
        df2: DataFrame,
        how: str,
        on: Optional[List[str]] = None,
    ) -> DataFrame:
        """Device join via shared key factorization (see relational.py):
        semi/anti flip validity masks (zero syncs); inner/left/right/full/
        cross enumerate matches on device with ONE host sync for the output
        row count. Null keys never match (SQL). Falls back to the host
        pandas path only for host-resident (nested/binary) columns."""
        from fugue_tpu.dataframe.utils import get_join_schemas

        j1: JaxDataFrame = self.to_df(df1)  # type: ignore
        j2: JaxDataFrame = self.to_df(df2)  # type: ignore
        j1, j2 = self._align_meshes(j1, j2)
        hownorm = how.lower().replace("_", "").replace(" ", "")
        key_schema, output_schema = get_join_schemas(j1, j2, hownorm, on)
        keys = list(key_schema.names)
        b1, b2 = j1.blocks, j2.blocks
        if relational.device_joinable(
            b1, b2, j1.schema.names, j2.schema.names
        ):
            if hownorm in ("semi", "leftsemi", "anti", "leftanti"):
                out = relational.semi_anti_join(
                    self, b1, b2, keys, anti=hownorm in ("anti", "leftanti")
                )
                return JaxDataFrame(out, output_schema)
            if hownorm in ("inner", "cross", "leftouter", "fullouter"):
                out = relational.expand_join(
                    self, b1, b2, keys, hownorm, j1.schema, j2.schema,
                    output_schema,
                )
                return JaxDataFrame(out, output_schema)
            if hownorm == "rightouter":
                # left join with sides swapped, columns reordered
                _, swapped_schema = get_join_schemas(
                    j2, j1, "leftouter", keys
                )
                out = relational.expand_join(
                    self, b2, b1, keys, "leftouter", j2.schema, j1.schema,
                    swapped_schema,
                )
                cols = {
                    n: out.columns[n] for n in output_schema.names
                }
                return JaxDataFrame(
                    JaxBlocks(
                        out._nrows, cols, out.mesh,
                        row_valid=out.row_valid, nrows_dev=out._nrows_dev,
                    ),
                    output_schema,
                )
        self._count_fallback("join", "host-resident columns")
        return self._host_op(
            lambda a, b: self._native.join(a, b, how=how, on=on), df1, df2
        )

    def union(self, df1: DataFrame, df2: DataFrame, distinct: bool = True) -> DataFrame:
        j1: JaxDataFrame = self.to_df(df1)  # type: ignore
        j2: JaxDataFrame = self.to_df(df2)  # type: ignore
        j1, j2 = self._align_meshes(j1, j2)
        assert_or_throw(
            j1.schema == j2.schema,
            ValueError(f"union schema mismatch {j1.schema} vs {j2.schema}"),
        )
        if j1.blocks.all_on_device and j2.blocks.all_on_device:
            out = JaxDataFrame(
                relational.union_all_blocks(j1.blocks, j2.blocks), j1.schema
            )
            return self.distinct(out) if distinct else out
        self._count_fallback("union", "host-resident columns")
        return self._host_op(
            lambda a, b: self._native.union(a, b, distinct=distinct), df1, df2
        )

    def subtract(
        self, df1: DataFrame, df2: DataFrame, distinct: bool = True
    ) -> DataFrame:
        return self._set_op(df1, df2, distinct, subtract=True)

    def intersect(
        self, df1: DataFrame, df2: DataFrame, distinct: bool = True
    ) -> DataFrame:
        return self._set_op(df1, df2, distinct, subtract=False)

    def _set_op(
        self, df1: DataFrame, df2: DataFrame, distinct: bool, subtract: bool
    ) -> DataFrame:
        name = "subtract" if subtract else "intersect"
        j1: JaxDataFrame = self.to_df(df1)  # type: ignore
        j2: JaxDataFrame = self.to_df(df2)  # type: ignore
        j1, j2 = self._align_meshes(j1, j2)
        assert_or_throw(
            j1.schema == j2.schema,
            ValueError(f"{name} schema mismatch {j1.schema} vs {j2.schema}"),
        )
        if j1.blocks.all_on_device and j2.blocks.all_on_device:
            out = relational.intersect_subtract(
                self, j1.blocks, j2.blocks, j1.schema.names, subtract,
                distinct=distinct,
            )
            return JaxDataFrame(out, j1.schema)
        self._count_fallback(name, "host-resident columns")
        host = (
            self._native.subtract if subtract else self._native.intersect
        )
        return self._host_op(
            lambda a, b: host(a, b, distinct=distinct), df1, df2
        )

    def distinct(self, df: DataFrame) -> DataFrame:
        """Mask-only distinct: factorize all columns, keep each segment's
        representative row by flipping validity — no gather, and zero host
        syncs on the binned path."""
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        blocks = jdf.blocks
        if blocks.all_on_device and not (
            blocks.nrows_known and blocks.nrows == 0
        ):
            fr = groupby.factorize_keys(blocks, jdf.schema.names)

            def _distinct_prog(
                seg: Any,
                first_idx: Any,
                row_valid: Optional[Any],
                nrows_s: Any,
            ) -> Any:
                pad_n = seg.shape[0]
                row_valid = groupby.materialize_validity(
                    row_valid, pad_n, nrows_s
                )
                pos = jnp.arange(pad_n, dtype=jnp.int32)
                # invalid rows' sentinel seg clamps OOB on gather; they
                # stay invalid regardless
                return row_valid & (first_idx[seg] == pos)

            keep = self._jit_cached(
                ("distinct", blocks.padded_nrows, fr.num_segments),
                _distinct_prog,
            )(fr.seg, fr.first_idx, blocks.row_valid, _nrows_arg(blocks))
            return JaxDataFrame(
                JaxBlocks(
                    None,
                    dict(blocks.columns),
                    blocks.mesh,
                    row_valid=keep,
                    nrows_dev=fr.num_groups_dev,
                ),
                jdf.schema,
            )
        self._count_fallback("distinct")
        return self.to_df(self._native.distinct(jdf.as_local_bounded()))

    def dropna(
        self,
        df: DataFrame,
        how: str = "any",
        thresh: Optional[int] = None,
        subset: Optional[List[str]] = None,
    ) -> DataFrame:
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        blocks = jdf.blocks
        names = subset if subset is not None else jdf.schema.names
        if all(
            n in blocks.columns and blocks.columns[n].on_device for n in names
        ):
            pad_n = blocks.padded_nrows
            masks = {
                n: blocks.columns[n].mask
                for n in names
                if blocks.columns[n].mask is not None
            }

            def _dropna_prog(
                masks_: Dict[str, Any],
                row_valid: Optional[Any],
                nrows_s: Any,
            ) -> Tuple[Any, Any]:
                row_valid = groupby.materialize_validity(
                    row_valid, pad_n, nrows_s
                )
                valid_count = jnp.full((pad_n,), len(names) - len(masks_),
                                       dtype=jnp.int32)
                for m in masks_.values():
                    valid_count = valid_count + m.astype(jnp.int32)
                if thresh is not None:
                    keep = valid_count >= thresh
                elif how == "any":
                    keep = valid_count == len(names)
                else:  # all
                    keep = valid_count > 0
                keep = keep & row_valid
                return keep, jnp.sum(keep).astype(jnp.int32)

            keep, cnt = self._jit_cached(
                ("dropna", pad_n, how, thresh, tuple(sorted(names))),
                _dropna_prog,
            )(masks, blocks.row_valid, _nrows_arg(blocks))
            return JaxDataFrame(
                JaxBlocks(
                    None,
                    dict(blocks.columns),
                    blocks.mesh,
                    row_valid=keep,
                    nrows_dev=cnt,
                ),
                jdf.schema,
            )
        self._count_fallback("dropna")
        return self.to_df(
            self._native.dropna(
                jdf.as_local_bounded(), how=how, thresh=thresh, subset=subset
            )
        )

    def fillna(
        self, df: DataFrame, value: Any, subset: Optional[List[str]] = None
    ) -> DataFrame:
        """Device fillna: one jitted mask-flip + ``jnp.where`` per frame —
        the block layout makes this trivial (masked slots take the fill
        value, the mask drops). Float columns also fill literal NaNs in the
        data, matching pandas semantics."""
        assert_or_throw(
            (not isinstance(value, dict))
            or all(v is not None for v in value.values()),
            ValueError("fillna dict can't contain None"),
        )
        assert_or_throw(value is not None, ValueError("fillna value can't be None"))
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        blocks = jdf.blocks
        if isinstance(value, dict):
            fills: Dict[str, Any] = dict(value)
        elif subset is not None:
            fills = {c: value for c in subset}
        else:
            fills = {c: value for c in jdf.schema.names}
        targets = {
            n: v
            for n, v in fills.items()
            if n in blocks.columns
        }
        res = relational.device_fillna(self, blocks, jdf.schema, targets)
        if res is not None:
            return JaxDataFrame(res, jdf.schema)
        self._count_fallback("fillna", "host-resident or untypable fill")
        return self.to_df(
            self._native.fillna(jdf.as_local_bounded(), value=value, subset=subset)
        )

    def sample(
        self,
        df: DataFrame,
        n: Optional[int] = None,
        frac: Optional[float] = None,
        replace: bool = False,
        seed: Optional[int] = None,
    ) -> DataFrame:
        assert_or_throw(
            (n is None) != (frac is None),
            ValueError("one and only one of n and frac must be set"),
        )
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        blocks = jdf.blocks
        if not replace:
            # mask-only device sampling, zero host syncs: frac keeps rows
            # under a uniform threshold; exact-n keeps the n smallest
            # uniforms (the n-th order statistic is computed in-program)
            res = relational.device_sample(self, blocks, n, frac, seed)
            return JaxDataFrame(res, jdf.schema)
        # replace=True duplicates rows (changes the row multiset) — host RNG
        # gather; not a "fallback" per se (no device path exists for it)
        if blocks.row_valid is not None:
            valid_idx = np.nonzero(np.asarray(blocks.row_valid))[0]
        else:
            valid_idx = np.arange(blocks.nrows)
        total = len(valid_idx)
        rng = np.random.default_rng(seed)
        count = n if n is not None else int(round(total * frac))  # type: ignore
        idx = valid_idx[rng.choice(total, size=count, replace=True)]
        return JaxDataFrame(
            gather_indices(jdf.blocks, jnp.asarray(np.sort(idx)), jdf.schema),
            jdf.schema,
        )

    def take(
        self,
        df: DataFrame,
        n: int,
        presort: str,
        na_position: str = "last",
        partition_spec: Optional[PartitionSpec] = None,
    ) -> DataFrame:
        assert_or_throw(
            isinstance(n, int) and n >= 0,
            ValueError("n must be a non-negative int"),
        )
        assert_or_throw(
            na_position in ("first", "last"), ValueError("invalid na_position")
        )
        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        partition_spec = partition_spec or PartitionSpec()
        from fugue_tpu.collections.partition import parse_presort_exp

        sorts = (
            parse_presort_exp(presort) if presort else partition_spec.presort
        )
        res = relational.device_take(
            self, jdf.blocks, jdf.schema, n, sorts, na_position,
            list(partition_spec.partition_by),
        )
        if res is not None:
            return JaxDataFrame(res, jdf.schema)
        self._count_fallback("take", "host-resident sort/partition column")
        return self.to_df(
            self._native.take(
                jdf.as_local_bounded(), n, presort, na_position, partition_spec
            )
        )

    def load_df(
        self,
        path: Union[str, List[str]],
        format_hint: Any = None,
        columns: Any = None,
        **kwargs: Any,
    ) -> DataFrame:
        from fugue_tpu.constants import FUGUE_CONF_JAX_IO_BATCH_ROWS

        # optimizer-attached row-group pruning triples (ADVISORY: the
        # downstream filter re-applies the predicate, so ignoring them
        # on the eager path is always correct)
        pruning = kwargs.pop("pruning", None)
        first = path if isinstance(path, str) else path[0]
        if _lake_io.is_lake_uri(first):
            # lake reads resolve a SNAPSHOT (version/timestamp) and prune
            # whole files from manifest stats — forward the triples; the
            # row-group streaming path doesn't apply to manifest-driven
            # multi-file reads
            from fugue_tpu.utils import io as _io

            local = _io.load_df(
                path, format_hint, columns, fs=self.fs,
                pruning=pruning, conf=self.conf, **kwargs
            )
            res = self.to_df(local)
            from fugue_tpu.lake import parse_lake_uri

            _, params = parse_lake_uri(first)
            pinned = (
                kwargs.get("version") is not None
                or kwargs.get("timestamp") is not None
                or "version" in params
                or "timestamp" in params
            )
            if pinned:
                # a PINNED snapshot is deterministic lineage: device-loss
                # recovery can re-read the exact same data (an unpinned
                # read would re-resolve to a possibly newer version)
                self._attach_load_lineage(
                    res,
                    lambda: _io.load_df(
                        path, format_hint, columns, fs=self.fs,
                        pruning=pruning, conf=self.conf, **kwargs
                    ).as_arrow(),
                )
            return res
        batch_rows = int(self.conf.get(FUGUE_CONF_JAX_IO_BATCH_ROWS, 0))
        if batch_rows > 0:
            from fugue_tpu.jax_backend import ingest

            res = ingest.try_stream_load(
                self, path, format_hint, columns, batch_rows,
                pruning=pruning, **kwargs
            )
            if res is not None:
                return res
        from fugue_tpu.utils import io as _io

        local = _io.load_df(path, format_hint, columns, fs=self.fs, **kwargs)
        res = self.to_df(local)
        # the stored artifact (data file or checkpoint) IS the lineage
        self._attach_load_lineage(
            res,
            lambda: _io.load_df(
                path, format_hint, columns, fs=self.fs, **kwargs
            ).as_arrow(),
        )
        return res

    def save_df(
        self,
        df: DataFrame,
        path: str,
        format_hint: Any = None,
        mode: str = "overwrite",
        partition_spec: Optional[PartitionSpec] = None,
        force_single: bool = False,
        **kwargs: Any,
    ) -> None:
        from fugue_tpu.constants import FUGUE_CONF_JAX_IO_BATCH_ROWS
        from fugue_tpu.utils import io as _io

        jdf: JaxDataFrame = self.to_df(df)  # type: ignore
        batch_rows = int(self.conf.get(FUGUE_CONF_JAX_IO_BATCH_ROWS, 0))
        partition_cols = _io.spec_partition_cols(partition_spec, force_single)
        if _lake_io.is_lake_uri(path):
            # lake saves are transactional manifest commits, not file
            # replacement — the pipelined row-group writer doesn't apply
            _io.save_df(
                jdf.as_local_bounded(), path, format_hint, mode,
                partition_cols=partition_cols, fs=self.fs, **kwargs,
            )
            return
        if batch_rows > 0:
            # pipelined save (fugue.jax.io.pipeline): row-group writes of
            # chunk k overlap the device->host fetch of chunk k+1, so the
            # parquet encode rides the tail of compute instead of waiting
            # for the full readback; falls through to the eager path for
            # targets/frames it does not cover
            from fugue_tpu.jax_backend import ingest

            if ingest.try_pipelined_save(
                self, jdf, path, format_hint, mode, partition_cols,
                batch_rows, dict(kwargs),
            ):
                return
            kwargs.setdefault("batch_rows", batch_rows)
        _io.save_df(
            jdf.as_local_bounded(), path, format_hint, mode,
            partition_cols=partition_cols,
            fs=self.fs, **kwargs,
        )

    def convert_yield_dataframe(self, df: DataFrame, as_local: bool) -> DataFrame:
        return df.as_local() if as_local else df

    # ---- helpers ---------------------------------------------------------
    def _host_op(self, func: Callable, *dfs: DataFrame) -> DataFrame:
        locals_ = [self.to_df(d).as_local_bounded() for d in dfs]
        return self.to_df(func(*locals_))

    def _can_select_on_device(
        self,
        jdf: JaxDataFrame,
        cols: SelectColumns,
        where: Optional[ColumnExpr],
        having: Optional[ColumnExpr],
    ) -> bool:
        if having is not None and not cols.has_agg:
            return False  # invalid SQL: host owns the error
        if cols.is_distinct:
            return False
        blocks = jdf.blocks
        if where is not None and (
            not expr_eval.can_eval_on_device(where, blocks)
            or expr_eval.is_string_result(where, blocks)
        ):
            return False
        if not cols.has_agg:
            return all(
                expr_eval.can_eval_on_device(c, blocks) for c in cols.all_cols
            )
        # aggregation: group keys are device columns (string keys group by
        # dictionary code) or device-evaluable expressions, which get
        # materialized as key columns before the aggregate
        for k in cols.group_keys:
            if (
                isinstance(k, _NamedColumnExpr)
                and k.as_type is None
                and k.output_name == k.name
            ):
                col = blocks.columns.get(k.name)
                if col is None or not col.on_device:
                    return False
                continue
            name = k.output_name
            if name == "" or name in blocks.columns:
                # unnamed, or shadowing an existing column an agg arg
                # might still reference: host handles it
                return False
            if not expr_eval.can_eval_on_device(k, blocks):
                return False
        from fugue_tpu.column.expressions import _FuncExpr

        for a in cols.agg_funcs:
            if not isinstance(a, _FuncExpr) or len(a.args) != 1:
                return False
            fn = a.func.lower()
            if fn not in _DEVICE_AGGS:
                return False
            if a.arg_distinct and fn not in _DEVICE_DISTINCT_AGGS:
                return False
            arg = a.args[0]
            if isinstance(arg, _NamedColumnExpr) and arg.wildcard:
                continue
            if not expr_eval.can_eval_on_device(arg, blocks) or (
                expr_eval.is_string_result(arg, blocks) and fn != "count"
            ):
                return False
        return True

    def _device_project(
        self, jdf: JaxDataFrame, cols: SelectColumns, out_schema: Schema
    ) -> DataFrame:
        blocks = jdf.blocks
        pad_n = blocks.padded_nrows
        dicts = expr_eval.dicts_of(blocks)
        exprs = list(cols.all_cols)

        def _project_prog(mcols: Dict[str, Any]) -> Dict[str, Any]:
            outs: Dict[str, Any] = {}
            for c, f in zip(exprs, out_schema.fields):
                v, m = expr_eval.eval_expr(mcols, c, pad_n, dicts)
                outs[f"v:{f.name}"] = v
                if m is not None:
                    outs[f"m:{f.name}"] = m
            return outs

        outs = self._jit_cached(
            ("project", tuple(c.__uuid__() for c in exprs), pad_n,
             expr_eval.dict_fingerprint(blocks)),
            _project_prog,
        )(expr_eval.blocks_to_masked(blocks))
        sharding = row_sharding(blocks.mesh)
        new_cols: Dict[str, JaxColumn] = {}
        for c, f in zip(exprs, out_schema.fields):
            # plain column references keep their stats/dictionary
            src = (
                blocks.columns.get(c.name)
                if isinstance(c, _NamedColumnExpr) and c.as_type is None
                else None
            )
            dict_r = (
                src.dictionary
                if src is not None
                else (
                    expr_eval.result_dictionary(c, blocks)
                    if pa.types.is_string(f.type)
                    else None
                )
            )
            data = outs[f"v:{f.name}"]
            stats = src.stats if src is not None else None
            if dict_r is not None and src is None:
                data, dict_r, stats = expr_eval.finalize_string_result(
                    data, dict_r
                )
            new_cols[f.name] = JaxColumn(
                f.type,
                jax.device_put(data, sharding),
                None
                if f"m:{f.name}" not in outs
                else jax.device_put(outs[f"m:{f.name}"], sharding),
                dict_r,
                stats,
            )
        return JaxDataFrame(
            blocks_with_columns(blocks, new_cols), out_schema
        )

    def _device_groupby_select(
        self,
        jdf: JaxDataFrame,
        cols: SelectColumns,
        out_schema: Schema,
        having: Optional[ColumnExpr],
    ) -> Optional[DataFrame]:
        keys: List[str] = []
        computed: List[ColumnExpr] = []
        for k in cols.group_keys:
            if (
                isinstance(k, _NamedColumnExpr)
                and k.as_type is None
                and k.output_name == k.name
            ):
                keys.append(k.name)
            else:
                # expression OR aliased key: materialize it as a key
                # column first (a bare-ref rename keeps dictionary and
                # stats; _can_select_on_device guarantees a fresh name)
                computed.append(k)
                keys.append(k.output_name)
        if computed:
            jdf = self.to_df(self.assign(jdf, computed))  # type: ignore
        agg_exprs = list(cols.agg_funcs)
        visible = [c.output_name for c in cols.all_cols]
        having2: Optional[ColumnExpr] = None
        extra: Dict[str, ColumnExpr] = {}
        if having is not None:
            # HAVING refers to aggregations: rewrite agg subtrees into
            # refs over the aggregated output, computing HIDDEN agg
            # columns as needed, filter, then drop the hidden columns
            from fugue_tpu.column.pandas_eval import _rewrite_having

            computed_map = {
                c.alias("").__uuid__(): c.output_name
                for c in cols.agg_funcs
            }
            having2 = _rewrite_having(having, computed_map, extra)
            agg_exprs = agg_exprs + list(extra.values())
        res = self._try_device_aggregate(
            jdf, keys, agg_exprs, out_schema=out_schema,
            col_order=visible + list(extra.keys()),
        )
        if res is None or having2 is None:
            return res
        jres: JaxDataFrame = self.to_df(self.filter(res, having2))  # type: ignore
        if extra:
            jres = JaxDataFrame(
                blocks_with_columns(
                    jres.blocks,
                    {n: jres.blocks.columns[n] for n in visible},
                ),
                jres.schema.extract(visible),
            )
        return jres

    def _jit_cached(
        self, key: Any, fn: Callable, static_argnums: Any = None
    ) -> Callable:
        """Per-engine jit cache: logical programs (aggregate plans, map fns,
        filters) are keyed by structure so repeated queries reuse the
        compiled executable. Keys never include row counts — those enter
        programs as traced scalars/masks.

        ``static_argnums`` passes through to ``jax.jit``; a static-arg
        program bypasses the disk tier (the exec-cache signature scheme is
        value-independent for host scalars, and an AOT executable is
        compiled for ONE static value — serving another would be wrong).
        Every distinct static value is a fresh trace, which the retrace
        sentinel counts against the program's budget like any other.

        Each call records (fn, arg avals) in the program log so
        ``program_cost_analysis`` can AOT-lower the exact program later and
        read XLA's own flops/bytes accounting."""
        cache = getattr(self, "_jit_cache", None)
        if cache is None:
            cache = {}
            self._jit_cache = cache
        local = cache.get(key)
        if local is not None:
            # engine-local reuse is a plan-cache hit too: the compiled
            # handle is shared either way (one counter, two tiers)
            self._plan_hits.inc()
            return local
        # process-wide handle reuse: a same-signature engine already
        # jitted this logical program → its per-shape executables
        # come along for free (zero XLA compile on this engine)
        global_key = (self._plan_sig, key)
        jitted = self._plan_cache.get_program(global_key)
        if jitted is None:
            jitted = (
                jax.jit(fn)
                if static_argnums is None
                else jax.jit(fn, static_argnums=static_argnums)
            )
            self._plan_cache.put_program(global_key, jitted)
            self._plan_misses.inc()
        else:
            self._plan_hits.inc()
        name = str(key[0]) if isinstance(key, tuple) and key else str(key)
        disk_ok = self._exec_enabled and static_argnums is None

        def _wrapped(
            *args: Any, _j: Any = jitted, _f: Callable = fn, _k: Any = key,
            _n: str = name, _disk: bool = disk_ok,
        ) -> Any:
            if self._program_log_armed:
                self._program_log[_k] = (
                    _f, jax.tree_util.tree_map(_as_aval, args)
                )
            if _disk:
                return self._dispatch_with_disk_tier(_j, _f, _k, _n, args)
            return self._traced_dispatch(_j, _n, args, key=_k)

        cache[key] = _wrapped
        return _wrapped

    def _dispatch_with_disk_tier(
        self, jitted: Any, fn: Callable, key: Any, name: str, args: Any
    ) -> Any:
        """Dispatch with the persistent-executable tier in front of the
        jit path: a shape this process never compiled first probes the
        disk cache (deserialize ≪ compile); a shape the jit path already
        compiled skips the probe forever. A deserialized executable that
        rejects the live inputs (layout/sharding drift) falls back to
        the jit path — the tier can lose time, never correctness."""
        from fugue_tpu.optimize.exec_cache import (
            args_signature,
            fn_source_hash,
        )

        sig = args_signature(args)
        if sig is None:
            # a leaf the signature scheme does not model (host object,
            # uncommitted np array): the disk tier skips this program
            return self._traced_dispatch(jitted, name, args, key=key)
        # the key folds the cache BASE URI (the probe/compiled/persist
        # bookkeeping describes one disk's state — two same-signature
        # engines pointed at different dirs must not starve each other)
        # and the FN SOURCE HASH (a code change under the same logical
        # key must never hit a warm-loaded stale executable)
        exec_key = (
            self._exec_cache.base_uri,
            (self._plan_sig, key),
            fn_source_hash(fn),
            sig.token,
        )
        want_persist = False
        compiled = self._plan_cache.get_executable(exec_key)
        if compiled is None and not self._plan_cache.was_compiled(exec_key):
            compiled = self._load_executable(key, fn, sig, exec_key)
            # the disk has no (valid) entry for this shape: persist one
            # after the jit dispatch below — even when the jit handle
            # already owns the executable (compiled by an earlier
            # same-signature engine), the disk must still learn it, or a
            # warm in-memory tier would starve the cross-process tier
            want_persist = compiled is None
        if compiled is not None:
            try:
                t0 = time.perf_counter()
                with start_span("engine.dispatch", program=name) as sp:
                    out = compiled(*args)
                    if sp:
                        sp.name = "engine.execute"
                # an AOT dispatch is compile-free by construction: it
                # counts as a hit on the per-dispatch compile surface
                self._compile_hits.inc()
                self._add_dispatch_secs(
                    "execute", time.perf_counter() - t0
                )
                return out
            except Exception as ex:
                # ANY failure of a deserialized executable — python-level
                # aval/sharding mismatch (ValueError/TypeError) or an
                # XLA runtime rejection the token scheme cannot model —
                # drops the entry and falls back to the jit path, whose
                # fresh persist below OVERWRITES the disk entry: a bad
                # cached executable may lose time, never correctness,
                # and can never poison a query across restarts
                self._plan_cache.drop_executable(exec_key)
                want_persist = True
                self.log.info(
                    "fugue_tpu exec-cache: cached executable for %s "
                    "rejected live inputs (%s: %s); recompiling",
                    name, type(ex).__name__, ex,
                )
        return self._traced_dispatch(
            jitted, name, args,
            persist=(key, fn, sig, exec_key) if want_persist else None,
            key=key,
        )

    def _load_executable(
        self, key: Any, fn: Callable, sig: Any, exec_key: Any
    ) -> Optional[Any]:
        """One disk-tier probe: deserialize the entry for (program key,
        fn hash, avals) if present and version-valid; counts
        hit/miss/evict/corrupt under ``tier="disk"``."""
        dc = self._exec_cache
        eid = dc.entry_id(self._plan_sig, key, fn, sig.token)
        if eid is None:
            self._plan_cache.mark_compiled(exec_key)  # never probe again
            return None
        t0 = time.perf_counter()
        status, compiled, _meta = dc.load(dc.entry_uri(self._plan_sig, eid))
        elapsed = time.perf_counter() - t0
        if status == "hit":
            self._disk_hits.inc()
            self._m_deserialize.labels().observe(elapsed)
            self._add_dispatch_secs("disk_load", elapsed)
            self._plan_cache.put_executable(exec_key, compiled)
            return compiled
        # disjoint result labels (matching the warm-scan path): an
        # absent entry is a miss; a version-stale or unreadable one
        # counts ONLY as evict/corrupt — either way the caller compiles
        if status == "evict":
            self._disk_evicts.inc()
        elif status == "corrupt":
            self._disk_corrupt.inc()
        else:
            self._disk_misses.inc()
        return None

    def try_begin_warm(self) -> Optional[Callable[[], int]]:
        """SYNCHRONOUSLY claim the once-per-(cache dir, plan signature)
        executable warm and hand back the work to run (on any thread);
        None when the disk tier is off or another caller already owns
        the claim. Callers who must not lose the claim to a concurrent
        warm trigger (the daemon's readiness gate vs a streamed
        ingest's first-batch hook) claim here first, then run/spawn."""
        if not self._exec_enabled:
            return None
        if not self._plan_cache.claim_warm(
            (self._exec_cache.base_uri, self._plan_sig)
        ):
            return None
        return self._warm_executables_now

    def warm_executables(self, background: bool = False) -> Any:
        """Load every disk-tier entry matching this engine's plan
        signature into the in-memory executable store, so upcoming
        dispatches are compile-free AND deserialize-free. Runs at most
        once per (cache dir, plan signature) per process (the claim
        lives on the plan cache, taken on THIS thread). Returns the
        number of executables loaded — or, with ``background=True``,
        the started thread (None when there is nothing to do)."""
        work = self.try_begin_warm()
        if work is None:
            return None if background else 0
        if background:
            from fugue_tpu.optimize.exec_cache import spawn_warm_thread

            return spawn_warm_thread(work)
        return work()

    def _warm_executables_now(self) -> int:
        dc = self._exec_cache
        loaded = 0
        try:
            for uri in dc.scan(self._plan_sig):
                t0 = time.perf_counter()
                status, compiled, meta = dc.load(uri)
                if status == "hit" and meta is not None:
                    self._disk_hits.inc()
                    elapsed = time.perf_counter() - t0
                    self._m_deserialize.labels().observe(elapsed)
                    self._add_dispatch_secs("disk_load", elapsed)
                    self._plan_cache.put_executable(
                        (
                            dc.base_uri,
                            (meta["plan_sig"], meta["key"]),
                            # entries without a recorded fn hash can
                            # never match a live dispatch key: stale
                            # formats warm-load inert, never wrong
                            meta.get("fn_hash", ""),
                            meta["aval_token"],
                        ),
                        compiled,
                    )
                    loaded += 1
                elif status == "evict":
                    self._disk_evicts.inc()
                elif status == "corrupt":
                    self._disk_corrupt.inc()
        except Exception as ex:  # pragma: no cover - warm is best-effort
            self.log.warning(
                "fugue_tpu exec-cache: warm scan failed (%s: %s)",
                type(ex).__name__, ex,
            )
        if loaded:
            self.log.info(
                "fugue_tpu exec-cache: pre-warmed %d executables from %s",
                loaded, dc.base_uri,
            )
        return loaded

    def _traced_dispatch(
        self, jitted: Any, name: str, args: Any, persist: Any = None,
        key: Any = None,
    ) -> Any:
        """One jitted-program dispatch under the compile/execute span
        split. Whether THIS dispatch compiled is read from jax's own
        per-shape cache (``_cache_size`` growth), so shape-driven
        recompiles (row_bucket=0) and post-failure retries are labeled
        ``engine.compile`` too — the slow-query breakdown must pin
        multi-second compile time on the compile phase, not execute.

        ``persist`` (set by the disk-tier dispatch path) is the
        ``(key, fn, sig, exec_key)`` needed to background-persist the
        executable this dispatch is about to compile.

        ``key`` is the logical program key for the retrace sentinel's
        per-program trace accounting (None for unkeyed dispatches —
        counted under the program name alone)."""
        sizer = getattr(jitted, "_cache_size", None)
        before = -1
        if sizer is not None:
            try:
                before = sizer()
            except Exception:  # pragma: no cover - jax version drift
                sizer = None
        t0 = time.perf_counter()
        with start_span("engine.dispatch", program=name) as sp:
            out = jitted(*args)
            compiled = False
            if sizer is not None:
                try:
                    compiled = sizer() > before
                except Exception:  # pragma: no cover
                    pass
            if compiled:
                self._compile_misses.inc()
                # retrace sentinel (debug twin of the FJX lint plane):
                # every ACTUAL trace is counted per program key; past the
                # budget the sentinel reports callsite + differing aval.
                # Off (the default) this is one module-global read.
                san = active_retrace_sentinel()
                if san is not None:
                    ev = san.note_trace(name, key, args)
                    if ev is not None:
                        self._m_retrace.labels(program=name).inc()
                        san.raise_if_armed(ev)
            else:
                self._compile_hits.inc()
            if sp:
                # spans are plain records: the name settles once the
                # dispatch revealed whether it compiled
                sp.name = "engine.compile" if compiled else "engine.execute"
        self._add_dispatch_secs(
            "compile" if compiled else "execute", time.perf_counter() - t0
        )
        if persist is not None:
            key, fn, sig, exec_key = persist
            # whichever way this dispatch went, the jit handle now owns
            # the shape in-process: later dispatches skip the disk probe
            self._plan_cache.mark_compiled(exec_key)
            # persist even when THIS dispatch did not compile — the
            # handle may carry an executable compiled before the disk
            # tier was watching (earlier same-signature engine), and the
            # probe above established the disk does not have it yet;
            # lower().compile() hits jax's in-memory caches either way
            self._exec_cache.schedule_persist(
                jitted, self._plan_sig, key, fn, sig, name,
                on_done=self._note_persist,
            )
        return out

    def _note_persist(self, ok: bool) -> None:
        (self._persist_ok if ok else self._persist_err).inc()

    def _map_program(
        self,
        key: Any,
        fn: Callable,
        array_args: Dict[str, Any],
        blocks: JaxBlocks,
        col_names: List[str],
        stash: Optional[Dict[str, Any]] = None,
    ) -> Tuple[Callable, Dict[str, str], Dict[str, Any]]:
        """Jit a compiled-map program and (once, at cache miss) analyze its
        jaxpr for column passthroughs: an output leaf that IS an input var
        carries the input column's value bounds, so stats (and dictionaries)
        propagate soundly through user transforms — the key enabler of
        sync-free group-by after a transform.

        ``stash`` collects fn-returned string decode tables at trace time;
        it is cached WITH the executable (the cache key includes the input
        dictionaries' identities, and the cached closure keeps them alive,
        so ``id`` reuse cannot alias entries)."""
        cache = getattr(self, "_map_cache", None)
        if cache is None:
            cache = {}
            self._map_cache = cache
        if key not in cache:
            inner = jax.jit(fn)

            def jitted(
                *args: Any, _j: Any = inner, _f: Callable = fn, _k: Any = key
            ) -> Any:
                # recorded like _jit_cached programs so the compiled map
                # shows up in program_cost_analysis (the headline's
                # transform traffic)
                if self._program_log_armed:
                    self._program_log[
                        ("map",) + (_k if isinstance(_k, tuple) else (_k,))
                    ] = (_f, jax.tree_util.tree_map(_as_aval, args))
                return self._traced_dispatch(_j, "map", args)
            passthrough: Dict[str, str] = {}
            try:
                shaped = {
                    k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                    for k, v in array_args.items()
                }
                rv = blocks.row_valid
                rv_s = (
                    None
                    if rv is None
                    else jax.ShapeDtypeStruct(rv.shape, rv.dtype)
                )
                closed = jax.make_jaxpr(fn)(
                    shaped, rv_s, jax.ShapeDtypeStruct((), jnp.int32)
                )
                in_leaves, in_tree = jax.tree_util.tree_flatten(
                    (shaped, rv_s, jax.ShapeDtypeStruct((), jnp.int32))
                )
                in_paths = [
                    p
                    for p, _ in jax.tree_util.tree_flatten_with_path(
                        (shaped, rv_s, jax.ShapeDtypeStruct((), jnp.int32))
                    )[0]
                ]
                # rebuild the output structure to get leaf names
                out_aval_tree = jax.eval_shape(
                    fn, shaped, rv_s, jax.ShapeDtypeStruct((), jnp.int32)
                )
                out_paths = [
                    p
                    for p, _ in jax.tree_util.tree_flatten_with_path(
                        out_aval_tree
                    )[0]
                ]
                invars = closed.jaxpr.invars
                outvars = closed.jaxpr.outvars
                var_to_in: Dict[Any, str] = {}
                for var, path in zip(invars, in_paths):
                    name = _path_leaf_key(path)
                    if name is not None:
                        var_to_in[var] = name
                for var, path in zip(outvars, out_paths):
                    name = _path_leaf_key(path)
                    if name is None or name.startswith("_"):
                        continue
                    src = var_to_in.get(var)
                    if src is not None and src in col_names:
                        passthrough[name] = src
            except Exception:  # pragma: no cover - analysis is best-effort
                passthrough = {}
            cache[key] = (jitted, passthrough, stash if stash is not None else {})
        return cache[key]

    def _try_device_aggregate(
        self,
        jdf: JaxDataFrame,
        keys: List[str],
        agg_cols: List[ColumnExpr],
        out_schema: Optional[Schema] = None,
        col_order: Optional[List[str]] = None,
    ) -> Optional[DataFrame]:
        from fugue_tpu.column.expressions import _FuncExpr

        blocks = jdf.blocks
        for k in keys:
            col = blocks.columns.get(k)
            if col is None or not col.on_device:
                return None
        plans = []
        distinct_args: Dict[str, str] = {}
        for c in agg_cols:
            if not isinstance(c, _FuncExpr) or len(c.args) != 1:
                return None
            fn = c.func.lower()
            if fn not in _DEVICE_AGGS:
                return None
            arg = c.args[0]
            if fn == "median" or fn in VARIANCE_FUNCS:
                # DISTINCT composes via the first-occurrence mask below
                tp0 = arg.infer_type(jdf.schema)
                if tp0 is None or not (
                    pa.types.is_integer(tp0)
                    or pa.types.is_floating(tp0)
                    or pa.types.is_boolean(tp0)
                ):
                    return None  # the host oracle owns the type error
            if c.arg_distinct:
                # DISTINCT: min/max are dedup-invariant; count/sum/avg
                # dedup via a per-(keys, value) first-occurrence mask.
                # first/last DISTINCT are order-sensitive: host runner.
                if fn in ("first", "last"):
                    return None
                if fn not in ("min", "max"):
                    if (
                        not isinstance(arg, _NamedColumnExpr)
                        or arg.wildcard
                        or arg.as_type is not None
                    ):
                        return None
                    acol = blocks.columns.get(arg.name)
                    if acol is None or not acol.on_device:
                        return None
                    if fn != "count" and acol.is_string:
                        return None
                    distinct_args[c.output_name] = arg.name
            if isinstance(arg, _NamedColumnExpr) and arg.wildcard:
                plans.append((c.output_name, "count", None, c))
                continue
            if not expr_eval.can_eval_on_device(
                arg, blocks
            ) or (
                expr_eval.is_string_result(arg, blocks) and fn != "count"
            ):
                return None
            plans.append((c.output_name, fn, arg, c))
        # known-empty inputs stay on the device path too: padded_len(0)=ndev
        # keeps arrays non-empty, all rows invalid, so keyed aggregates give
        # 0 groups and global ones count=0/NULL — the SAME conventions a
        # lazily-empty masked frame gets (advisor r2, low: the two paths
        # must not diverge based on whether the count happens to be known)
        pad_n = blocks.padded_nrows
        dicts = expr_eval.dicts_of(blocks)
        # resolve output types up front (needed inside the traced program)
        typed_plans = []
        for name, func, arg, expr in plans:
            tp = expr.infer_type(jdf.schema)
            if tp is None:
                return None
            typed_plans.append((name, func, arg, tp))
        ndev = int(blocks.mesh.devices.size)
        sharding = row_sharding(blocks.mesh)
        if len(keys) == 0:
            return self._global_aggregate(
                jdf, typed_plans, col_order, sharding, distinct_args
            )
        bspec = groupby.bin_spec(blocks, keys)
        if bspec is not None:
            kinds = []
            need_int, all_f32 = False, True
            for _, func, arg, _ in typed_plans:
                kind = self._packed_agg_kind(jdf, func, arg)
                kinds.append(kind)
                if kind == "int":
                    need_int = True
                elif kind == "float":
                    atp = arg.infer_type(jdf.schema)
                    if atp is None or not pa.types.is_float32(atp):
                        all_f32 = False
            if all(k is not None for k in kinds):
                # payload estimate for the crossover table: every plan
                # contributes at most one payload row + the occupancy slot
                # (dedup inside the program can only shrink it)
                strategy = self._groupby_strategy(
                    blocks,
                    pad_n,
                    bspec.total,
                    1 + len(typed_plans),
                    need_int=need_int,
                    all_f32=all_f32,
                )
                if strategy is not None:
                    return self._binned_packed_aggregate(
                        jdf, keys, typed_plans, bspec, col_order,
                        sharding, strategy, distinct_args,
                    )
        fr = groupby.factorize_keys(blocks, keys)
        num_segments = fr.num_segments
        out_pad = padded_len(num_segments, ndev)
        # the generic (unpacked) path still routes its sum-type reductions
        # through the strategy layer per tier — min/max/median etc. stay
        # scatter-native inside _segment_agg_impl
        seg_strategy = self._count_reduce_strategy(blocks, num_segments)
        # devices-aware column of the strategy decision: on multi-device
        # meshes, repartition rows by key (all-to-all) so each device
        # reduces only its own segments instead of every device reducing
        # the full segment space redundantly
        from fugue_tpu.jax_backend import segtune as _segtune
        from fugue_tpu.jax_backend import shuffle as _shuffle

        use_shuffle = _segtune.choose_shuffle(
            self._shuffle_mode(), blocks.mesh, pad_n, num_segments
        )
        # combinable plan sets ride the map-side combine (partial
        # aggregation + reduce-scatter-layout all-to-all): O(S * ndev)
        # traffic. Only non-combinable aggregates (median, variance)
        # need the O(rows * ndev) row shuffle
        use_preagg = use_shuffle and _shuffle.preagg_ok(
            [f for _, f, _, _ in typed_plans]
        )
        use_overlap = (
            use_shuffle
            and not use_preagg
            and _segtune.choose_overlap(
                self._shuffle_overlap_mode(), blocks.mesh, num_segments
            )
        )
        mesh = blocks.mesh

        # ONE fused program: every agg + key gather + padding, single dispatch
        def _agg_program(
            mcols: Dict[str, Any],
            key_data: Dict[str, Any],
            key_masks: Dict[str, Any],
            seg_: Any,
            first_idx_: Any,
            occupied_: Optional[Any],
            dsegs_: Dict[str, Any],
            dfirsts_: Dict[str, Any],
            row_valid: Optional[Any],
            nrows_s: Any,
        ) -> Dict[str, Any]:
            valid_ = groupby.materialize_validity(row_valid, pad_n, nrows_s)
            outs: Dict[str, Any] = {}
            for k in keys:
                kd = key_data[k][first_idx_]
                km = key_masks.get(k)
                outs[f"k:{k}"] = _pad_to(kd, out_pad)
                if km is not None:
                    outs[f"km:{k}"] = _pad_to(km[first_idx_], out_pad)
            plan_inputs = []
            for name, func, arg, tp in typed_plans:
                if func == "count" and arg is None:
                    values: Any = jnp.ones((pad_n,), dtype=jnp.int32)
                    mask: Any = None
                else:
                    values, mask = expr_eval.eval_expr(
                        mcols, arg, pad_n, dicts
                    )
                mask = _apply_distinct_mask(
                    dsegs_, dfirsts_, name, pad_n, mask
                )
                plan_inputs.append((name, func, tp, values, mask))
            if use_preagg:
                # map-side combine: per-device partials, one tiny
                # all-to-all of (ndev, S_local) partial tables
                pairs = _shuffle.preagg_segment_aggs(
                    mesh,
                    [f for _, f, _, _, _ in plan_inputs],
                    seg_,
                    valid_,
                    [
                        None if f == "count" else v
                        for _, f, _, v, _ in plan_inputs
                    ],
                    [m for _, _, _, _, m in plan_inputs],
                    num_segments,
                    strategy=seg_strategy,
                )
            elif use_shuffle:
                # ONE all-to-all co-locates every plan's rows by key;
                # count transports only its mask (values are unused by
                # the count kernel — but the mask MUST travel, it folds
                # into the effective row count)
                pairs = _shuffle.shuffled_segment_aggs(
                    mesh,
                    [f for _, f, _, _, _ in plan_inputs],
                    seg_,
                    valid_,
                    [
                        None if f == "count" else v
                        for _, f, _, v, _ in plan_inputs
                    ],
                    [m for _, _, _, _, m in plan_inputs],
                    num_segments,
                    strategy=seg_strategy,
                    overlap=use_overlap,
                )
            else:
                pairs = [
                    groupby._segment_agg_impl(
                        f, v, m, seg_, num_segments, valid_,
                        strategy=seg_strategy,
                    )
                    for _, f, _, v, m in plan_inputs
                ]
            for (name, func, tp, _, _), (v, m) in zip(plan_inputs, pairs):
                outs[f"a:{name}"] = _pad_to(_cast_agg_result(v, tp), out_pad)
                if m is not None:
                    outs[f"am:{name}"] = _pad_to(m, out_pad)
            if occupied_ is not None:
                outs["_occupied"] = _pad_to(occupied_, out_pad)
            return outs

        dsegs, dfirsts = _distinct_factorize(blocks, keys, distinct_args)
        prog_key = (
            "agg",
            tuple((n, f, None if a is None else a.__uuid__(), str(t))
                  for n, f, a, t in typed_plans),
            tuple(keys), num_segments, out_pad, pad_n, seg_strategy,
            ("shuf", use_shuffle, use_preagg, use_overlap, ndev),
            tuple(sorted(distinct_args.items())),
            expr_eval.dict_fingerprint(blocks),
        )
        self._count_strategy("generic")
        if use_shuffle:
            # per-strategy shuffle visibility: which exchange plan ran
            # (map-side combine vs row shuffle) and which reduction
            # kernel the local pass used
            self._count_strategy(
                "shuffle_preagg" if use_preagg
                else f"shuffle_{seg_strategy}"
            )
        key_data = {k: blocks.columns[k].data for k in keys}
        key_masks = {
            k: blocks.columns[k].mask
            for k in keys
            if blocks.columns[k].mask is not None
        }
        t0 = time.perf_counter() if use_shuffle else 0.0
        outs = self._jit_cached(prog_key, _agg_program)(
            expr_eval.blocks_to_masked(blocks),
            key_data,
            key_masks,
            fr.seg,
            fr.first_idx,
            fr.occupied,
            dsegs,
            dfirsts,
            blocks.row_valid,
            _nrows_arg(blocks),
        )
        if use_shuffle:
            if use_preagg:
                # per-segment partial widths: count ships an i32 count,
                # everything else an 8B value + a marker/count column
                widths = sum(
                    4 if f == "count" else 9 for _, f, _, _ in typed_plans
                )
                nbytes = _shuffle.estimate_preagg_bytes(
                    num_segments, ndev, widths
                )
            else:
                widths = sum(
                    (0 if f == "count" else 8) + 1
                    for _, f, _, _ in typed_plans
                )
                nbytes = _shuffle.estimate_shuffle_bytes(
                    pad_n, ndev, widths
                )
            self._count_shuffle(
                "aggregate", nbytes, time.perf_counter() - t0, use_overlap
            )
        out_cols: Dict[str, JaxColumn] = {}
        schema_fields = [jdf.schema[k] for k in keys]
        for k in keys:
            src_col = blocks.columns[k]
            out_cols[k] = JaxColumn(
                src_col.pa_type,
                jax.device_put(outs[f"k:{k}"], sharding),
                None if f"km:{k}" not in outs else jax.device_put(
                    outs[f"km:{k}"], sharding
                ),
                src_col.dictionary,
                src_col.stats,
            )
        for name, func, arg, tp in typed_plans:
            out_cols[name] = JaxColumn(
                tp,
                jax.device_put(outs[f"a:{name}"], sharding),
                None if f"am:{name}" not in outs else jax.device_put(
                    outs[f"am:{name}"], sharding
                ),
            )
            schema_fields.append(pa.field(name, tp))
        schema = Schema(schema_fields)
        if col_order is not None:
            schema = schema.extract(col_order)
            out_cols = {n: out_cols[n] for n in col_order}
        if "_occupied" in outs:
            # binned path: empty bins masked out lazily; count stays a
            # device scalar until the host asks
            row_valid_out = jax.device_put(outs["_occupied"], sharding)
            return JaxDataFrame(
                JaxBlocks(
                    None,
                    out_cols,
                    blocks.mesh,
                    row_valid=row_valid_out,
                    nrows_dev=fr.num_groups_dev,
                ),
                schema,
            )
        return JaxDataFrame(
            JaxBlocks(num_segments, out_cols, blocks.mesh), schema
        )

    def _try_stream_aggregate(
        self, df: DataFrame, keys: List[str], agg_cols: List[ColumnExpr]
    ) -> Optional[DataFrame]:
        """Streaming aggregation for iterable-of-frames inputs (keys must
        be integer-like, aggs in the streaming whitelist); None when the
        input is an ordinary bounded frame."""
        from fugue_tpu.dataframe.dataframe_iterable_dataframe import (
            LocalDataFrameIterableDataFrame,
        )

        if not isinstance(df, LocalDataFrameIterableDataFrame):
            return None
        if len(keys) == 0:
            return None
        schema = df.schema
        for k in keys:
            if k not in schema or not (
                pa.types.is_integer(schema[k].type)
                or pa.types.is_boolean(schema[k].type)
            ):
                return None
        from fugue_tpu.column.expressions import _FuncExpr
        from fugue_tpu.jax_backend import streaming

        plans: List[Tuple[str, str, Optional[str]]] = []
        for c in agg_cols:
            if (
                not isinstance(c, _FuncExpr)
                or len(c.args) != 1
                or c.arg_distinct
                or c.func.lower() not in streaming._SUPPORTED
            ):
                return None
            arg = c.args[0]
            if isinstance(arg, _NamedColumnExpr) and arg.wildcard:
                src = keys[0]  # count(*): count key occurrences
            elif isinstance(arg, _NamedColumnExpr) and arg.as_type is None:
                src = arg.name
            else:
                return None
            plans.append((c.output_name, c.func.lower(), src))

        def _chunks() -> Any:
            for local in df.native:
                yield local.as_pandas()

        try:
            return streaming.stream_aggregate(
                self, _chunks(), schema, list(keys), plans
            )
        except streaming.StreamFallback as fb:
            # bounded-path semantics can't stream (NULL keys, unbounded key
            # space, empty stream): materialize and go through the normal
            # path so results never depend on the container type
            self._count_fallback("aggregate", f"stream fallback: {fb}")
            from fugue_tpu.dataframe import PandasDataFrame

            pdf = streaming.materialize_fallback(fb, schema)
            bounded = PandasDataFrame(pdf, schema)
            jdf = self.to_df(bounded)
            res = self._try_device_aggregate(jdf, list(keys), agg_cols)
            if res is not None:
                return res
            return self.to_df(
                self._native.aggregate(
                    bounded, PartitionSpec(by=list(keys)), agg_cols
                )
            )

    def _strategy_mode(self) -> str:
        """The configured strategy: ``fugue.jax.groupby.strategy``, with
        the legacy ``fugue.jax.groupby.matmul`` knob mapped onto it
        (always -> matmul, never -> scatter) for back-compat."""
        from fugue_tpu.constants import (
            FUGUE_CONF_JAX_GROUPBY_MATMUL,
            FUGUE_CONF_JAX_GROUPBY_STRATEGY,
        )

        mode = str(
            self.conf.get(FUGUE_CONF_JAX_GROUPBY_STRATEGY, "auto")
        ).lower()
        assert_or_throw(
            mode == "auto" or mode in groupby.STRATEGIES,
            ValueError(
                f"{FUGUE_CONF_JAX_GROUPBY_STRATEGY}={mode!r} is not one of "
                f"{('auto',) + groupby.STRATEGIES}"
            ),
        )
        legacy = str(
            self.conf.get(FUGUE_CONF_JAX_GROUPBY_MATMUL, "auto")
        ).lower()
        if mode == "auto" and legacy != "auto":
            mode = "matmul" if legacy == "always" else "scatter"
        return mode

    def _shuffle_mode(self) -> str:
        """``fugue.jax.shuffle`` normalized to auto/on/off — whether
        segment reductions repartition rows by key over the mesh first."""
        from fugue_tpu.constants import FUGUE_CONF_JAX_SHUFFLE
        from fugue_tpu.jax_backend import segtune

        return segtune.shuffle_mode(
            self.conf.get(FUGUE_CONF_JAX_SHUFFLE, "auto"),
            FUGUE_CONF_JAX_SHUFFLE,
        )

    def _shuffle_overlap_mode(self) -> str:
        """``fugue.jax.shuffle.overlap`` normalized to auto/on/off —
        whether shuffled reductions double-buffer the next key-range's
        all-to-all behind the current range's local reduction."""
        from fugue_tpu.constants import FUGUE_CONF_JAX_SHUFFLE_OVERLAP
        from fugue_tpu.jax_backend import segtune

        return segtune.shuffle_mode(
            self.conf.get(FUGUE_CONF_JAX_SHUFFLE_OVERLAP, "auto"),
            FUGUE_CONF_JAX_SHUFFLE_OVERLAP,
        )

    def _join_shuffle(self, mesh: Any, rows: int, num_segments: int) -> bool:
        """Shuffle decision for relational.py's join count reductions —
        same strategy column as aggregates, exposed so expand_join does
        not reach into conf itself."""
        from fugue_tpu.jax_backend import segtune

        return segtune.choose_shuffle(
            self._shuffle_mode(), mesh, rows, num_segments
        )

    def _groupby_strategy(
        self,
        blocks: JaxBlocks,
        rows: int,
        num_segments: int,
        n_payload: int,
        need_int: bool = False,
        all_f32: bool = True,
    ) -> Optional[str]:
        """Select the packed segment-reduction strategy for one aggregate
        shape, or None when no strategy is eligible (the caller then takes
        the generic per-agg path). Eligibility: the matmul family cannot
        sum integers exactly and is capped at _MATMUL_MAX_SEGMENTS (the
        one-hot transient), matmul_bf16 additionally needs all-f32 float
        payloads; scatter/sort run up to _PACKED_MAX_SEGMENTS. ``auto``
        consults segtune's measured table + one-shot on-device autotune;
        an explicit conf pin is honored when eligible."""
        from fugue_tpu.constants import FUGUE_CONF_JAX_GROUPBY_AUTOTUNE
        from fugue_tpu.jax_backend import segtune

        candidates: List[str] = []
        if not need_int and num_segments <= groupby._MATMUL_MAX_SEGMENTS:
            candidates.append("matmul")
            if all_f32:
                candidates.append("matmul_bf16")
        if num_segments <= groupby._PACKED_MAX_SEGMENTS:
            candidates.extend(["scatter", "sort"])
        if not candidates:
            return None
        mode = self._strategy_mode()
        if mode != "auto":
            return mode if mode in candidates else None
        # bf16's hi/lo split trades ~8 mantissa bits for speed — an
        # accuracy change users must PIN into, never an autotune pick
        # (review finding)
        candidates = [c for c in candidates if c != "matmul_bf16"]
        return segtune.choose_strategy(
            blocks.mesh,
            rows,
            num_segments,
            n_payload,
            candidates,
            typed_conf_get(self.conf, FUGUE_CONF_JAX_GROUPBY_AUTOTUNE),
            self.log,
        )

    def _count_reduce_strategy(
        self, blocks: JaxBlocks, num_segments: int
    ) -> str:
        """Strategy for single-payload 0/1 count reductions (join sides,
        window/generic aggregates): the shapes relational.py shares with
        the group-by machinery. Sorting inside a join program is never
        worth it for one payload, so the choice is matmul-vs-scatter by
        tier and segment cap; explicit strategy pins map onto that pair."""
        from fugue_tpu.jax_backend import segtune

        mode = self._strategy_mode()
        if mode in ("matmul", "matmul_bf16"):
            return (
                mode
                if num_segments <= groupby._MATMUL_MAX_SEGMENTS
                else "scatter"
            )
        if mode in ("scatter", "sort"):
            return "scatter"
        platform = blocks.mesh.devices.flat[0].platform
        if (
            platform != "cpu"
            and num_segments <= groupby._MATMUL_MAX_SEGMENTS
        ):
            return "matmul"
        return "scatter"

    def _packed_agg_kind(
        self, jdf: JaxDataFrame, func: str, arg: Any
    ) -> Optional[str]:
        """How an aggregation rides the packed strategy kernels: "count",
        "float" (f32/f64 sum/avg payload), "int" (exact integer sum/avg
        payload — scatter/sort strategies only), or None (not packable:
        min/max/median and friends stay on the generic path)."""
        if func == "count":
            return "count"
        if func not in ("sum", "avg", "mean"):
            return None
        tp = arg.infer_type(jdf.schema) if arg is not None else None
        if tp is None and isinstance(arg, _NamedColumnExpr):
            col = jdf.schema[arg.name] if arg.name in jdf.schema else None
            tp = col.type if col is not None else None
        if tp is None:
            return None
        if pa.types.is_floating(tp):
            return "float"
        if pa.types.is_integer(tp):
            return "int"
        return None

    def _global_aggregate(
        self,
        jdf: JaxDataFrame,
        typed_plans: List[Tuple[str, str, Any, pa.DataType]],
        col_order: Optional[List[str]],
        sharding: Any,
        distinct_args: Optional[Dict[str, str]] = None,
    ) -> DataFrame:
        """Keyless aggregation: plain masked jnp reductions — one program,
        no segments, no scatter. DISTINCT aggregates contribute only the
        first row of each value (a per-value factorize mask)."""
        blocks = jdf.blocks
        pad_n = blocks.padded_nrows
        dicts = expr_eval.dicts_of(blocks)
        dsegs, dfirsts = _distinct_factorize(blocks, [], distinct_args)

        def _prog(
            mcols: Dict[str, Any],
            dsegs_: Dict[str, Any],
            dfirsts_: Dict[str, Any],
            row_valid: Optional[Any],
            nrows_s: Any,
        ) -> Dict[str, Any]:
            valid = groupby.materialize_validity(row_valid, pad_n, nrows_s)
            outs: Dict[str, Any] = {}
            for name, func, arg, tp in typed_plans:
                if func == "count" and arg is None:
                    values: Any = jnp.ones((pad_n,), dtype=jnp.int32)
                    mask: Any = None
                else:
                    values, mask = expr_eval.eval_expr(
                        mcols, arg, pad_n, dicts
                    )
                mask = _apply_distinct_mask(
                    dsegs_, dfirsts_, name, pad_n, mask
                )
                eff = valid if mask is None else (mask & valid)
                cnt = jnp.sum(eff.astype(jnp.int32))
                if func == "count":
                    v: Any = cnt
                    m: Any = None
                elif func in ("sum", "avg", "mean"):
                    tot = jnp.sum(jnp.where(eff, values, 0))
                    v = (
                        tot
                        if func == "sum"
                        else tot / jnp.maximum(cnt, 1)
                    )
                    m = cnt > 0
                elif func == "median":
                    eff2 = eff
                    if jnp.issubdtype(values.dtype, jnp.floating):
                        eff2 = eff2 & ~jnp.isnan(values)
                    c2 = jnp.sum(eff2.astype(jnp.int32))
                    fv2 = values.astype(jnp.float64)
                    sv = jnp.sort(jnp.where(eff2, fv2, jnp.inf))
                    npad = sv.shape[0]
                    lo = jnp.clip((c2 - 1) // 2, 0, npad - 1)
                    hi = jnp.clip(c2 // 2, 0, npad - 1)
                    v = (sv[lo] + sv[hi]) * 0.5
                    m = c2 > 0
                elif func in VARIANCE_FUNCS:
                    eff2 = eff
                    if jnp.issubdtype(values.dtype, jnp.floating):
                        eff2 = eff2 & ~jnp.isnan(values)  # pandas skips NaN
                    c2 = jnp.sum(eff2.astype(jnp.int32))
                    fv = jnp.where(eff2, values.astype(jnp.float64), 0.0)
                    cf = c2.astype(jnp.float64)
                    mean = jnp.sum(fv) / jnp.maximum(cf, 1.0)
                    dev = jnp.where(
                        eff2, values.astype(jnp.float64) - mean, 0.0
                    )
                    ss = jnp.sum(dev * dev)
                    pop = func in ("stddev_pop", "var_pop")
                    var = ss / jnp.maximum(cf if pop else cf - 1.0, 1.0)
                    v = jnp.sqrt(var) if func.startswith("stddev") else var
                    m = c2 > (0 if pop else 1)
                elif func == "min":
                    v = jnp.min(
                        jnp.where(eff, values, groupby._type_max(values.dtype))
                    )
                    m = cnt > 0
                elif func == "max":
                    v = jnp.max(
                        jnp.where(eff, values, groupby._type_min(values.dtype))
                    )
                    m = cnt > 0
                else:  # first/last
                    idx = jnp.arange(pad_n, dtype=jnp.int32)
                    pick = (
                        jnp.argmin(jnp.where(valid, idx, pad_n))
                        if func == "first"
                        else jnp.argmax(jnp.where(valid, idx, -1))
                    )
                    v = values[pick]
                    # no valid row at all (e.g. filter removed everything
                    # from a lazy-count frame) -> NULL, not row-0 garbage
                    any_valid = jnp.any(valid)
                    m = (
                        any_valid
                        if mask is None
                        else (mask[pick] & any_valid)
                    )
                outs[f"a:{name}"] = _cast_agg_result(
                    jnp.asarray(v)[None], tp
                )
                if m is not None:
                    outs[f"am:{name}"] = jnp.asarray(m)[None]
            return outs

        prog_key = (
            "gagg",
            tuple(
                (n, f, None if a is None else a.__uuid__(), str(t))
                for n, f, a, t in typed_plans
            ),
            pad_n,
            tuple(sorted((distinct_args or {}).items())),
            expr_eval.dict_fingerprint(blocks),
        )
        outs = self._jit_cached(prog_key, _prog)(
            expr_eval.blocks_to_masked(blocks),
            dsegs,
            dfirsts,
            blocks.row_valid,
            _nrows_arg(blocks),
        )
        ndev = int(blocks.mesh.devices.size)
        out_pad = padded_len(1, ndev)
        out_cols: Dict[str, JaxColumn] = {}
        schema_fields = []
        for name, func, arg, tp in typed_plans:
            out_cols[name] = JaxColumn(
                tp,
                jax.device_put(
                    _pad_to(outs[f"a:{name}"], out_pad), sharding
                ),
                None
                if f"am:{name}" not in outs
                else jax.device_put(
                    _pad_to(outs[f"am:{name}"], out_pad), sharding
                ),
            )
            schema_fields.append(pa.field(name, tp))
        schema = Schema(schema_fields)
        if col_order is not None:
            schema = schema.extract(col_order)
            out_cols = {n: out_cols[n] for n in col_order}
        return JaxDataFrame(
            JaxBlocks(1, out_cols, blocks.mesh), schema
        )

    def _binned_packed_aggregate(
        self,
        jdf: JaxDataFrame,
        keys: List[str],
        typed_plans: List[Tuple[str, str, Any, pa.DataType]],
        bspec: "groupby.BinSpec",
        col_order: Optional[List[str]],
        sharding: Any,
        strategy: str,
        distinct_args: Optional[Dict[str, str]] = None,
    ) -> DataFrame:
        """The group-by hot path: ONE jitted program computing mixed-radix
        segment ids inline, ALL sum/avg/count reductions (float, exact-int
        and DISTINCT variants) packed into a single strategy kernel —
        one-hot matmul / bf16 matmul / packed scatter / sorted scatter,
        per the crossover selector — and key values decoded arithmetically
        from bin indices (gather-free). Zero host syncs on the matmul and
        scatter strategies; the group count stays a lazy device scalar.
        DISTINCT aggregates fold their first-occurrence-of-(keys, value)
        masks into the payloads, so they ride the same packed kernel."""
        blocks = jdf.blocks
        pad_n = blocks.padded_nrows
        dicts = expr_eval.dicts_of(blocks)
        ndev = int(blocks.mesh.devices.size)
        total = bspec.total
        out_pad = padded_len(total, ndev)
        key_dtypes = {k: blocks.columns[k].data.dtype for k in keys}
        distinct_args = distinct_args or {}
        plan_kinds = [
            "c" if (func == "count") else (
                "i"
                if self._packed_agg_kind(jdf, func, arg) == "int"
                else "f"
            )
            for _, func, arg, _ in typed_plans
        ]

        def _prog(
            mcols: Dict[str, Any],
            key_data: Dict[str, Any],
            key_masks: Dict[str, Any],
            dsegs_: Dict[str, Any],
            dfirsts_: Dict[str, Any],
            row_valid: Optional[Any],
            nrows_s: Any,
        ) -> Dict[str, Any]:
            valid = groupby.materialize_validity(row_valid, pad_n, nrows_s)
            seg = groupby.inline_seg(
                bspec, key_data, key_masks, valid
            )
            float_payloads: List[Any] = []
            count_payloads: List[Any] = [valid]  # occupancy rides along
            int_payloads: List[Any] = []
            # payload DEDUP: kernel work scales with the payload count, and
            # real queries repeat payloads constantly — SUM(v)+AVG(v) share
            # one float payload; COUNT(*) / any unmasked count IS the
            # occupancy vector (slot 0). A sum+avg+count query drops from
            # 6 payload rows to 2 — a ~3x work cut on the hot path.
            # DISTINCT variants key separately (their effective mask also
            # carries the first-occurrence dedup mask).
            fkeys: Dict[str, int] = {}
            ckeys: Dict[str, int] = {"__valid__": 0}
            ikeys: Dict[str, int] = {}
            slots: List[Tuple[str, Any]] = []  # (kind, index-key) per plan

            def _count_slot(key: str, vec: Any) -> int:
                if key not in ckeys:
                    count_payloads.append(vec)
                    ckeys[key] = len(count_payloads) - 1
                return ckeys[key]

            def _float_slot(key: str, vec: Any) -> int:
                if key not in fkeys:
                    float_payloads.append(vec)
                    fkeys[key] = len(float_payloads) - 1
                return fkeys[key]

            def _int_slot(key: str, vec: Any) -> int:
                if key not in ikeys:
                    int_payloads.append(vec)
                    ikeys[key] = len(int_payloads) - 1
                return ikeys[key]

            for (name, func, arg, tp), kind in zip(typed_plans, plan_kinds):
                if func == "count" and arg is None:
                    slots.append(("c", 0))  # COUNT(*) == occupancy
                    continue
                akey = arg.__uuid__()
                dname = distinct_args.get(name)
                values, mask = expr_eval.eval_expr(mcols, arg, pad_n, dicts)
                mask = _apply_distinct_mask(
                    dsegs_, dfirsts_, name, pad_n, mask
                )
                parts = ([f"m:{akey}"] if mask is not None else [])
                if dname is not None:
                    parts.append(f"d:{dname}")
                eff_key = "|".join(parts) or "__valid__"
                eff = valid if mask is None else (mask & valid)
                if func == "count":
                    slots.append(("c", _count_slot(eff_key, eff)))
                    continue
                ci = _count_slot(eff_key, eff)
                pkey = f"{akey}|{eff_key}"
                if kind == "i":
                    ii = _int_slot(pkey, jnp.where(eff, values, 0))
                    slots.append(("i", (ii, ci)))
                else:
                    fi = _float_slot(pkey, jnp.where(eff, values, 0))
                    slots.append(("f", (fi, ci)))
            f_sums, c_sums, i_sums = groupby.segment_sums(
                float_payloads, count_payloads, seg, total,
                strategy=strategy, int_payloads=int_payloads,
            )
            occupied = c_sums[0] > 0
            outs: Dict[str, Any] = {
                "_occupied": _pad_to(occupied, out_pad),
                "_num": jnp.sum(occupied.astype(jnp.int32)),
            }
            decoded = groupby.decode_bin_keys(bspec, key_dtypes)
            for k in keys:
                kv, km = decoded[k]
                outs[f"k:{k}"] = _pad_to(kv, out_pad)
                if km is not None:
                    outs[f"km:{k}"] = _pad_to(km, out_pad)
            for (name, func, arg, tp), slot in zip(typed_plans, slots):
                kind, idx = slot
                if kind == "c":
                    outs[f"a:{name}"] = _pad_to(
                        _cast_agg_result(c_sums[idx], tp), out_pad
                    )
                    continue
                si, ci = idx
                tot = i_sums[si] if kind == "i" else f_sums[si]
                cnt = c_sums[ci]
                if func == "sum":
                    v = tot
                else:  # avg/mean
                    v = tot / jnp.maximum(cnt, 1)
                outs[f"a:{name}"] = _pad_to(_cast_agg_result(v, tp), out_pad)
                outs[f"am:{name}"] = _pad_to(cnt > 0, out_pad)
            return outs

        prog_key = (
            "bagg",
            tuple(
                (n, f, None if a is None else a.__uuid__(), str(t))
                for n, f, a, t in typed_plans
            ),
            bspec,
            pad_n,
            strategy,
            tuple(sorted(distinct_args.items())),
            expr_eval.dict_fingerprint(blocks),
        )
        self._count_strategy(strategy)
        dsegs, dfirsts = _distinct_factorize(blocks, keys, distinct_args)
        key_data = {k: blocks.columns[k].data for k in keys}
        key_masks = {
            k: blocks.columns[k].mask
            for k in keys
            if blocks.columns[k].mask is not None
        }
        outs = self._jit_cached(prog_key, _prog)(
            expr_eval.blocks_to_masked(blocks),
            key_data,
            key_masks,
            dsegs,
            dfirsts,
            blocks.row_valid,
            _nrows_arg(blocks),
        )
        out_cols: Dict[str, JaxColumn] = {}
        schema_fields = [jdf.schema[k] for k in keys]
        for k in keys:
            src_col = blocks.columns[k]
            out_cols[k] = JaxColumn(
                src_col.pa_type,
                jax.device_put(outs[f"k:{k}"], sharding),
                None
                if f"km:{k}" not in outs
                else jax.device_put(outs[f"km:{k}"], sharding),
                src_col.dictionary,
                src_col.stats,
            )
        for name, func, arg, tp in typed_plans:
            out_cols[name] = JaxColumn(
                tp,
                jax.device_put(outs[f"a:{name}"], sharding),
                None
                if f"am:{name}" not in outs
                else jax.device_put(outs[f"am:{name}"], sharding),
            )
            schema_fields.append(pa.field(name, tp))
        schema = Schema(schema_fields)
        if col_order is not None:
            schema = schema.extract(col_order)
            out_cols = {n: out_cols[n] for n in col_order}
        return JaxDataFrame(
            JaxBlocks(
                None,
                out_cols,
                blocks.mesh,
                row_valid=jax.device_put(outs["_occupied"], sharding),
                nrows_dev=outs["_num"],
            ),
            schema,
        )


def _devices_from_conf(conf: Any) -> Optional[List[Any]]:
    """Parse ``fugue.jax.devices`` — a comma-separated list of indices
    into ``jax.devices()`` — into the device slice the engine's mesh
    should cover. Empty/unset means all devices. Out-of-range or
    non-integer indices raise: a replica silently grabbing the whole pod
    because of a typo'd slice would defeat the isolation the knob
    exists for."""
    from fugue_tpu.constants import FUGUE_CONF_JAX_DEVICES

    raw = str(conf.get(FUGUE_CONF_JAX_DEVICES, "") or "").strip()
    if raw == "":
        return None
    devs = jax.devices()
    out: List[Any] = []
    for part in raw.split(","):
        part = part.strip()
        if part == "":
            continue
        try:
            idx = int(part)
        except ValueError:
            raise ValueError(
                f"{FUGUE_CONF_JAX_DEVICES}={raw!r}: {part!r} is not an "
                "integer device index"
            )
        if not (0 <= idx < len(devs)):
            raise ValueError(
                f"{FUGUE_CONF_JAX_DEVICES}={raw!r}: index {idx} is out of "
                f"range for {len(devs)} visible devices"
            )
        out.append(devs[idx])
    if len(out) == 0:
        return None
    return out


def _host_mesh_like(mesh: Any) -> Any:
    """A mesh over the CPU backend for the host placement tier. When the
    default platform already is CPU (tests, CPU-only boxes) the accelerator
    mesh IS the host mesh — return the same object so placement becomes a
    no-op and mesh identity checks stay cheap."""
    try:
        cpu_devs = jax.devices("cpu")
    except RuntimeError:  # pragma: no cover - no CPU backend registered
        return mesh
    if list(mesh.devices.flat) == list(cpu_devs[: mesh.devices.size]) and (
        mesh.devices.size == len(cpu_devs)
    ):
        return mesh
    return make_mesh(list(cpu_devs))


def blocks_with_columns(
    blocks: JaxBlocks, new_cols: Dict[str, JaxColumn]
) -> JaxBlocks:
    """New column set, same row membership (lazy state passes through)."""
    return JaxBlocks(
        blocks._nrows,
        new_cols,
        blocks.mesh,
        row_valid=blocks.row_valid,
        nrows_dev=blocks._nrows_dev,
    )


# the aggregate families the device paths accept (one definition so the
# can-select gate and the plan builder cannot drift apart)
_DEVICE_AGGS = (
    "min", "max", "sum", "avg", "mean", "count", "first", "last",
    "median", *VARIANCE_FUNCS,
)
_DEVICE_DISTINCT_AGGS = (
    "min", "max", "sum", "avg", "mean", "count", "median",
    *VARIANCE_FUNCS,
)


def _distinct_factorize(
    blocks: JaxBlocks, keys: List[str], distinct_args: Optional[Dict[str, str]]
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Per-(keys, value) factorizations backing DISTINCT aggregates —
    shared by the keyed and global aggregate paths."""
    dsegs: Dict[str, Any] = {}
    dfirsts: Dict[str, Any] = {}
    for name, argname in (distinct_args or {}).items():
        fr2 = groupby.factorize_keys(blocks, keys + [argname])
        dsegs[name] = fr2.seg
        dfirsts[name] = fr2.first_idx
    return dsegs, dfirsts


def _apply_distinct_mask(
    dsegs: Dict[str, Any],
    dfirsts: Dict[str, Any],
    name: str,
    pad_n: int,
    mask: Optional[Any],
) -> Optional[Any]:
    """Fold the first-occurrence-of-(keys, value) mask into an agg's
    validity mask (inside a traced program)."""
    if name not in dsegs:
        return mask
    pos_ = jnp.arange(pad_n, dtype=jnp.int32)
    dmask = dfirsts[name][dsegs[name]] == pos_
    return dmask if mask is None else (mask & dmask)


def _nrows_arg(blocks: JaxBlocks) -> Any:
    """Row count as a program argument with no host sync: a known int (jax
    converts per call, no retrace) or the pending device scalar."""
    if blocks._nrows is not None:
        return np.int32(blocks._nrows)
    if blocks._nrows_dev is not None:
        return blocks._nrows_dev
    return np.int32(-1)  # row_valid is set; programs use the mask directly


class _StringDictUnavailable(Exception):
    """A compiled map produced string-typed output codes with no decode
    table (neither passthrough-inherited nor fn-returned) — the caller
    falls back to the host map path."""


def _is_dict_key(k: str) -> bool:
    return k.startswith("_") and k.endswith("_dict")


def _path_leaf_key(path: Any) -> Optional[str]:
    """Dict key of a pytree leaf path like (DictKey('k'),) -> 'k'."""
    if len(path) == 0:
        return None
    last = path[-1]
    key = getattr(last, "key", None)
    return key if isinstance(key, str) else None


def _as_aval(x: Any) -> Any:
    """Shape/dtype signature of a program argument (for AOT re-lowering in
    program_cost_analysis; keeps no reference to the data)."""
    return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))


def _pad_to(v: jnp.ndarray, target: int) -> jnp.ndarray:
    n = int(v.shape[0])
    if n == target:
        return v
    return jnp.concatenate([v, jnp.zeros((target - n,), dtype=v.dtype)])


def _cast_agg_result(v: jnp.ndarray, tp: pa.DataType) -> jnp.ndarray:
    target = tp.to_pandas_dtype()
    try:
        return v.astype(target)
    except Exception:  # pragma: no cover
        return v
