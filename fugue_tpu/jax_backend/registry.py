"""Register the jax backend (pattern parity: fugue_spark/registry.py:26-131):
engine names, inference from JaxDataFrame inputs, and the jax-annotated
transformer param that unlocks the compiled whole-shard map path."""

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from fugue_tpu.dataframe.function_wrapper import (
    AnnotatedParam,
    fugue_annotated_param,
)
from fugue_tpu.dataframe.dataframe import as_fugue_df
from fugue_tpu.execution.factory import (
    infer_execution_engine,
    register_execution_engine,
)
from fugue_tpu.jax_backend.dataframe import JaxDataFrame
from fugue_tpu.jax_backend.execution_engine import JaxExecutionEngine
from fugue_tpu.schema import Schema


@fugue_annotated_param(Dict[str, jax.Array])
class JaxArraysParam(AnnotatedParam):
    """Transformer param ``Dict[str, jax.Array]``: on JaxExecutionEngine the
    function runs compiled over whole mesh-sharded columns (with
    ``_segment_ids``/``_num_segments`` when partitioned); on host engines it
    receives the partition's columns as jax arrays."""

    code = "j"
    format_hint = "jax"

    def to_input(self, df: Any, ctx: Dict[str, Any]) -> Any:
        # contract: jax transformers see NUMERIC/bool columns (strings and
        # nested types don't exist on device; use a pandas transformer there).
        # The ABI matches the compiled whole-shard path (JaxMapEngine.
        # _compiled_map): ``_row_valid`` / ``_nrows`` / ``_segment_ids`` /
        # ``_num_segments`` are always present so a transformer written to
        # the documented contract runs unmodified on host engines — here each
        # call is exactly one logical partition, i.e. one segment.
        pdf = df.as_pandas()
        res: Dict[str, Any] = {}
        for c in pdf.columns:
            np_col = pdf[c].to_numpy()
            if np_col.dtype.kind in "biuf":
                res[str(c)] = jnp.asarray(np_col)
        n = len(pdf)
        res["_nrows"] = jnp.int32(n)
        res["_row_valid"] = jnp.ones((n,), dtype=bool)
        res["_segment_ids"] = jnp.zeros((n,), dtype=jnp.int32)
        res["_num_segments"] = 1
        return res

    def to_output_df(self, output: Any, schema: Schema, ctx: Dict[str, Any]) -> Any:
        import pandas as pd

        from fugue_tpu.dataframe import PandasDataFrame

        n = int(output.get("_nrows", -1))
        data = {}
        for f in schema.fields:
            arr = np.asarray(output[f.name])
            data[f.name] = arr if n < 0 else arr[:n]
        return PandasDataFrame(pd.DataFrame(data), schema)


def _register() -> None:
    register_execution_engine(
        "jax", lambda conf, **kwargs: JaxExecutionEngine(conf, **kwargs)
    )
    register_execution_engine(
        "tpu", lambda conf, **kwargs: JaxExecutionEngine(conf, **kwargs)
    )

    @infer_execution_engine.candidate(
        lambda objs: any(isinstance(o, JaxDataFrame) for o in objs)
    )
    def _infer_jax(objs: List[Any]) -> Any:
        return "jax"

    @as_fugue_df.candidate(lambda df, **kw: isinstance(df, JaxDataFrame))
    def _jax_as_fugue(df: JaxDataFrame, **kwargs: Any) -> JaxDataFrame:
        return df

    from fugue_tpu.dataframe.api import get_native_as_df

    @get_native_as_df.candidate(lambda df: isinstance(df, JaxDataFrame))
    def _jax_native(df: JaxDataFrame) -> JaxDataFrame:
        # the backend IS jax: JaxDataFrame is its native frame (unlike spark
        # where .native unwraps to a third-party object)
        return df


_register()
