"""Column-algebra evaluation on device: masked jnp arrays, Kleene logic.

The JAX lowering of the same expression tree the pandas evaluator interprets
(BASELINE: "FugueSQL group-by aggregates lower to segment_sum/segment_max
scans on device") — select/filter/assign run as jit-compiled elementwise
programs over mesh-sharded columns; XLA fuses the chain into the surrounding
ops (HBM-bandwidth-friendly: one pass).

String columns participate through their dictionary encoding: predicates
(=, <>, <, <=, >, >=, LIKE, IN-as-OR) are resolved against a shared
lexicographic vocabulary built on the host from the SMALL dictionaries,
then executed as int32 lookup-table gathers + numeric compares on device
(the dictionaries never leave the host; only code arrays ride the mesh).
Because the lookup tables are baked into traced programs as constants,
jit cache keys at the call sites must include ``dict_fingerprint``.
"""

import re
from typing import Any, Dict, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from fugue_tpu.column.expressions import (
    ColumnExpr,
    _BinaryOpExpr,
    _FuncExpr,
    _LitColumnExpr,
    _NamedColumnExpr,
    _UnaryOpExpr,
)
from fugue_tpu.column.pandas_eval import like_pattern_to_regex
from fugue_tpu.jax_backend.blocks import JaxBlocks, JaxColumn
from fugue_tpu.utils.assertion import assert_or_throw

# a masked value: (values, mask) — mask None means all-valid
Masked = Tuple[jnp.ndarray, Optional[jnp.ndarray]]


class _Str(NamedTuple):
    """A dictionary-encoded string value during device evaluation."""

    codes: jnp.ndarray
    mask: Optional[jnp.ndarray]
    dictionary: np.ndarray  # host-resident decode table


class _StrLit(NamedTuple):
    value: str


_Value = Union[Masked, _Str, _StrLit]

_CMP_OPS = ("==", "!=", "<", "<=", ">", ">=")


def _valid(m: Masked) -> jnp.ndarray:
    v, mask = m
    if mask is None:
        return jnp.ones(v.shape, dtype=jnp.bool_)
    return mask


def eval_expr(
    cols: Dict[str, Masked],
    expr: ColumnExpr,
    nrows: int,
    dicts: Optional[Dict[str, np.ndarray]] = None,
) -> Masked:
    res = _eval(cols, expr, nrows, dicts or {})
    if isinstance(res, (_Str, _StrLit)):
        assert_or_throw(
            isinstance(res, _Str) and expr.as_type is None,
            NotImplementedError("string-valued expression on device"),
        )
        return (res.codes, res.mask)  # type: ignore[union-attr]
    if expr.as_type is not None:
        res = _cast(res, expr.as_type)
    return res


def _eval(
    cols: Dict[str, Masked],
    expr: ColumnExpr,
    nrows: int,
    dicts: Dict[str, np.ndarray],
) -> _Value:
    if isinstance(expr, _NamedColumnExpr):
        assert_or_throw(
            expr.name in cols, ValueError(f"{expr.name} not available on device")
        )
        v, m = cols[expr.name]
        if expr.name in dicts:
            return _Str(v, m, dicts[expr.name])
        return (v, m)
    if isinstance(expr, _LitColumnExpr):
        v = expr.value
        if v is None:
            return jnp.zeros((nrows,)), jnp.zeros((nrows,), dtype=jnp.bool_)
        if isinstance(v, str):
            return _StrLit(v)
        assert_or_throw(
            isinstance(v, (int, float, bool)),
            ValueError(f"literal {v!r} not supported on device"),
        )
        return jnp.full((nrows,), v), None
    if isinstance(expr, _UnaryOpExpr):
        inner = _eval(cols, expr.col, nrows, dicts)
        if expr.op in ("IS_NULL", "NOT_NULL"):
            if isinstance(inner, _StrLit):
                raise NotImplementedError("IS NULL on a string literal")
            if isinstance(inner, _Str):
                inner = (inner.codes, inner.mask)
            if expr.op == "IS_NULL":
                return (~_valid(inner)), None
            return _valid(inner), None
        if isinstance(inner, (_Str, _StrLit)):
            raise NotImplementedError(f"unary {expr.op} on strings")
        iv, im = inner
        if expr.op == "-":
            return -iv, im
        if expr.op == "~":
            return ~iv.astype(jnp.bool_), im
        raise NotImplementedError(f"unary {expr.op} on device")
    if isinstance(expr, _BinaryOpExpr):
        left = _eval(cols, expr.left, nrows, dicts)
        right = _eval(cols, expr.right, nrows, dicts)
        if isinstance(left, (_Str, _StrLit)) or isinstance(
            right, (_Str, _StrLit)
        ):
            return _str_compare(expr.op, left, right, nrows)
        return _binary(expr.op, left, right)
    if isinstance(expr, _FuncExpr) and not expr.is_aggregation:
        f = expr.func.lower()
        if f == "coalesce":
            raws = [_eval(cols, a, nrows, dicts) for a in expr.args]
            if any(isinstance(a, (_Str, _StrLit)) for a in raws):
                raise NotImplementedError("COALESCE over strings on device")
            args = [a for a in raws if isinstance(a, tuple)]
            out_v, _ = args[0]
            out_m = _valid(args[0])
            for a in args[1:]:
                av, _am = a
                out_v = jnp.where(out_m, out_v, av)
                out_m = out_m | _valid(a)
            return out_v, out_m
        if f == "like":
            operand = _eval(cols, expr.args[0], nrows, dicts)
            pat = expr.args[1]
            neg = expr.args[2]
            assert_or_throw(
                isinstance(operand, _Str)
                and isinstance(pat, _LitColumnExpr)
                and isinstance(pat.value, str)
                and isinstance(neg, _LitColumnExpr),
                NotImplementedError("LIKE needs a string column + literal"),
            )
            rx = re.compile(like_pattern_to_regex(pat.value))
            d = operand.dictionary
            lut = np.fromiter(
                (rx.fullmatch(str(x)) is not None for x in d),
                dtype=bool,
                count=len(d),
            )
            if len(lut) == 0:
                lut = np.zeros(1, dtype=bool)
            hit = jnp.asarray(lut)[
                jnp.clip(operand.codes, 0, len(lut) - 1)
            ]
            if neg.value:
                hit = ~hit
            return hit, operand.mask
        if f == "case_when":
            raws = [_eval(cols, a, nrows, dicts) for a in expr.args]
            if any(isinstance(a, (_Str, _StrLit)) for a in raws):
                raise NotImplementedError("string CASE branches on device")
            default = raws[-1]
            out_v, _ = default
            out_valid = _valid(default)
            # first-match-wins: apply branches in REVERSE so earlier
            # conditions overwrite later ones
            for i in range(len(raws) - 2, 0, -2):
                cond, val = raws[i - 1], raws[i]
                cv, _cm = cond
                match = cv.astype(jnp.bool_) & _valid(cond)
                vv, _vm = val
                out_v = jnp.where(match, vv, out_v)
                out_valid = jnp.where(match, _valid(val), out_valid)
            # a NULL-literal default is float64 zeros but contributes no
            # VALUES — don't let it promote int branches to float
            vtypes = [
                raws[i][0].dtype for i in range(1, len(raws) - 1, 2)
            ]
            last = expr.args[-1]
            if not (
                isinstance(last, _LitColumnExpr) and last.value is None
            ):
                vtypes.append(default[0].dtype)
            if vtypes:
                out_v = out_v.astype(jnp.result_type(*vtypes))
            return out_v, out_valid
        raise NotImplementedError(f"function {expr.func} on device")
    raise NotImplementedError(f"can't evaluate {expr} on device")


def _str_compare(op: str, left: _Value, right: _Value, nrows: int) -> Masked:
    """String comparison via a shared lexicographic vocabulary: each
    side's dictionary (or literal) maps to its rank in the union, then
    the compare runs numerically on device."""
    if op not in _CMP_OPS:
        raise NotImplementedError(f"binary {op} on strings")
    sides = (left, right)
    if not any(isinstance(s, _Str) for s in sides):
        raise NotImplementedError("literal-vs-literal string compare")
    parts = []
    for s in sides:
        if isinstance(s, _Str):
            parts.append(s.dictionary.astype(str))
        elif isinstance(s, _StrLit):
            parts.append(np.array([s.value], dtype=str))
        else:
            raise NotImplementedError("string vs non-string comparison")
    vocab = np.unique(np.concatenate([p.astype(str) for p in parts]))

    def _rank(s: _Value) -> Masked:
        if isinstance(s, _StrLit):
            r = int(np.searchsorted(vocab, s.value))
            return jnp.full((nrows,), r, dtype=jnp.int32), None
        assert isinstance(s, _Str)
        lut = np.searchsorted(vocab, s.dictionary.astype(str)).astype(
            np.int32
        )
        if len(lut) == 0:
            lut = np.zeros(1, dtype=np.int32)
        v = jnp.asarray(lut)[jnp.clip(s.codes, 0, len(lut) - 1)]
        return v, s.mask

    return _binary(op, _rank(left), _rank(right))


def _binary(op: str, left: Masked, right: Masked) -> Masked:
    lv, lm = left
    rv, rm = right
    if op in ("&", "|"):
        la, ra = lv.astype(jnp.bool_), rv.astype(jnp.bool_)
        lvalid, rvalid = _valid(left), _valid(right)
        lf, rf = la & lvalid, ra & rvalid  # null -> False-filled
        if op == "&":
            value = lf & rf
            valid = (lvalid & rvalid) | (lvalid & ~la) | (rvalid & ~ra)
        else:
            value = lf | rf
            valid = (lvalid & rvalid) | (lvalid & la) | (rvalid & ra)
        return value, valid
    both = None
    if lm is not None or rm is not None:
        both = _valid(left) & _valid(right)
    if op == "==":
        return lv == rv, both
    if op == "!=":
        return lv != rv, both
    if op == "<":
        return lv < rv, both
    if op == "<=":
        return lv <= rv, both
    if op == ">":
        return lv > rv, both
    if op == ">=":
        return lv >= rv, both
    if op == "+":
        return lv + rv, both
    if op == "-":
        return lv - rv, both
    if op == "*":
        return lv * rv, both
    if op == "/":
        return jnp.true_divide(lv, rv), both
    raise NotImplementedError(f"binary {op} on device")


def _cast(m: Masked, tp: pa.DataType) -> Masked:
    v, mask = m
    if pa.types.is_floating(tp):
        dtype = tp.to_pandas_dtype()
        return v.astype(dtype), mask
    if pa.types.is_integer(tp):
        return v.astype(tp.to_pandas_dtype()), mask
    if pa.types.is_boolean(tp):
        return v.astype(jnp.bool_), mask
    raise NotImplementedError(f"device cast to {tp}")


def blocks_to_masked(blocks: JaxBlocks) -> Dict[str, Masked]:
    res: Dict[str, Masked] = {}
    for name, col in blocks.columns.items():
        if col.on_device:
            res[name] = (col.data, col.mask)
    return res


def dicts_of(blocks: JaxBlocks) -> Dict[str, np.ndarray]:
    """Decode tables of the device-resident string columns (host side)."""
    return {
        name: col.dictionary
        for name, col in blocks.columns.items()
        if col.on_device and col.is_string
    }


def dict_fingerprint(blocks: JaxBlocks) -> Tuple[Any, ...]:
    """A stable key component for jit caches of programs that bake
    string-dictionary lookup tables in as constants: same expression +
    same fingerprint => identical program."""
    out = []
    for name in sorted(blocks.columns):
        col = blocks.columns[name]
        if col.on_device and col.is_string:
            fp = getattr(col, "_dict_fp", None)
            if fp is None:
                fp = hash("\x00".join(str(x) for x in col.dictionary))
                col._dict_fp = fp  # type: ignore[attr-defined]
            out.append((name, len(col.dictionary), fp))
    return tuple(out)


def can_eval_on_device(expr: ColumnExpr, blocks: JaxBlocks) -> bool:
    """Whether the whole expression tree references only device columns
    and supported ops. String-KINDED results are only allowed for bare
    column references (the caller re-attaches the dictionary); string
    subtrees under comparisons/LIKE always lower."""
    try:
        kind = _check(expr, blocks)
    except NotImplementedError:
        return False
    if kind == "num":
        return True
    return (
        kind == "str"
        and isinstance(expr, _NamedColumnExpr)
        and expr.as_type is None
    )


def is_string_result(expr: ColumnExpr, blocks: JaxBlocks) -> bool:
    try:
        return _check(expr, blocks) != "num"
    except NotImplementedError:
        return False


def _check(expr: ColumnExpr, blocks: JaxBlocks) -> str:
    """Kind inference mirroring ``_eval`` exactly: returns "num", "str"
    (dictionary column) or "strlit"; raises NotImplementedError for
    anything ``_eval`` would reject."""
    if isinstance(expr, _NamedColumnExpr):
        col = blocks.columns.get(expr.name)
        if col is None or not col.on_device:
            raise NotImplementedError(expr.name)
        return "str" if col.is_string else "num"
    if isinstance(expr, _LitColumnExpr):
        if isinstance(expr.value, str):
            return "strlit"
        if expr.value is not None and not isinstance(
            expr.value, (int, float, bool)
        ):
            raise NotImplementedError(str(expr.value))
        return "num"
    if isinstance(expr, _UnaryOpExpr):
        k = _check(expr.col, blocks)
        if expr.op in ("IS_NULL", "NOT_NULL"):
            if k == "strlit":
                raise NotImplementedError("IS NULL on a string literal")
            return "num"
        if expr.op in ("-", "~"):
            if k != "num":
                raise NotImplementedError(f"unary {expr.op} on strings")
            return "num"
        raise NotImplementedError(expr.op)
    if isinstance(expr, _BinaryOpExpr):
        lk = _check(expr.left, blocks)
        rk = _check(expr.right, blocks)
        if lk == "num" and rk == "num":
            return "num"
        if expr.op in _CMP_OPS and "num" not in (lk, rk) and "str" in (
            lk, rk
        ):
            return "num"
        raise NotImplementedError(f"binary {expr.op} on {lk}/{rk}")
    if isinstance(expr, _FuncExpr) and not expr.is_aggregation:
        f = expr.func.lower()
        if f == "coalesce":
            for a in expr.args:
                if _check(a, blocks) != "num":
                    raise NotImplementedError("COALESCE over strings")
            return "num"
        if f == "like":
            if _check(expr.args[0], blocks) != "str":
                raise NotImplementedError("LIKE needs a string column")
            if not (
                isinstance(expr.args[1], _LitColumnExpr)
                and isinstance(expr.args[1].value, str)
            ):
                raise NotImplementedError("LIKE needs a literal pattern")
            return "num"
        if f == "case_when":
            for a in expr.args:
                if _check(a, blocks) != "num":
                    raise NotImplementedError("string CASE branches")
            return "num"
        raise NotImplementedError(expr.func)
    raise NotImplementedError(str(expr))
