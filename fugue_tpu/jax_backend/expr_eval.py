"""Column-algebra evaluation on device: masked jnp arrays, Kleene logic.

The JAX lowering of the same expression tree the pandas evaluator interprets
(BASELINE: "FugueSQL group-by aggregates lower to segment_sum/segment_max
scans on device") — select/filter/assign run as jit-compiled elementwise
programs over mesh-sharded columns; XLA fuses the chain into the surrounding
ops (HBM-bandwidth-friendly: one pass).

String columns participate through their dictionary encoding: predicates
(=, <>, <, <=, >, >=, LIKE, IN-as-OR) are resolved against a shared
lexicographic vocabulary built on the host from the SMALL dictionaries,
then executed as int32 lookup-table gathers + numeric compares on device
(the dictionaries never leave the host; only code arrays ride the mesh).
Because the lookup tables are baked into traced programs as constants,
jit cache keys at the call sites must include ``dict_fingerprint``.
"""

from typing import Any, Dict, List, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from fugue_tpu.column.expressions import (
    ColumnExpr,
    _BinaryOpExpr,
    _FuncExpr,
    _LitColumnExpr,
    _NamedColumnExpr,
    _UnaryOpExpr,
)
from fugue_tpu.column.pandas_eval import compile_like_regex
from fugue_tpu.jax_backend.blocks import JaxBlocks, JaxColumn
from fugue_tpu.utils.assertion import assert_or_throw

# a masked value: (values, mask) — mask None means all-valid
Masked = Tuple[jnp.ndarray, Optional[jnp.ndarray]]


class _Str(NamedTuple):
    """A dictionary-encoded string value during device evaluation."""

    codes: jnp.ndarray
    mask: Optional[jnp.ndarray]
    dictionary: np.ndarray  # host-resident decode table


class _StrLit(NamedTuple):
    value: str


_Value = Union[Masked, _Str, _StrLit]

_CMP_OPS = ("==", "!=", "<", "<=", ">", ">=")

# caps for host-built pairwise-dictionary tables (dynamic LIKE LUTs,
# composed CONCAT dictionaries): beyond this the host work/memory stops
# being "proportional to the dictionaries" and the host runner wins
_MAX_PAIR_LUT = 1 << 20
_MAX_COMPOSED_DICT = 1 << 18


def _like_literal(operand: "_Str", pattern: str, negated: bool) -> Masked:
    """LIKE against one literal pattern: a 1D dictionary LUT + gather.
    The LUT rows come from the SAME anchored regex helper the host
    evaluators use, so device and host can never diverge on values like
    a trailing newline (ADVICE r5 #3)."""
    rx = compile_like_regex(pattern)
    d = operand.dictionary
    lut = np.fromiter(
        (rx.fullmatch(str(x)) is not None for x in d),
        dtype=bool,
        count=len(d),
    )
    if len(lut) == 0:
        lut = np.zeros(1, dtype=bool)
    hit = jnp.asarray(lut)[jnp.clip(operand.codes, 0, len(lut) - 1)]
    if negated:
        hit = ~hit
    return hit, operand.mask


def _valid(m: Masked) -> jnp.ndarray:
    v, mask = m
    if mask is None:
        return jnp.ones(v.shape, dtype=jnp.bool_)
    return mask


def eval_expr(
    cols: Dict[str, Masked],
    expr: ColumnExpr,
    nrows: int,
    dicts: Optional[Dict[str, np.ndarray]] = None,
) -> Masked:
    res = _eval(cols, expr, nrows, dicts or {})
    if isinstance(res, (_Str, _StrLit)):
        assert_or_throw(
            isinstance(res, _Str) and expr.as_type is None,
            NotImplementedError("string-valued expression on device"),
        )
        return (res.codes, res.mask)  # type: ignore[union-attr]
    if expr.as_type is not None:
        res = _cast(res, expr.as_type)
    return res


def _eval(
    cols: Dict[str, Masked],
    expr: ColumnExpr,
    nrows: int,
    dicts: Dict[str, np.ndarray],
) -> _Value:
    if isinstance(expr, _NamedColumnExpr):
        assert_or_throw(
            expr.name in cols, ValueError(f"{expr.name} not available on device")
        )
        v, m = cols[expr.name]
        if expr.name in dicts:
            return _Str(v, m, dicts[expr.name])
        return (v, m)
    if isinstance(expr, _LitColumnExpr):
        v = expr.value
        if v is None:
            return jnp.zeros((nrows,)), jnp.zeros((nrows,), dtype=jnp.bool_)
        if isinstance(v, str):
            return _StrLit(v)
        assert_or_throw(
            isinstance(v, (int, float, bool)),
            ValueError(f"literal {v!r} not supported on device"),
        )
        return jnp.full((nrows,), v), None
    if isinstance(expr, _UnaryOpExpr):
        inner = _eval(cols, expr.col, nrows, dicts)
        if expr.op in ("IS_NULL", "NOT_NULL"):
            if isinstance(inner, _StrLit):
                raise NotImplementedError("IS NULL on a string literal")
            if isinstance(inner, _Str):
                inner = (inner.codes, inner.mask)
            if expr.op == "IS_NULL":
                return (~_valid(inner)), None
            return _valid(inner), None
        if isinstance(inner, (_Str, _StrLit)):
            raise NotImplementedError(f"unary {expr.op} on strings")
        iv, im = inner
        if expr.op == "-":
            return -iv, im
        if expr.op == "~":
            return ~iv.astype(jnp.bool_), im
        raise NotImplementedError(f"unary {expr.op} on device")
    if isinstance(expr, _BinaryOpExpr):
        left = _eval(cols, expr.left, nrows, dicts)
        right = _eval(cols, expr.right, nrows, dicts)
        if isinstance(left, (_Str, _StrLit)) or isinstance(
            right, (_Str, _StrLit)
        ):
            return _str_compare(expr.op, left, right, nrows)
        return _binary(expr.op, left, right)
    if isinstance(expr, _FuncExpr) and not expr.is_aggregation:
        f = expr.func.lower()
        if f == "coalesce":
            raws = [_eval(cols, a, nrows, dicts) for a in expr.args]
            if any(isinstance(a, (_Str, _StrLit)) for a in raws):
                raise NotImplementedError("COALESCE over strings on device")
            args = [a for a in raws if isinstance(a, tuple)]
            out_v, _ = args[0]
            out_m = _valid(args[0])
            for a in args[1:]:
                av, _am = a
                out_v = jnp.where(out_m, out_v, av)
                out_m = out_m | _valid(a)
            return out_v, out_m
        if f == "like":
            operand = _eval(cols, expr.args[0], nrows, dicts)
            pat = expr.args[1]
            neg = expr.args[2]
            assert_or_throw(
                isinstance(operand, _Str)
                and isinstance(neg, _LitColumnExpr),
                NotImplementedError("LIKE needs a string column"),
            )
            if isinstance(pat, _LitColumnExpr) and isinstance(
                pat.value, str
            ):
                return _like_literal(operand, pat.value, bool(neg.value))
            # dynamic pattern COLUMN: the result depends only on the
            # (value code, pattern code) pair — one host-built 2D LUT
            # over the two dictionaries, one device gather
            pv = _eval(cols, pat, nrows, dicts)
            if isinstance(pv, _StrLit):
                return _like_literal(operand, pv.value, bool(neg.value))
            assert_or_throw(
                isinstance(pv, _Str),
                NotImplementedError("LIKE pattern must be a string"),
            )
            do, dp = operand.dictionary, pv.dictionary
            no, np_ = max(len(do), 1), max(len(dp), 1)
            assert_or_throw(
                no * np_ <= _MAX_PAIR_LUT,
                NotImplementedError("dynamic LIKE dictionaries too large"),
            )
            lut2 = np.zeros((no, np_), dtype=bool)
            for j, p in enumerate(dp):
                rxp = compile_like_regex(str(p))
                lut2[: len(do), j] = np.fromiter(
                    (rxp.fullmatch(str(x)) is not None for x in do),
                    dtype=bool,
                    count=len(do),
                )
            flat = jnp.asarray(lut2.reshape(-1))
            oi = jnp.clip(operand.codes, 0, no - 1)
            pj = jnp.clip(pv.codes, 0, np_ - 1)
            hit = flat[oi * np_ + pj]
            if neg.value:
                hit = ~hit
            return hit, _and_masks(operand.mask, pv.mask)
        if f == "case_when":
            raws = [_eval(cols, a, nrows, dicts) for a in expr.args]
            if any(isinstance(a, (_Str, _StrLit)) for a in raws):
                raise NotImplementedError("string CASE branches on device")
            default = raws[-1]
            out_v, _ = default
            out_valid = _valid(default)
            # first-match-wins: apply branches in REVERSE so earlier
            # conditions overwrite later ones
            for i in range(len(raws) - 2, 0, -2):
                cond, val = raws[i - 1], raws[i]
                cv, _cm = cond
                match = cv.astype(jnp.bool_) & _valid(cond)
                vv, _vm = val
                out_v = jnp.where(match, vv, out_v)
                out_valid = jnp.where(match, _valid(val), out_valid)
            # a NULL-literal default is float64 zeros but contributes no
            # VALUES — don't let it promote int branches to float
            vtypes = [
                raws[i][0].dtype for i in range(1, len(raws) - 1, 2)
            ]
            last = expr.args[-1]
            if not (
                isinstance(last, _LitColumnExpr) and last.value is None
            ):
                vtypes.append(default[0].dtype)
            if vtypes:
                out_v = out_v.astype(jnp.result_type(*vtypes))
            return out_v, out_valid
        if f in _DEV_NUM_UNARY:
            v, m = _num_arg(_eval(cols, expr.args[0], nrows, dicts))
            out = _DEV_NUM_UNARY[f](v)
            if f in ("floor", "ceil", "ceiling", "sign"):
                # int64 result; NaN inputs must become NULL, not garbage
                valid = (
                    jnp.ones(out.shape, dtype=jnp.bool_) if m is None else m
                )
                if jnp.issubdtype(out.dtype, jnp.floating):
                    valid = valid & ~jnp.isnan(out)
                    out = jnp.where(valid, out, jnp.zeros_like(out))
                return out.astype(jnp.int64), valid
            return out, m
        if f == "round":
            v, m = _num_arg(_eval(cols, expr.args[0], nrows, dicts))
            digits = _dev_scalar(expr.args, 1, 0)
            return jnp.round(v.astype(jnp.float64), int(digits)), m
        if f in ("power", "pow"):
            lv, lm = _num_arg(_eval(cols, expr.args[0], nrows, dicts))
            rv, rm = _num_arg(_eval(cols, expr.args[1], nrows, dicts))
            m = _and_masks(lm, rm)
            return lv.astype(jnp.float64) ** rv.astype(jnp.float64), m
        if f == "mod":
            lv, lm = _num_arg(_eval(cols, expr.args[0], nrows, dicts))
            rv, rm = _num_arg(_eval(cols, expr.args[1], nrows, dicts))
            # truncated modulo (sign of dividend), matching the host
            # runners; x % 0 is NULL
            m = _and_masks(lm, rm)
            nz = rv != 0
            m = nz if m is None else (m & nz)
            return jnp.fmod(lv, jnp.where(nz, rv, 1)), m
        if f == "nullif":
            a = _eval(cols, expr.args[0], nrows, dicts)
            b = _eval(cols, expr.args[1], nrows, dicts)
            if isinstance(a, (_Str, _StrLit)) or isinstance(
                b, (_Str, _StrLit)
            ):
                eqv, eqm = _str_compare("==", a, b, nrows)
                assert_or_throw(
                    isinstance(a, _Str),
                    NotImplementedError("NULLIF on a string literal"),
                )
                eq = eqv & (
                    jnp.ones((nrows,), jnp.bool_) if eqm is None else eqm
                )
                am = (
                    jnp.ones((nrows,), jnp.bool_)
                    if a.mask is None
                    else a.mask
                )
                return _Str(a.codes, am & ~eq, a.dictionary)
            av, am = a
            bv, bm = b
            eq = (av == bv) & _valid(a) & _valid(b)
            return av, _valid(a) & ~eq
        if f in ("if", "iif"):
            cond = _eval(cols, expr.args[0], nrows, dicts)
            yes = _eval(cols, expr.args[1], nrows, dicts)
            no = _eval(cols, expr.args[2], nrows, dicts)
            if any(isinstance(x, (_Str, _StrLit)) for x in (cond, yes, no)):
                raise NotImplementedError("string IF branches on device")
            cv, _cm = cond
            match = cv.astype(jnp.bool_) & _valid(cond)
            return (
                jnp.where(match, yes[0], no[0]),
                jnp.where(match, _valid(yes), _valid(no)),
            )
        if f in ("length", "len"):
            operand = _eval(cols, expr.args[0], nrows, dicts)
            assert_or_throw(
                isinstance(operand, _Str),
                NotImplementedError("LENGTH needs a string column"),
            )
            d = operand.dictionary
            lut = np.fromiter(
                (len(str(x)) for x in d), dtype=np.int64, count=len(d)
            )
            if len(lut) == 0:
                lut = np.zeros(1, dtype=np.int64)
            return (
                jnp.asarray(lut)[jnp.clip(operand.codes, 0, len(lut) - 1)],
                operand.mask,
            )
        if f in _DICT_TRANSFORMS or f in (
            "substring", "substr", "replace", "concat"
        ):
            return _dict_transform_eval(cols, expr, f, nrows, dicts)
        raise NotImplementedError(f"function {expr.func} on device")
    raise NotImplementedError(f"can't evaluate {expr} on device")


_DEV_NUM_UNARY: Dict[str, Any] = {
    "abs": jnp.abs,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "ceiling": jnp.ceil,
    "sqrt": jnp.sqrt,
    "exp": jnp.exp,
    "ln": jnp.log,
    "log": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "sign": jnp.sign,
}

_DICT_TRANSFORMS: Dict[str, Any] = {
    "upper": lambda x: x.upper(),
    "ucase": lambda x: x.upper(),
    "lower": lambda x: x.lower(),
    "lcase": lambda x: x.lower(),
    "trim": lambda x: x.strip(),
    "ltrim": lambda x: x.lstrip(),
    "rtrim": lambda x: x.rstrip(),
    "reverse": lambda x: x[::-1],
}


def _num_arg(v: _Value) -> Masked:
    if isinstance(v, (_Str, _StrLit)):
        raise NotImplementedError("numeric function over strings")
    return v


def _and_masks(
    a: Optional[jnp.ndarray], b: Optional[jnp.ndarray]
) -> Optional[jnp.ndarray]:
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _dev_scalar(args: Any, i: int, default: Any) -> Any:
    if i >= len(args):
        return default
    a = args[i]
    assert_or_throw(
        isinstance(a, _LitColumnExpr)
        and isinstance(a.value, (int, float, str)),
        NotImplementedError("scalar parameter must be a literal on device"),
    )
    return a.value


def _transformed_dictionary(f: str, args: Any, d: np.ndarray) -> np.ndarray:
    """The host-side dictionary transform for a string scalar function —
    codes are untouched, only the decode table changes."""
    sd = [str(x) for x in d]
    if f in _DICT_TRANSFORMS:
        fn = _DICT_TRANSFORMS[f]
        return np.array([fn(x) for x in sd], dtype=object)
    if f in ("substring", "substr"):
        start0 = max(int(_dev_scalar(args, 1, 1)) - 1, 0)
        if len(args) > 2:
            n = int(_dev_scalar(args, 2, 0))
            return np.array(
                [x[start0:start0 + n] for x in sd], dtype=object
            )
        return np.array([x[start0:] for x in sd], dtype=object)
    if f == "replace":
        old = str(_dev_scalar(args, 1, ""))
        new = str(_dev_scalar(args, 2, ""))
        return np.array([x.replace(old, new) for x in sd], dtype=object)
    raise NotImplementedError(f)  # pragma: no cover - callers gate


def _dict_transform_eval(
    cols: Dict[str, Masked],
    expr: "_FuncExpr",
    f: str,
    nrows: int,
    dicts: Dict[str, np.ndarray],
) -> _Value:
    """String scalar functions as pure dictionary rewrites: the codes and
    mask pass through, the decode table is transformed on the host."""
    if f == "concat":
        # any mix of string COLUMNS and literals. One column: the result
        # dictionary is prefix + entry + suffix. Multiple columns: the
        # result dictionary is the (capped) cross product of the column
        # dictionaries and the codes compose in mixed radix — still pure
        # dictionary rewriting, host work proportional to the product of
        # the dictionaries, zero extra device passes.
        parts = [_eval(cols, a, nrows, dicts) for a in expr.args]
        strs = [p for p in parts if isinstance(p, _Str)]
        if len(strs) == 0 and all(isinstance(p, _StrLit) for p in parts):
            return _StrLit("".join(p.value for p in parts))
        if not all(isinstance(p, (_Str, _StrLit)) for p in parts):
            raise NotImplementedError("CONCAT over non-string values")
        if len(strs) == 1:
            src = strs[0]
            idx = parts.index(src)
            pre = "".join(
                p.value for p in parts[:idx]  # type: ignore[union-attr]
            )
            post = "".join(
                p.value for p in parts[idx + 1:]  # type: ignore[union-attr]
            )
            nd = np.array(
                [pre + str(x) + post for x in src.dictionary], dtype=object
            )
            return _Str(src.codes, src.mask, nd)
        # codes in mixed radix, row-major over the columns in order —
        # matching _compose_concat_dictionary's enumeration exactly
        code: Any = None
        mask: Optional[jnp.ndarray] = None
        for p in strs:
            sz = max(len(p.dictionary), 1)
            c = jnp.clip(p.codes, 0, sz - 1)
            code = c if code is None else code * sz + c
            mask = _and_masks(mask, p.mask)
        tmpl = [
            p.value if isinstance(p, _StrLit) else None for p in parts
        ]
        nd = _compose_concat_dictionary(
            tmpl, [p.dictionary for p in strs]
        )
        return _Str(code, mask, nd)
    operand = _eval(cols, expr.args[0], nrows, dicts)
    assert_or_throw(
        isinstance(operand, _Str),
        NotImplementedError(f"{f} needs a string column"),
    )
    nd = _transformed_dictionary(f, expr.args, operand.dictionary)
    return _Str(operand.codes, operand.mask, nd)


def _str_compare(op: str, left: _Value, right: _Value, nrows: int) -> Masked:
    """String comparison via a shared lexicographic vocabulary: each
    side's dictionary (or literal) maps to its rank in the union, then
    the compare runs numerically on device."""
    if op not in _CMP_OPS:
        raise NotImplementedError(f"binary {op} on strings")
    sides = (left, right)
    if not any(isinstance(s, _Str) for s in sides):
        raise NotImplementedError("literal-vs-literal string compare")
    parts = []
    for s in sides:
        if isinstance(s, _Str):
            parts.append(s.dictionary.astype(str))
        elif isinstance(s, _StrLit):
            parts.append(np.array([s.value], dtype=str))
        else:
            raise NotImplementedError("string vs non-string comparison")
    vocab = np.unique(np.concatenate([p.astype(str) for p in parts]))

    def _rank(s: _Value) -> Masked:
        if isinstance(s, _StrLit):
            r = int(np.searchsorted(vocab, s.value))
            return jnp.full((nrows,), r, dtype=jnp.int32), None
        assert isinstance(s, _Str)
        lut = np.searchsorted(vocab, s.dictionary.astype(str)).astype(
            np.int32
        )
        if len(lut) == 0:
            lut = np.zeros(1, dtype=np.int32)
        v = jnp.asarray(lut)[jnp.clip(s.codes, 0, len(lut) - 1)]
        return v, s.mask

    return _binary(op, _rank(left), _rank(right))


def _binary(op: str, left: Masked, right: Masked) -> Masked:
    lv, lm = left
    rv, rm = right
    if op in ("&", "|"):
        la, ra = lv.astype(jnp.bool_), rv.astype(jnp.bool_)
        lvalid, rvalid = _valid(left), _valid(right)
        lf, rf = la & lvalid, ra & rvalid  # null -> False-filled
        if op == "&":
            value = lf & rf
            valid = (lvalid & rvalid) | (lvalid & ~la) | (rvalid & ~ra)
        else:
            value = lf | rf
            valid = (lvalid & rvalid) | (lvalid & la) | (rvalid & ra)
        return value, valid
    both = None
    if lm is not None or rm is not None:
        both = _valid(left) & _valid(right)
    if op == "==":
        return lv == rv, both
    if op == "!=":
        return lv != rv, both
    if op == "<":
        return lv < rv, both
    if op == "<=":
        return lv <= rv, both
    if op == ">":
        return lv > rv, both
    if op == ">=":
        return lv >= rv, both
    if op == "+":
        return lv + rv, both
    if op == "-":
        return lv - rv, both
    if op == "*":
        return lv * rv, both
    if op == "/":
        return jnp.true_divide(lv, rv), both
    raise NotImplementedError(f"binary {op} on device")


def _cast(m: Masked, tp: pa.DataType) -> Masked:
    v, mask = m
    if pa.types.is_floating(tp):
        dtype = tp.to_pandas_dtype()
        return v.astype(dtype), mask
    if pa.types.is_integer(tp):
        return v.astype(tp.to_pandas_dtype()), mask
    if pa.types.is_boolean(tp):
        return v.astype(jnp.bool_), mask
    raise NotImplementedError(f"device cast to {tp}")


def blocks_to_masked(blocks: JaxBlocks) -> Dict[str, Masked]:
    res: Dict[str, Masked] = {}
    for name, col in blocks.columns.items():
        if col.on_device:
            res[name] = (col.data, col.mask)
    return res


def canonicalize_string_column(
    data: jnp.ndarray, dictionary: np.ndarray
) -> Tuple[jnp.ndarray, np.ndarray]:
    """Re-encode codes when a TRANSFORMED decode table contains
    duplicate values (e.g. TRIM collapsing ``"a "`` and ``"a"``):
    code-identity operations — group-by, distinct, joins, sort ranks —
    require one code per distinct string."""
    if len(dictionary) == 0:
        return data, dictionary
    uniq, inverse = np.unique(dictionary.astype(str), return_inverse=True)
    if len(uniq) == len(dictionary):
        return data, dictionary
    lut = jnp.asarray(inverse.astype(np.int32))
    new = jnp.take(lut, jnp.clip(data, 0, len(dictionary) - 1))
    return new, uniq.astype(object)


def finalize_string_result(
    data: jnp.ndarray, dictionary: np.ndarray
) -> Tuple[jnp.ndarray, np.ndarray, Tuple[int, int]]:
    """Canonicalize a transformed string column and derive its code
    stats — the one shared attach path for computed string outputs."""
    data, dictionary = canonicalize_string_column(data, dictionary)
    return data, dictionary, (0, max(len(dictionary) - 1, 0))


def dicts_of(blocks: JaxBlocks) -> Dict[str, np.ndarray]:
    """Decode tables of the device-resident string columns (host side)."""
    return {
        name: col.dictionary
        for name, col in blocks.columns.items()
        if col.on_device and col.is_string
    }


def dict_fingerprint(blocks: JaxBlocks) -> Tuple[Any, ...]:
    """A stable key component for jit caches of programs that bake
    string-dictionary lookup tables in as constants: same expression +
    same fingerprint => identical program. Hashed with a DETERMINISTIC
    digest (not builtin ``hash``, which is salted per process) so the
    persistent executable cache recognizes the same dictionary across
    process restarts."""
    import hashlib

    out = []
    for name in sorted(blocks.columns):
        col = blocks.columns[name]
        if col.on_device and col.is_string:
            fp = getattr(col, "_dict_fp", None)
            if fp is None:
                digest = hashlib.blake2b(
                    "\x00".join(str(x) for x in col.dictionary).encode(),
                    digest_size=8,
                ).digest()
                fp = int.from_bytes(digest, "big")
                col._dict_fp = fp  # type: ignore[attr-defined]
            out.append((name, len(col.dictionary), fp))
    return tuple(out)


def can_eval_on_device(expr: ColumnExpr, blocks: JaxBlocks) -> bool:
    """Whether the whole expression tree references only device columns
    and supported ops. String-KINDED results are only allowed when the
    output decode table is statically known (bare refs and
    dictionary-transform chains — the caller re-attaches it via
    ``result_dictionary``); string subtrees under comparisons/LIKE
    always lower."""
    try:
        kind = _check(expr, blocks)
    except NotImplementedError:
        return False
    if kind == "num":
        return True
    return kind == "str" and expr.as_type is None and _dict_chain_ok(expr)


def _compose_concat_dictionary(
    tmpl: List[Optional[str]], dicts_: List[np.ndarray]
) -> np.ndarray:
    """The decode table of a multi-column CONCAT: the cross product of
    the column dictionaries (row-major over the columns in order —
    matching the mixed-radix code composition), with literal fragments
    interleaved per the template (None marks a column slot)."""
    import itertools

    total = 1
    for d in dicts_:
        total *= max(len(d), 1)
    assert_or_throw(
        total <= _MAX_COMPOSED_DICT,
        NotImplementedError("CONCAT dictionaries too large to compose"),
    )
    parts = list(tmpl)
    col_idx = [i for i, t in enumerate(parts) if t is None]
    nd = np.full(total, "", dtype=object)  # empty dicts: all-masked
    for flat, combo in enumerate(itertools.product(*dicts_)):
        for i, v in zip(col_idx, combo):
            parts[i] = str(v)
        nd[flat] = "".join(parts)  # type: ignore[arg-type]
    return nd


def _dict_chain_ok(expr: ColumnExpr) -> bool:
    """Structural mirror of ``_walk_dict`` with no dictionary work —
    ``can_eval_on_device`` uses it so the decode table is only built by
    the callers that actually need it."""
    if isinstance(expr, _NamedColumnExpr):
        return True
    if isinstance(expr, _FuncExpr):
        f = expr.func.lower()
        if f == "nullif":
            return _dict_chain_ok(expr.args[0])
        if f == "concat":
            subs = [
                a for a in expr.args if not isinstance(a, _LitColumnExpr)
            ]
            return len(subs) >= 1 and all(_dict_chain_ok(s) for s in subs)
        if f in _DICT_TRANSFORMS or f in ("substring", "substr", "replace"):
            return _dict_chain_ok(expr.args[0])
    return False


def result_dictionary(
    expr: ColumnExpr, blocks: JaxBlocks
) -> Optional[np.ndarray]:
    """The output decode table of a codes-preserving string expression
    (bare column refs and dictionary-transform chains: UPPER, TRIM,
    SUBSTRING, REPLACE, one-column CONCAT, string NULLIF); None when the
    expression is not such a chain."""
    try:
        if _check(expr, blocks) != "str":
            return None
        return _walk_dict(expr, blocks)
    except NotImplementedError:
        return None


def _walk_dict(expr: ColumnExpr, blocks: JaxBlocks) -> np.ndarray:
    if isinstance(expr, _NamedColumnExpr):
        col = blocks.columns[expr.name]
        assert col.dictionary is not None
        return col.dictionary
    if isinstance(expr, _FuncExpr):
        f = expr.func.lower()
        if f == "concat":
            str_idx = [
                i
                for i, a in enumerate(expr.args)
                if _check(a, blocks) == "str"
            ]
            if len(str_idx) == 1:
                src_i = str_idx[0]
                pre = "".join(
                    a.value  # type: ignore[union-attr]
                    for a in expr.args[:src_i]
                )
                post = "".join(
                    a.value  # type: ignore[union-attr]
                    for a in expr.args[src_i + 1:]
                )
                inner = _walk_dict(expr.args[src_i], blocks)
                return np.array(
                    [pre + str(x) + post for x in inner], dtype=object
                )
            # multi-column: composed cross-product dictionary, SAME
            # enumeration as _eval's mixed-radix code composition
            for i, a in enumerate(expr.args):
                if i not in str_idx and not (
                    isinstance(a, _LitColumnExpr)
                    and isinstance(a.value, str)
                ):
                    raise NotImplementedError("non-literal CONCAT filler")
            tmpl = [
                None if i in str_idx else a.value  # type: ignore[union-attr]
                for i, a in enumerate(expr.args)
            ]
            return _compose_concat_dictionary(
                tmpl, [_walk_dict(expr.args[i], blocks) for i in str_idx]
            )
        if f == "nullif":
            return _walk_dict(expr.args[0], blocks)
        return _transformed_dictionary(
            f, expr.args, _walk_dict(expr.args[0], blocks)
        )
    raise NotImplementedError(str(expr))


def is_string_result(expr: ColumnExpr, blocks: JaxBlocks) -> bool:
    try:
        return _check(expr, blocks) != "num"
    except NotImplementedError:
        return False


def _check(expr: ColumnExpr, blocks: JaxBlocks) -> str:
    """Kind inference mirroring ``_eval`` exactly: returns "num", "str"
    (dictionary column) or "strlit"; raises NotImplementedError for
    anything ``_eval`` would reject."""
    if isinstance(expr, _NamedColumnExpr):
        col = blocks.columns.get(expr.name)
        if col is None or not col.on_device:
            raise NotImplementedError(expr.name)
        return "str" if col.is_string else "num"
    if isinstance(expr, _LitColumnExpr):
        if isinstance(expr.value, str):
            return "strlit"
        if expr.value is not None and not isinstance(
            expr.value, (int, float, bool)
        ):
            raise NotImplementedError(str(expr.value))
        return "num"
    if isinstance(expr, _UnaryOpExpr):
        k = _check(expr.col, blocks)
        if expr.op in ("IS_NULL", "NOT_NULL"):
            if k == "strlit":
                raise NotImplementedError("IS NULL on a string literal")
            return "num"
        if expr.op in ("-", "~"):
            if k != "num":
                raise NotImplementedError(f"unary {expr.op} on strings")
            return "num"
        raise NotImplementedError(expr.op)
    if isinstance(expr, _BinaryOpExpr):
        lk = _check(expr.left, blocks)
        rk = _check(expr.right, blocks)
        if lk == "num" and rk == "num":
            return "num"
        if expr.op in _CMP_OPS and "num" not in (lk, rk) and "str" in (
            lk, rk
        ):
            return "num"
        raise NotImplementedError(f"binary {expr.op} on {lk}/{rk}")
    if isinstance(expr, _FuncExpr) and not expr.is_aggregation:
        f = expr.func.lower()
        if f == "coalesce":
            for a in expr.args:
                if _check(a, blocks) != "num":
                    raise NotImplementedError("COALESCE over strings")
            return "num"
        if f == "like":
            if _check(expr.args[0], blocks) != "str":
                raise NotImplementedError("LIKE needs a string column")
            if not (
                isinstance(expr.args[1], _LitColumnExpr)
                and isinstance(expr.args[1].value, str)
            ):
                # dynamic pattern: any string expression works (the
                # evaluator builds a pairwise-dictionary LUT, capped)
                if _check(expr.args[1], blocks) not in ("str", "strlit"):
                    raise NotImplementedError(
                        "LIKE pattern must be a string"
                    )
            return "num"
        if f == "case_when":
            for a in expr.args:
                if _check(a, blocks) != "num":
                    raise NotImplementedError("string CASE branches")
            return "num"
        if f in _DEV_NUM_UNARY:
            if _check(expr.args[0], blocks) != "num":
                raise NotImplementedError(f"{f} over strings")
            return "num"
        if f == "round":
            if _check(expr.args[0], blocks) != "num":
                raise NotImplementedError("ROUND over strings")
            _check_scalar_lit(expr.args, 1)
            return "num"
        if f in ("power", "pow", "mod"):
            if (
                _check(expr.args[0], blocks) != "num"
                or _check(expr.args[1], blocks) != "num"
            ):
                raise NotImplementedError(f"{f} over strings")
            return "num"
        if f == "nullif":
            lk = _check(expr.args[0], blocks)
            rk = _check(expr.args[1], blocks)
            if lk == "num" and rk == "num":
                return "num"
            if lk == "str" and rk in ("str", "strlit"):
                return "str"
            raise NotImplementedError(f"NULLIF on {lk}/{rk}")
        if f in ("if", "iif"):
            for a in expr.args:
                if _check(a, blocks) != "num":
                    raise NotImplementedError("string IF branches")
            return "num"
        if f in ("length", "len"):
            if _check(expr.args[0], blocks) != "str":
                raise NotImplementedError("LENGTH needs a string column")
            return "num"
        if f in _DICT_TRANSFORMS:
            if _check(expr.args[0], blocks) != "str":
                raise NotImplementedError(f"{f} needs a string column")
            return "str"
        if f in ("substring", "substr", "replace"):
            if _check(expr.args[0], blocks) != "str":
                raise NotImplementedError(f"{f} needs a string column")
            _check_scalar_lit(expr.args, 1)
            _check_scalar_lit(expr.args, 2)
            return "str"
        if f == "concat":
            kinds = [_check(a, blocks) for a in expr.args]
            if any(k == "num" for k in kinds):
                raise NotImplementedError("CONCAT of non-strings")
            if all(k == "strlit" for k in kinds):
                return "strlit"
            # one or more string columns: dictionary rewrite (multiple
            # columns compose a capped cross-product dictionary)
            return "str"
        raise NotImplementedError(expr.func)
    raise NotImplementedError(str(expr))


def _check_scalar_lit(args: Any, i: int) -> None:
    if i < len(args) and not (
        isinstance(args[i], _LitColumnExpr)
        and isinstance(args[i].value, (int, float, str))
    ):
        raise NotImplementedError("scalar parameter must be a literal")
