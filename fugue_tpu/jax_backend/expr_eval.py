"""Column-algebra evaluation on device: masked jnp arrays, Kleene logic.

The JAX lowering of the same expression tree the pandas evaluator interprets
(BASELINE: "FugueSQL group-by aggregates lower to segment_sum/segment_max
scans on device") — select/filter/assign run as jit-compiled elementwise
programs over mesh-sharded columns; XLA fuses the chain into the surrounding
ops (HBM-bandwidth-friendly: one pass)."""

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from fugue_tpu.column.expressions import (
    ColumnExpr,
    _BinaryOpExpr,
    _FuncExpr,
    _LitColumnExpr,
    _NamedColumnExpr,
    _UnaryOpExpr,
)
from fugue_tpu.jax_backend.blocks import JaxBlocks, JaxColumn
from fugue_tpu.utils.assertion import assert_or_throw

# a masked value: (values, mask) — mask None means all-valid
Masked = Tuple[jnp.ndarray, Optional[jnp.ndarray]]


def _valid(m: Masked) -> jnp.ndarray:
    v, mask = m
    if mask is None:
        return jnp.ones(v.shape, dtype=jnp.bool_)
    return mask


def eval_expr(cols: Dict[str, Masked], expr: ColumnExpr, nrows: int) -> Masked:
    res = _eval(cols, expr, nrows)
    if expr.as_type is not None:
        res = _cast(res, expr.as_type)
    return res


def _eval(cols: Dict[str, Masked], expr: ColumnExpr, nrows: int) -> Masked:
    if isinstance(expr, _NamedColumnExpr):
        assert_or_throw(
            expr.name in cols, ValueError(f"{expr.name} not available on device")
        )
        return cols[expr.name]
    if isinstance(expr, _LitColumnExpr):
        v = expr.value
        if v is None:
            return jnp.zeros((nrows,)), jnp.zeros((nrows,), dtype=jnp.bool_)
        assert_or_throw(
            isinstance(v, (int, float, bool)),
            ValueError(f"literal {v!r} not supported on device"),
        )
        return jnp.full((nrows,), v), None
    if isinstance(expr, _UnaryOpExpr):
        inner = _eval(cols, expr.col, nrows)
        iv, im = inner
        if expr.op == "IS_NULL":
            return (~_valid(inner)), None
        if expr.op == "NOT_NULL":
            return _valid(inner), None
        if expr.op == "-":
            return -iv, im
        if expr.op == "~":
            return ~iv.astype(jnp.bool_), im
        raise NotImplementedError(f"unary {expr.op} on device")
    if isinstance(expr, _BinaryOpExpr):
        left = _eval(cols, expr.left, nrows)
        right = _eval(cols, expr.right, nrows)
        return _binary(expr.op, left, right)
    if isinstance(expr, _FuncExpr) and not expr.is_aggregation:
        if expr.func.lower() == "coalesce":
            args = [_eval(cols, a, nrows) for a in expr.args]
            out_v, out_m = args[0]
            out_m = _valid(args[0])
            for a in args[1:]:
                av, am = a
                out_v = jnp.where(out_m, out_v, av)
                out_m = out_m | _valid(a)
            return out_v, out_m
        raise NotImplementedError(f"function {expr.func} on device")
    raise NotImplementedError(f"can't evaluate {expr} on device")


def _binary(op: str, left: Masked, right: Masked) -> Masked:
    lv, lm = left
    rv, rm = right
    if op in ("&", "|"):
        la, ra = lv.astype(jnp.bool_), rv.astype(jnp.bool_)
        lvalid, rvalid = _valid(left), _valid(right)
        lf, rf = la & lvalid, ra & rvalid  # null -> False-filled
        if op == "&":
            value = lf & rf
            valid = (lvalid & rvalid) | (lvalid & ~la) | (rvalid & ~ra)
        else:
            value = lf | rf
            valid = (lvalid & rvalid) | (lvalid & la) | (rvalid & ra)
        return value, valid
    both = None
    if lm is not None or rm is not None:
        both = _valid(left) & _valid(right)
    if op == "==":
        return lv == rv, both
    if op == "!=":
        return lv != rv, both
    if op == "<":
        return lv < rv, both
    if op == "<=":
        return lv <= rv, both
    if op == ">":
        return lv > rv, both
    if op == ">=":
        return lv >= rv, both
    if op == "+":
        return lv + rv, both
    if op == "-":
        return lv - rv, both
    if op == "*":
        return lv * rv, both
    if op == "/":
        return jnp.true_divide(lv, rv), both
    raise NotImplementedError(f"binary {op} on device")


def _cast(m: Masked, tp: pa.DataType) -> Masked:
    v, mask = m
    if pa.types.is_floating(tp):
        dtype = tp.to_pandas_dtype()
        return v.astype(dtype), mask
    if pa.types.is_integer(tp):
        return v.astype(tp.to_pandas_dtype()), mask
    if pa.types.is_boolean(tp):
        return v.astype(jnp.bool_), mask
    raise NotImplementedError(f"device cast to {tp}")


def blocks_to_masked(blocks: JaxBlocks) -> Dict[str, Masked]:
    res: Dict[str, Masked] = {}
    for name, col in blocks.columns.items():
        if col.on_device and not col.is_string:
            res[name] = (col.data, col.mask)
    return res


def can_eval_on_device(expr: ColumnExpr, blocks: JaxBlocks) -> bool:
    """Whether the whole expression tree references only device numeric
    columns and supported ops."""
    try:
        _check(expr, blocks)
        return True
    except NotImplementedError:
        return False


def _check(expr: ColumnExpr, blocks: JaxBlocks) -> None:
    if isinstance(expr, _NamedColumnExpr):
        col = blocks.columns.get(expr.name)
        if col is None or not col.on_device or col.is_string:
            raise NotImplementedError(expr.name)
        return
    if isinstance(expr, _LitColumnExpr):
        if expr.value is not None and not isinstance(expr.value, (int, float, bool)):
            raise NotImplementedError(str(expr.value))
        return
    if isinstance(expr, _UnaryOpExpr):
        if expr.op not in ("IS_NULL", "NOT_NULL", "-", "~"):
            raise NotImplementedError(expr.op)
        _check(expr.col, blocks)
        return
    if isinstance(expr, _BinaryOpExpr):
        _check(expr.left, blocks)
        _check(expr.right, blocks)
        return
    if isinstance(expr, _FuncExpr) and not expr.is_aggregation:
        if expr.func.lower() != "coalesce":
            raise NotImplementedError(expr.func)
        for a in expr.args:
            _check(a, blocks)
        return
    raise NotImplementedError(str(expr))
