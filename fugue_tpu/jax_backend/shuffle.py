"""All-to-all shuffle repartition: co-locate matching keys per device.

The row-sharded layout (blocks.py) places rows on devices by POSITION,
not by key: rows of one group-by segment (or one join key) are spread
over every shard, so a naive sharded segment reduction makes XLA insert
a full cross-device combine of the (num_segments,) partials — or worse,
gather the rows. This module is the classic distributed-relational
answer (Spark's exchange, the repartition before every hash join):
shuffle rows so that segment ``g`` lands wholly on device ``g % ndev``,
then reduce LOCALLY with zero cross-device traffic in the reduction
itself.

Mechanics (everything is shape-stable so the one-trace invariant and
the zero-recompile counters survive):

- Each device routes its ``L`` local rows by ``dest = seg % ndev``
  (invalid rows get a sentinel and travel nowhere), packs them into a
  padded ``(ndev, L)`` send buffer — per-device send COUNTS are data,
  the buffer shape is not — and exchanges buffers with one
  ``jax.lax.all_to_all`` over the ``"p"`` mesh axis inside
  ``shard_map``.
- Received chunks concatenate in SOURCE-device order and each source
  packs its rows in original order (stable sort by destination), so
  within any segment the shuffled row order equals the global row
  order — order-sensitive aggregates (first/last) stay exact.
- The local reduction runs on local segment ids ``seg // ndev`` over
  ``S_local = ceil(S / ndev)`` local segments; the per-device outputs
  concatenate to a ``(ndev * S_local,)`` array whose position
  ``d * S_local + l`` holds global segment ``l * ndev + d``. A STATIC
  permutation gather restores canonical segment order, so results are
  byte-identical to the unshuffled path.
- Collective/compute overlap: with ``overlap`` the segment space is
  split into key-range chunks; the trace issues chunk ``i+1``'s
  all-to-all before chunk ``i``'s reduction so XLA's latency-hiding
  scheduler runs the next shuffle behind the current reduction on
  accelerators with async collectives. Chunks own disjoint segment
  ranges, so merging is a static range select — no arithmetic combine,
  no accuracy terms.

The price of shape stability is a padded receive: every device
receives ``ndev`` chunks of ``L`` rows, an ``ndev``-fold row blowup
carried only through the (streaming, mask-aware) local reduction.
That is the standard padded-all-to-all tradeoff; the decision of WHEN
it pays lives in segtune.choose_shuffle (the devices-aware strategy
column), not here.

For COMBINABLE aggregates (count/sum/avg/min/max/first/last) the row
shuffle is overkill: :func:`preagg_segment_aggs` is the map-side
combine (Spark's partial aggregation before the exchange): each device
reduces its OWN rows into per-segment partials, one all-to-all
exchanges partials in reduce-scatter layout (device ``d`` receives
every source's partials for segment range ``[d*S_local, (d+1)*S_local)``),
and a tiny ``(ndev, S_local)`` combine finishes each segment. Traffic
is ``O(S * ndev)`` values instead of ``O(rows * ndev)`` — the asymptotic
win whenever ``S << rows``, which is the common group-by shape. Only
non-combinable aggregates (median, variance family) and true
materializing repartitions (:func:`shuffle_rows`) need the row path.
"""

import math
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35 exposes shard_map at the top level
    from jax import shard_map  # type: ignore
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore

from fugue_tpu.jax_backend import groupby

__all__ = [
    "PREAGG_FUNCS",
    "estimate_preagg_bytes",
    "estimate_shuffle_bytes",
    "grouped_sort",
    "local_segments",
    "preagg_ok",
    "preagg_segment_aggs",
    "preagg_segment_count",
    "sharded_cumsum",
    "sharded_expand_rows",
    "sharded_grouped_order",
    "shuffle_rows",
    "shuffled_segment_aggs",
    "shuffled_segment_count",
]


def sharded_cumsum(mesh: Optional[Mesh], x: Any) -> Any:
    """Prefix sum that stays fast on a sharded axis (trace-time building
    block). GSPMD's partitioning of ``jnp.cumsum`` over a sharded array
    degenerates into a serialized cross-device scan (measured: 800k i32
    rows, 2 forced host devices — 149 s vs 8 ms unsharded), which made
    every multi-device join pay for its start-offset scans. The classic
    two-level scan fixes it: each device cumsums its OWN chunk, one
    all-gather of the ``ndev`` chunk totals computes each device's
    exclusive offset, one streaming add applies it. On one device this
    is exactly ``jnp.cumsum``."""
    ndev = 1 if mesh is None else int(mesh.devices.size)
    if ndev <= 1:
        return jnp.cumsum(x)
    n = x.shape[0]
    pad = (-n) % ndev
    xp = jnp.pad(x, (0, pad)) if pad else x

    def _body(xl: Any) -> Any:
        local = jnp.cumsum(xl)
        totals = jax.lax.all_gather(local[-1], "p")  # (ndev,)
        k = jax.lax.axis_index("p")
        offset = jnp.sum(
            jnp.where(jnp.arange(ndev) < k, totals, 0),
            dtype=local.dtype,
        )
        return local + offset

    out = shard_map(
        _body, mesh=mesh, in_specs=P("p"), out_specs=P("p"),
        check_rep=False,
    )(xp)
    return out[:n] if pad else out


def _scatter_max_exchange(ndev: int, out_n: int, idx: Any, vals: Any) -> Any:
    """Shared kernel of the sharded scatter patterns below (call INSIDE a
    ``shard_map`` body): every device scatter-maxes its LOCAL
    ``(idx, vals)`` pairs into a full-size ``(out_n,)`` buffer (init
    ``-1``), then ONE all-to-all in reduce-scatter layout hands device
    ``d`` every source's partials for output chunk ``d`` and a streaming
    max combines them. Total scatter work stays O(n) across the mesh —
    GSPMD's own partitioning of the same scatter all-reduces ndev
    full-output partial copies instead (measured ndev-fold cost). Returns
    this device's combined ``(out_n // ndev,)`` chunk; slots no index
    hit hold ``-1``."""
    buf = jnp.full((out_n,), -1, jnp.int32).at[idx].max(vals, mode="drop")
    part = buf.reshape(ndev, out_n // ndev)
    ex = jax.lax.all_to_all(part, "p", split_axis=0, concat_axis=0)
    return jnp.max(ex, axis=0)


def sharded_expand_rows(mesh: Mesh, start: Any, out_n: int) -> Any:
    """Expansion row indices ``i[t] = index of the last start <= t`` for
    a SORTED (nondecreasing) ``start`` — the multi-device form of the
    scatter-marks + prefix-sum expansion (relational.expand_join). The
    single-device scatter+scan beats binary search there, but its GSPMD
    partitioning scatters into per-device copies of the FULL output and
    all-reduces them (ndev-fold work), and a per-chunk replicated
    scatter of ALL starts is O(p1 * ndev). Sharded: each device
    scatter-maxes only its OWN rows' ids at their start offsets, a
    reduce-scatter-layout all-to-all combines the partials per output
    chunk, and a local running max plus a scalar carry (all-gather of
    chunk maxima) finishes the prefix — ``cummax`` of scattered row ids
    IS ``cumsum(marks) - 1`` when starts are sorted (each row
    contributes exactly one mark, so the count of starts <= t minus one
    equals the largest row id with start <= t)."""
    ndev = int(mesh.devices.size)
    p1 = start.shape[0]
    pad = (-p1) % ndev
    st = start.astype(jnp.int32)
    if pad:
        # synthetic rows scatter at out_n -> dropped, never selected
        st = jnp.pad(st, (0, pad), constant_values=out_n)
    l1 = (p1 + pad) // ndev

    def _body(st_l: Any) -> Any:
        k = jax.lax.axis_index("p")
        ids = k.astype(jnp.int32) * l1 + jnp.arange(l1, dtype=jnp.int32)
        mine = _scatter_max_exchange(ndev, out_n, st_l, ids)
        run = jax.lax.cummax(mine)
        top = jax.lax.all_gather(run[-1], "p")  # (ndev,) chunk maxima
        carry = jnp.max(jnp.where(jnp.arange(ndev) < k, top, -1))
        return jnp.maximum(run, carry)

    body = shard_map(
        _body, mesh=mesh, in_specs=(P("p"),), out_specs=P("p"),
        check_rep=False,
    )
    return body(st)


def grouped_sort(seg: Any, s_hi: int, length: int) -> Tuple[Any, Any]:
    """Stable sort-by-segment as ONE value sort of a fused
    ``segment * length + row`` composite key — XLA CPU's value sort is
    ~5x the speed of the pair sort behind stable ``argsort`` (measured
    2.5ms vs 15.6ms at 50k rows), and the composite is stable by
    construction. ``seg`` values must lie in ``[0, s_hi]``. Returns
    ``(order, seg_sorted)``. Falls back to stable argsort when the
    composite cannot fit the widest available integer (x64 disabled and
    ``(s_hi + 1) * length`` past int32)."""
    if jax.config.jax_enable_x64:
        dt = jnp.int64
    elif (int(s_hi) + 1) * int(length) <= np.iinfo(np.int32).max:
        dt = jnp.int32
    else:  # pragma: no cover - engine always enables x64 (blocks.py)
        order = jnp.argsort(seg, stable=True).astype(jnp.int32)
        return order, seg[order]
    keys = seg.astype(dt) * length + jnp.arange(length, dtype=dt)
    ks = jnp.sort(keys)
    return (ks % length).astype(jnp.int32), (ks // length).astype(jnp.int32)


def sharded_grouped_order(
    mesh: Mesh, seg: Any, num_segments: int
) -> Tuple[Any, Any, Any]:
    """Fused grouped-by-segment metadata for ONE sharded segment vector:
    returns ``(counts, cstart, order)`` where ``counts[s]`` is the global
    row count of segment ``s``, ``cstart`` its exclusive prefix sum, and
    ``order[p]`` the row index at grouped output position ``p`` (segment
    ``s`` occupies positions ``cstart[s]..``; rows within a segment keep
    global row order) — the sharded replacement for ``segment_count`` +
    ``cumsum`` + ``argsort(seg, stable)``. GSPMD partitions that argsort
    by replicating the FULL sort onto every device (measured ~linear
    slowdown in device count); here each device stable-sorts only its
    LOCAL rows, and ONE all-gather of per-device partial segment counts
    feeds all three outputs (the count/cumsum/order pipeline would
    otherwise exchange the same partials three times: the map-side
    combine, the two-level scan, and the rank bases). The reduce-scatter
    max-combine (:func:`_scatter_max_exchange`) delivers the inverse
    permutation directly — no replicated work, no GSPMD scatter
    all-reduce. Positions of rows with ``seg >= num_segments`` are never
    emitted; uncovered output slots hold ``-1`` (callers mask those
    rows, and XLA's OOB gather clamp keeps the index harmless)."""
    ndev = int(mesh.devices.size)
    n = seg.shape[0]
    s_cap = max(int(num_segments), 1)
    L = n // ndev

    def _body(seg_: Any) -> Tuple[Any, Any, Any]:
        valid = seg_ < s_cap
        segc = jnp.where(valid, seg_, s_cap).astype(jnp.int32)
        order_l, s_sorted = grouped_sort(segc, s_cap, L)
        # rank within segment run: distance to the run's first slot (a
        # streaming cummax; binary search here costs log(L) gather
        # passes)
        t = jnp.arange(L, dtype=jnp.int32)
        is_start = jnp.concatenate(
            [jnp.ones((1,), bool), s_sorted[1:] != s_sorted[:-1]]
        )
        rank_sorted = t - jax.lax.cummax(jnp.where(is_start, t, 0))
        cnt = jax.ops.segment_sum(
            valid.astype(jnp.int32), segc, num_segments=s_cap + 1
        )[:s_cap]
        counts = jax.lax.all_gather(cnt, "p")  # (ndev, s_cap)
        c = jnp.sum(counts, axis=0)
        cstart_ = jnp.cumsum(c) - c
        k = jax.lax.axis_index("p")
        base = jnp.sum(
            jnp.where((jnp.arange(ndev) < k)[:, None], counts, 0), axis=0
        )
        sg = jnp.clip(s_sorted, 0, s_cap - 1)
        # global grouped position per SORTED slot (no inverse scatter
        # back to row order: the row ids travel with the sorted slots)
        posg = jnp.where(
            s_sorted < s_cap, cstart_[sg] + base[sg] + rank_sorted, n
        )
        rows_g = k.astype(jnp.int32) * L + order_l
        return c, cstart_, _scatter_max_exchange(ndev, n, posg, rows_g)

    body = shard_map(
        _body, mesh=mesh, in_specs=(P("p"),),
        out_specs=(P(), P(), P("p")), check_rep=False,
    )
    return body(seg.astype(jnp.int32))

#: Aggregates with an exact distributive/algebraic decomposition: a
#: per-device partial plus a tiny cross-device combine reproduces the
#: global result. median needs co-located raw values and the variance
#: family's two-pass form needs the global mean, so they stay on the
#: row shuffle.
PREAGG_FUNCS = frozenset(
    {"count", "sum", "avg", "mean", "min", "max", "first", "last"}
)


def preagg_ok(funcs: List[str]) -> bool:
    """True when EVERY aggregate in the plan set can ride the map-side
    combine (partial aggregation) path."""
    return all(f.lower() in PREAGG_FUNCS for f in funcs)


def local_segments(num_segments: int, ndev: int) -> int:
    """``S_local``: local segments per device after repartition."""
    return max(1, -(-max(num_segments, 1) // ndev))


def _canon_perm(num_segments: int, ndev: int) -> np.ndarray:
    """Static gather restoring canonical segment order: local output
    position ``d * S_local + l`` holds global segment ``l * ndev + d``,
    so ``canon[g] = (g % ndev) * S_local + g // ndev``."""
    s_local = local_segments(num_segments, ndev)
    g = np.arange(max(num_segments, 1), dtype=np.int32)
    return (g % ndev) * s_local + g // ndev


def estimate_shuffle_bytes(pad_n: int, ndev: int, payload_widths: int) -> int:
    """Static transported-byte estimate for the metrics surface: every
    device ships a full padded ``(ndev, L)`` buffer per transported
    array (seg codes: 4B, receive marker: 1B, plus the payload widths).
    ``payload_widths`` is the per-row byte sum of value/mask arrays."""
    return int(pad_n) * int(ndev) * (5 + int(payload_widths))


def estimate_preagg_bytes(
    num_segments: int, ndev: int, partial_widths: int
) -> int:
    """Static transported-byte estimate for the map-side-combine path:
    every device ships its full padded ``(ndev, S_local)`` partial table
    per partial array; ``partial_widths`` is the per-segment byte sum of
    the partial arrays (value + nonempty marker per aggregate)."""
    s_pad = local_segments(num_segments, ndev) * ndev
    return int(s_pad) * int(ndev) * int(partial_widths)


def _send(buf_rows: int, slot: Any, ok: Any, arr: Any) -> Any:
    """Scatter ``arr`` (already dest-sorted) into a flat send buffer of
    ``buf_rows`` slots; rows not being sent target an out-of-bounds slot
    and are dropped."""
    idx = jnp.where(ok, slot, buf_rows)
    return (
        jnp.zeros((buf_rows,), arr.dtype).at[idx].set(arr, mode="drop")
    )


def _exchange(ndev: int, buf: Any) -> Any:
    """One padded all-to-all: ``(ndev * L,)`` send buffer -> ``(ndev * L,)``
    receive buffer whose chunk ``i`` came from source device ``i``."""
    rows = buf.shape[0] // ndev
    out = jax.lax.all_to_all(
        buf.reshape(ndev, rows), "p", split_axis=0, concat_axis=0,
        tiled=False,
    )
    return out.reshape(-1)


def _shuffle_local(
    ndev: int,
    seg: Any,
    route: Any,
    payloads: List[Optional[Any]],
) -> Tuple[Any, Any, List[Optional[Any]]]:
    """Per-shard body: route local rows (``route`` True = participate)
    to device ``seg % ndev``. Returns (seg_sh, received_marker,
    payloads_sh), each ``(ndev * L,)``; ``received_marker`` is True on
    slots that carry a real row."""
    L = seg.shape[0]
    dest = jnp.where(route, seg % ndev, ndev).astype(jnp.int32)
    # stable: within one destination chunk rows keep original order
    order = jnp.argsort(dest, stable=True).astype(jnp.int32)
    d_sorted = dest[order]
    pos = jnp.arange(L, dtype=jnp.int32) - jnp.searchsorted(
        d_sorted, d_sorted, side="left"
    ).astype(jnp.int32)
    slot = jnp.clip(d_sorted, 0, ndev - 1) * L + pos
    ok = d_sorted < ndev
    buf_rows = ndev * L
    seg_sh = _exchange(ndev, _send(buf_rows, slot, ok, seg[order]))
    marker = _exchange(
        ndev,
        _send(
            buf_rows, slot, ok, jnp.ones((L,), jnp.uint8)[order]
        ),
    ).astype(jnp.bool_)
    outs: List[Optional[Any]] = []
    for p in payloads:
        if p is None:
            outs.append(None)
            continue
        v = p[order]
        if v.dtype == jnp.bool_:
            v = _exchange(
                ndev, _send(buf_rows, slot, ok, v.astype(jnp.uint8))
            ).astype(jnp.bool_)
        else:
            v = _exchange(ndev, _send(buf_rows, slot, ok, v))
        outs.append(v)
    return seg_sh, marker, outs


def shuffled_segment_aggs(
    mesh: Mesh,
    funcs: List[str],
    seg: Any,
    valid: Any,
    values: List[Optional[Any]],
    masks: List[Optional[Any]],
    num_segments: int,
    strategy: str = "scatter",
    overlap: bool = False,
) -> List[Tuple[Any, Optional[Any]]]:
    """Shuffle-repartitioned segment aggregation (trace-time building
    block; call INSIDE a jitted program whose row arrays are sharded on
    ``mesh``).

    For each ``funcs[i]`` computes the same result as
    ``groupby._segment_agg_impl(funcs[i], values[i], masks[i], seg,
    num_segments, valid, strategy)`` but with rows repartitioned so each
    device reduces only its own segments. ``values[i]`` may be None for
    ``count`` (nothing but the segment codes travels). Returns
    ``(value, mask)`` pairs of shape ``(num_segments,)`` in canonical
    segment order — byte-identical to the unshuffled path."""
    ndev = int(mesh.devices.size)
    S = max(int(num_segments), 1)
    s_local = local_segments(S, ndev)
    n_chunks = 2 if (overlap and S >= 2 * ndev) else 1
    # chunk boundaries on GLOBAL segment ids, aligned to ndev so each
    # chunk's local segment range is contiguous: seg g is in chunk
    # (g // ndev) >= split_local
    split_local = s_local // 2 if n_chunks == 2 else s_local
    n_payload = len(funcs)

    def _body(seg_: Any, valid_: Any, vals_: Any, masks_: Any) -> Any:
        chunk_outs: List[List[Tuple[Any, Optional[Any]]]] = []
        shuffled: List[Tuple[Any, Any, List[Optional[Any]]]] = []
        # issue EVERY chunk's all-to-all before the first reduction:
        # chunk i+1's shuffle is independent of chunk i's reduce, so
        # the latency-hiding scheduler overlaps them on hardware with
        # async collectives
        for c in range(n_chunks):
            if n_chunks == 1:
                route = valid_
            else:
                lseg = seg_ // ndev
                in_range = (
                    (lseg < split_local) if c == 0 else (lseg >= split_local)
                )
                route = valid_ & in_range
            payloads: List[Optional[Any]] = []
            for i in range(n_payload):
                payloads.append(vals_.get(i))
                payloads.append(masks_.get(i))
            shuffled.append(_shuffle_local(ndev, seg_, route, payloads))
        for c in range(n_chunks):
            seg_sh, marker, payloads_sh = shuffled[c]
            seg_loc = jnp.where(
                marker, seg_sh // ndev, s_local
            ).astype(jnp.int32)
            outs: List[Tuple[Any, Optional[Any]]] = []
            for i, func in enumerate(funcs):
                v_sh = payloads_sh[2 * i]
                m_sh = payloads_sh[2 * i + 1]
                if v_sh is None:  # count: only the marker matters
                    v_sh = jnp.zeros(marker.shape, jnp.int32)
                outs.append(
                    groupby._segment_agg_impl(
                        func, v_sh, m_sh, seg_loc, s_local, marker,
                        strategy=strategy,
                    )
                )
            chunk_outs.append(outs)
        if n_chunks == 1:
            merged = chunk_outs[0]
        else:
            # chunks own DISJOINT local segment ranges: merge is a
            # static range select, exact for every aggregate kind
            lidx = jnp.arange(s_local, dtype=jnp.int32)
            take1 = lidx >= split_local
            merged = []
            for (v0, m0), (v1, m1) in zip(chunk_outs[0], chunk_outs[1]):
                v = jnp.where(take1, v1, v0)
                if m0 is None and m1 is None:
                    m = None
                else:
                    z = jnp.zeros((s_local,), jnp.bool_)
                    m = jnp.where(
                        take1, z if m1 is None else m1,
                        z if m0 is None else m0,
                    )
                merged.append((v, m))
        flat: List[Any] = []
        for v, m in merged:
            flat.append(v)
            flat.append(jnp.zeros((0,), jnp.bool_) if m is None else m)
        return tuple(flat)

    vals_in = {i: v for i, v in enumerate(values) if v is not None}
    masks_in = {i: m for i, m in enumerate(masks) if m is not None}
    has_mask = [
        masks[i] is not None or funcs[i].lower() in ("first", "last")
        for i in range(n_payload)
    ]
    # first/last return a gathered mask only when the input had one;
    # every other func returns a validity mask. Compute the exact
    # out-mask presence the unshuffled path would produce:
    out_has_mask = []
    for i, func in enumerate(funcs):
        f = func.lower()
        if f == "count":
            out_has_mask.append(False)
        elif f in ("first", "last"):
            out_has_mask.append(masks[i] is not None)
        else:
            out_has_mask.append(True)
    body = shard_map(
        _body,
        mesh=mesh,
        in_specs=(P("p"), P("p"), P("p"), P("p")),
        out_specs=P("p"),
        check_rep=False,
    )
    flat = body(seg.astype(jnp.int32), valid, vals_in, masks_in)
    canon = jnp.asarray(_canon_perm(num_segments, ndev))
    results: List[Tuple[Any, Optional[Any]]] = []
    for i in range(n_payload):
        v_g = flat[2 * i][canon]
        m_flat = flat[2 * i + 1]
        m_g = m_flat[canon] if out_has_mask[i] else None
        results.append((v_g, m_g))
    return results


def shuffled_segment_count(
    mesh: Mesh,
    vec: Any,
    seg: Any,
    num_segments: int,
    strategy: str = "scatter",
) -> Any:
    """Shuffle-repartitioned drop-in for :func:`groupby.segment_count`
    (the join-side / window count shape): ``vec`` is the bool
    participation vector. Only segment codes + the receive marker
    travel."""
    (res,) = shuffled_segment_aggs(
        mesh,
        ["count"],
        seg,
        vec,
        [None],
        [None],
        num_segments,
        strategy=strategy,
    )
    v, _ = res
    return v


def _exchange_partials(ndev: int, part: Any) -> Any:
    """Reduce-scatter layout: each device's ``(S_pad,)`` partial table,
    viewed as ``(ndev, S_local)`` chunks, is exchanged so device ``d``
    receives row ``s`` = source ``s``'s partials for ``d``'s segment
    range. Bool partials transit as uint8 (all_to_all payload rule)."""
    s_local = part.shape[0] // ndev
    if part.dtype == jnp.bool_:
        out = jax.lax.all_to_all(
            part.astype(jnp.uint8).reshape(ndev, s_local),
            "p", split_axis=0, concat_axis=0, tiled=False,
        )
        return out.astype(jnp.bool_)
    return jax.lax.all_to_all(
        part.reshape(ndev, s_local), "p",
        split_axis=0, concat_axis=0, tiled=False,
    )


def preagg_segment_aggs(
    mesh: Mesh,
    funcs: List[str],
    seg: Any,
    valid: Any,
    values: List[Optional[Any]],
    masks: List[Optional[Any]],
    num_segments: int,
    strategy: str = "scatter",
) -> List[Tuple[Any, Optional[Any]]]:
    """Map-side combine (trace-time building block; call INSIDE a jitted
    program whose row arrays are sharded on ``mesh``): same contract and
    results as :func:`shuffled_segment_aggs`, but each device first
    reduces its OWN rows into per-segment partials and only the
    ``(ndev, S_local)`` partial tables cross the wire — ``O(S * ndev)``
    traffic instead of ``O(rows * ndev)``. Every func must be in
    :data:`PREAGG_FUNCS`.

    Per-aggregate decomposition (partials -> combine):

    - ``count``: partial counts -> sum
    - ``sum``: partial sums + nonempty markers -> sum / any
    - ``avg``: partial sums + partial counts -> sum, then one divide
      (averages themselves don't combine; their components do)
    - ``min``/``max``: identity-filled partial extrema -> min/max
    - ``first``/``last``: per-device candidate + has-rows marker; rows
      are position-sharded in device order, so the global first (last)
      is the candidate from the lowest (highest) device with rows
    """
    bad = [f for f in funcs if f.lower() not in PREAGG_FUNCS]
    if bad:
        raise ValueError(f"non-combinable aggregates for preagg: {bad}")
    ndev = int(mesh.devices.size)
    S = max(int(num_segments), 1)
    s_local = local_segments(S, ndev)
    s_pad = s_local * ndev
    n_payload = len(funcs)

    def _body(seg_: Any, valid_: Any, vals_: Any, masks_: Any) -> Any:
        partials: List[Tuple[str, List[Any]]] = []
        for i, func in enumerate(funcs):
            f = func.lower()
            if f == "mean":
                f = "avg"
            v = vals_.get(i)
            m = masks_.get(i)
            eff = valid_ if m is None else (m & valid_)
            if f == "count":
                cnt = groupby.segment_count(eff, seg_, s_pad, strategy)
                partials.append(("count", [cnt]))
            elif f == "sum":
                tot, ne = groupby._segment_agg_impl(
                    "sum", v, m, seg_, s_pad, valid_, strategy=strategy
                )
                partials.append(("sum", [tot, ne]))
            elif f == "avg":
                tot, _ = groupby._segment_agg_impl(
                    "sum", v, m, seg_, s_pad, valid_, strategy=strategy
                )
                cnt = groupby.segment_count(eff, seg_, s_pad, strategy)
                partials.append(("avg", [tot, cnt]))
            elif f in ("min", "max"):
                pv, ne = groupby._segment_agg_impl(
                    f, v, m, seg_, s_pad, valid_, strategy=strategy
                )
                partials.append((f, [pv, ne]))
            else:  # first / last: candidate value + has-valid-rows
                pv, pm = groupby._segment_agg_impl(
                    f, v, m, seg_, s_pad, valid_, strategy=strategy
                )
                has = jax.ops.segment_sum(
                    valid_.astype(jnp.int32), seg_, num_segments=s_pad
                ) > 0
                arrs = [pv, has]
                if pm is not None:
                    arrs.append(pm)
                partials.append((f, arrs))
        exchanged = [
            (tag, [_exchange_partials(ndev, a) for a in arrs])
            for tag, arrs in partials
        ]
        flat: List[Any] = []
        for i, (tag, R) in enumerate(exchanged):
            if tag == "count":
                v_o: Any = jnp.sum(R[0], axis=0)
                m_o: Optional[Any] = None
            elif tag == "sum":
                v_o = jnp.sum(R[0], axis=0)
                m_o = jnp.any(R[1], axis=0)
            elif tag == "avg":
                tot = jnp.sum(R[0], axis=0)
                cnt = jnp.sum(R[1], axis=0)
                av = tot / jnp.maximum(cnt, 1)
                dt = vals_[i].dtype
                v_o = av.astype(
                    jnp.float64 if dt == jnp.float64 else jnp.float32
                )
                m_o = cnt > 0
            elif tag == "min":
                v_o = jnp.min(R[0], axis=0)
                m_o = jnp.any(R[1], axis=0)
            elif tag == "max":
                v_o = jnp.max(R[0], axis=0)
                m_o = jnp.any(R[1], axis=0)
            else:  # first / last
                H = R[1]
                if tag == "first":
                    # argmax returns the FIRST max: lowest device with rows
                    src = jnp.argmax(H, axis=0)
                else:
                    src = (ndev - 1) - jnp.argmax(H[::-1], axis=0)
                v_o = jnp.take_along_axis(R[0], src[None, :], axis=0)[0]
                m_o = (
                    jnp.take_along_axis(R[2], src[None, :], axis=0)[0]
                    if len(R) > 2
                    else None
                )
            flat.append(v_o)
            flat.append(jnp.zeros((0,), jnp.bool_) if m_o is None else m_o)
        return tuple(flat)

    vals_in = {i: v for i, v in enumerate(values) if v is not None}
    masks_in = {i: m for i, m in enumerate(masks) if m is not None}
    out_has_mask = []
    for i, func in enumerate(funcs):
        f = func.lower()
        if f == "count":
            out_has_mask.append(False)
        elif f in ("first", "last"):
            out_has_mask.append(masks[i] is not None)
        else:
            out_has_mask.append(True)
    body = shard_map(
        _body,
        mesh=mesh,
        in_specs=(P("p"), P("p"), P("p"), P("p")),
        out_specs=P("p"),
        check_rep=False,
    )
    flat = body(seg.astype(jnp.int32), valid, vals_in, masks_in)
    # reduce-scatter layout is ALREADY canonical: global position
    # d * S_local + l IS global segment d * S_local + l
    results: List[Tuple[Any, Optional[Any]]] = []
    for i in range(n_payload):
        v_g = flat[2 * i][:S]
        m_g = flat[2 * i + 1][:S] if out_has_mask[i] else None
        results.append((v_g, m_g))
    return results


def preagg_segment_count(
    mesh: Mesh,
    vec: Any,
    seg: Any,
    num_segments: int,
    strategy: str = "scatter",
) -> Any:
    """Map-side-combine drop-in for :func:`groupby.segment_count` (the
    join-side / window count shape): each device counts its own rows,
    one ``(ndev, S_local)`` all-to-all, one sum."""
    (res,) = preagg_segment_aggs(
        mesh,
        ["count"],
        seg,
        vec,
        [None],
        [None],
        num_segments,
        strategy=strategy,
    )
    v, _ = res
    return v


def shuffle_rows(
    mesh: Mesh,
    seg: Any,
    valid: Any,
    arrays: Dict[str, Any],
) -> Tuple[Any, Any, Dict[str, Any]]:
    """The raw repartition primitive (trace-time): route every valid row
    to device ``seg % ndev``, returning ``(seg_sh, row_valid_sh,
    arrays_sh)`` with ``ndev * pad_n`` global rows (the padded receive).
    Used by relational.repartition_by_key to materialize a key
    co-located frame."""
    ndev = int(mesh.devices.size)
    names = sorted(arrays)

    def _body(seg_: Any, valid_: Any, arrs_: Any) -> Any:
        seg_sh, marker, outs = _shuffle_local(
            ndev, seg_, valid_, [arrs_[n] for n in names]
        )
        return (seg_sh, marker) + tuple(outs)

    body = shard_map(
        _body,
        mesh=mesh,
        in_specs=(P("p"), P("p"), P("p")),
        out_specs=P("p"),
        check_rep=False,
    )
    out = body(seg.astype(jnp.int32), valid, dict(arrays))
    seg_sh, marker = out[0], out[1]
    return seg_sh, marker, {n: out[2 + i] for i, n in enumerate(names)}
