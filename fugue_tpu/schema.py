"""Schema: an ordered, expression-parseable column schema backed by pyarrow.

Plays the role the reference delegates to ``triad.Schema`` (see reference
``fugue/dataframe/dataframe.py:29`` usage) but is built from scratch here:
a thin ordered mapping ``name -> pyarrow.DataType`` with a compact string
expression syntax::

    "a:int,b:str,c:[long],d:{x:double,y:str},e:<str,int>,f:datetime"

Supported type tokens (aliases in parens): bool(boolean), int8(byte),
int16(short), int32, int(=int64 alias long), uint8..uint64, float16,
float(float32), double(float64), str(string), bytes(binary), date,
datetime(timestamp, microsecond), null, decimal(p,s), [T] lists,
{name:T,...} structs, <K,V> maps.

Design note for TPU: the schema intentionally keeps pyarrow as the *host
boundary* type system; device blocks (fugue_tpu/jax_backend) map a subset of
these (numeric/bool/temporal + dictionary-encoded strings) onto jax dtypes.
"""

import re
from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

import pandas as pd
import pyarrow as pa

from fugue_tpu.utils.assertion import assert_or_throw

_SIMPLE_TYPES: Dict[str, pa.DataType] = {
    "null": pa.null(),
    "bool": pa.bool_(),
    "boolean": pa.bool_(),
    "int8": pa.int8(),
    "byte": pa.int8(),
    "int16": pa.int16(),
    "short": pa.int16(),
    "int32": pa.int32(),
    "int": pa.int32(),
    "int64": pa.int64(),
    "long": pa.int64(),
    "uint8": pa.uint8(),
    "ubyte": pa.uint8(),
    "uint16": pa.uint16(),
    "ushort": pa.uint16(),
    "uint32": pa.uint32(),
    "uint": pa.uint32(),
    "uint64": pa.uint64(),
    "ulong": pa.uint64(),
    "float16": pa.float16(),
    "float32": pa.float32(),
    "float": pa.float32(),
    "float64": pa.float64(),
    "double": pa.float64(),
    "string": pa.string(),
    "str": pa.string(),
    "binary": pa.binary(),
    "bytes": pa.binary(),
    "date": pa.date32(),
    "datetime": pa.timestamp("us"),
    "timestamp": pa.timestamp("us"),
}

# canonical (shortest, unambiguous) names for to-string conversion
_TYPE_TO_NAME: Dict[pa.DataType, str] = {
    pa.null(): "null",
    pa.bool_(): "bool",
    pa.int8(): "int8",
    pa.int16(): "int16",
    pa.int32(): "int",
    pa.int64(): "long",
    pa.uint8(): "uint8",
    pa.uint16(): "uint16",
    pa.uint32(): "uint32",
    pa.uint64(): "uint64",
    pa.float16(): "float16",
    pa.float32(): "float",
    pa.float64(): "double",
    pa.string(): "str",
    pa.binary(): "bytes",
    pa.date32(): "date",
}

_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def is_valid_column_name(name: str) -> bool:
    return isinstance(name, str) and _NAME_RE.match(name) is not None


def parse_type(expr: str) -> pa.DataType:
    """Parse a single type expression into a pyarrow DataType."""
    t, pos = _parse_type(expr, 0)
    assert_or_throw(pos == len(expr.strip()) or expr[pos:].strip() == "",
                    ValueError(f"invalid type expression {expr.rstrip(chr(0))!r}"))
    return t


def type_to_expr(tp: pa.DataType) -> str:
    """Canonical string name of a pyarrow type (inverse of :func:`parse_type`)."""
    if tp in _TYPE_TO_NAME:
        return _TYPE_TO_NAME[tp]
    if pa.types.is_timestamp(tp):
        if tp.tz is None and tp.unit == "us":
            return "datetime"
        tz = f",{tp.tz}" if tp.tz is not None else ""
        return f"timestamp({tp.unit}{tz})"
    if pa.types.is_decimal(tp):
        return f"decimal({tp.precision},{tp.scale})"
    if pa.types.is_list(tp) or pa.types.is_large_list(tp):
        return f"[{type_to_expr(tp.value_type)}]"
    if pa.types.is_map(tp):
        return f"<{type_to_expr(tp.key_type)},{type_to_expr(tp.item_type)}>"
    if pa.types.is_struct(tp):
        inner = ",".join(f"{f.name}:{type_to_expr(f.type)}" for f in tp)
        return "{" + inner + "}"
    if pa.types.is_large_string(tp):
        return "str"
    if pa.types.is_large_binary(tp):
        return "bytes"
    raise ValueError(f"unsupported type {tp}")


def _skip_ws(s: str, pos: int) -> int:
    while pos < len(s) and s[pos].isspace():
        pos += 1
    return pos


def _parse_name(s: str, pos: int) -> Tuple[str, int]:
    pos = _skip_ws(s, pos)
    if pos < len(s) and s[pos] == "`":
        end = s.find("`", pos + 1)
        assert_or_throw(end > pos, ValueError(f"unclosed backquote in {s.rstrip(chr(0))!r}"))
        return s[pos + 1 : end], end + 1
    m = re.match(r"[A-Za-z_][A-Za-z0-9_]*", s[pos:])
    assert_or_throw(
        m is not None, ValueError(f"invalid name at {s[pos:].rstrip(chr(0))!r}")
    )
    return m.group(0), pos + m.end()


def _parse_type(s: str, pos: int) -> Tuple[pa.DataType, int]:
    pos = _skip_ws(s, pos)
    assert_or_throw(pos < len(s), ValueError(f"empty type expression in {s.rstrip(chr(0))!r}"))
    ch = s[pos]
    if ch == "[":
        inner, pos = _parse_type(s, pos + 1)
        pos = _skip_ws(s, pos)
        assert_or_throw(pos < len(s) and s[pos] == "]", ValueError(f"expect ] in {s.rstrip(chr(0))!r}"))
        return pa.list_(inner), pos + 1
    if ch == "<":
        ktype, pos = _parse_type(s, pos + 1)
        pos = _skip_ws(s, pos)
        assert_or_throw(pos < len(s) and s[pos] == ",", ValueError(f"expect , in map {s.rstrip(chr(0))!r}"))
        vtype, pos = _parse_type(s, pos + 1)
        pos = _skip_ws(s, pos)
        assert_or_throw(pos < len(s) and s[pos] == ">", ValueError(f"expect > in {s.rstrip(chr(0))!r}"))
        return pa.map_(ktype, vtype), pos + 1
    if ch == "{":
        fields, pos = _parse_fields(s, pos + 1, "}")
        return pa.struct(fields), pos
    m = re.match(r"[A-Za-z_][A-Za-z0-9_]*", s[pos:])
    assert_or_throw(m is not None, ValueError(f"invalid type at {s[pos:].rstrip(chr(0))!r}"))
    name = m.group(0).lower()
    pos += m.end()
    if name == "decimal":
        pos = _skip_ws(s, pos)
        assert_or_throw(pos < len(s) and s[pos] == "(", ValueError("decimal needs (p,s)"))
        end = s.find(")", pos)
        assert_or_throw(end > 0, ValueError("decimal needs closing )"))
        parts = [p.strip() for p in s[pos + 1 : end].split(",")]
        prec = int(parts[0])
        scale = int(parts[1]) if len(parts) > 1 else 0
        return pa.decimal128(prec, scale), end + 1
    if name == "timestamp":
        pos2 = _skip_ws(s, pos)
        if pos2 < len(s) and s[pos2] == "(":
            end = s.find(")", pos2)
            assert_or_throw(end > 0, ValueError("timestamp needs closing )"))
            parts = [p.strip() for p in s[pos2 + 1 : end].split(",")]
            unit = parts[0]
            tz = parts[1] if len(parts) > 1 else None
            return pa.timestamp(unit, tz), end + 1
        return pa.timestamp("us"), pos
    assert_or_throw(name in _SIMPLE_TYPES, ValueError(f"unknown type {name!r}"))
    return _SIMPLE_TYPES[name], pos


def _parse_fields(s: str, pos: int, closing: str) -> Tuple[List[pa.Field], int]:
    fields: List[pa.Field] = []
    while True:
        pos = _skip_ws(s, pos)
        assert_or_throw(pos < len(s), ValueError(f"unclosed struct in {s.rstrip(chr(0))!r}"))
        if s[pos] == closing:
            return fields, pos + 1
        name, pos = _parse_name(s, pos)
        pos = _skip_ws(s, pos)
        assert_or_throw(pos < len(s) and s[pos] == ":", ValueError(f"expect : after {name}"))
        tp, pos = _parse_type(s, pos + 1)
        fields.append(pa.field(name, tp))
        pos = _skip_ws(s, pos)
        if pos < len(s) and s[pos] == ",":
            pos += 1


class Schema:
    """Ordered column schema. Construct from expression strings, pyarrow
    schemas/fields, pandas dataframes, dicts, tuples, or other Schemas;
    mix-and-match via ``Schema("a:int", other_schema, ("b", pa.int64()))``.
    """

    def __init__(self, *args: Any, **kwargs: Any):
        self._fields: Dict[str, pa.Field] = {}
        for a in args:
            self._append(a)
        for k, v in kwargs.items():
            self._append_field(pa.field(k, self._to_type(v)))

    # ---- construction helpers -------------------------------------------
    def _append(self, obj: Any) -> None:
        if obj is None:
            return
        if isinstance(obj, str):
            s = obj.strip()
            if s == "":
                return
            fields, pos = _parse_fields(s + "\0", 0, "\0")
            for f in fields:
                self._append_field(f)
        elif isinstance(obj, Schema):
            for f in obj.fields:
                self._append_field(f)
        elif isinstance(obj, pa.Schema):
            for f in obj:
                self._append_field(f)
        elif isinstance(obj, pa.Field):
            self._append_field(obj)
        elif isinstance(obj, pd.DataFrame):
            self._append(pa.Schema.from_pandas(obj, preserve_index=False))
        elif isinstance(obj, tuple) and len(obj) == 2:
            self._append_field(pa.field(obj[0], self._to_type(obj[1])))
        elif isinstance(obj, dict):
            for k, v in obj.items():
                self._append_field(pa.field(k, self._to_type(v)))
        elif isinstance(obj, Iterable):
            for x in obj:
                self._append(x)
        else:
            raise ValueError(f"can't build schema from {obj!r}")

    def _to_type(self, v: Any) -> pa.DataType:
        if isinstance(v, pa.DataType):
            return v
        if isinstance(v, str):
            return parse_type(v)
        raise ValueError(f"can't interpret {v!r} as a type")

    def _append_field(self, f: pa.Field) -> None:
        assert_or_throw(
            isinstance(f.name, str) and f.name != "" and not f.name.startswith("_#"),
            ValueError(f"invalid field name {f.name!r}"),
        )
        assert_or_throw(
            f.name not in self._fields, KeyError(f"duplicated field name {f.name}")
        )
        tp = f.type
        # normalize: large_string -> string, ns timestamps stay as-is
        if pa.types.is_large_string(tp):
            tp = pa.string()
        elif pa.types.is_large_binary(tp):
            tp = pa.binary()
        self._fields[f.name] = pa.field(f.name, tp)

    # ---- core accessors --------------------------------------------------
    @property
    def names(self) -> List[str]:
        return list(self._fields.keys())

    @property
    def fields(self) -> List[pa.Field]:
        return list(self._fields.values())

    @property
    def types(self) -> List[pa.DataType]:
        return [f.type for f in self._fields.values()]

    @property
    def pa_schema(self) -> pa.Schema:
        return pa.schema(self.fields)

    @property
    def pandas_dtype(self) -> Dict[str, Any]:
        return {f.name: f.type.to_pandas_dtype() for f in self.fields}

    def __len__(self) -> int:
        return len(self._fields)

    def __iter__(self):
        return iter(self._fields.keys())

    def __contains__(self, key: Any) -> bool:
        if isinstance(key, str):
            if "," in key or ":" in key:
                try:
                    other = Schema(key)
                except Exception:
                    return False
                return all(f.name in self._fields and self._fields[f.name].type == f.type
                           for f in other.fields)
            return key in self._fields
        if isinstance(key, pa.Field):
            return key.name in self._fields and self._fields[key.name].type == key.type
        if isinstance(key, Schema):
            return all(f in self for f in key.fields)
        if isinstance(key, Iterable):
            return all(k in self for k in key)
        return False

    def __getitem__(self, key: Union[str, int]) -> pa.Field:
        if isinstance(key, int):
            return self.fields[key]
        return self._fields[key]

    def index_of_key(self, key: str) -> int:
        for i, n in enumerate(self._fields.keys()):
            if n == key:
                return i
        raise KeyError(key)

    def get_type(self, key: str) -> pa.DataType:
        return self._fields[key].type

    # ---- comparisons -----------------------------------------------------
    def __eq__(self, other: Any) -> bool:
        if other is None:
            return False
        if not isinstance(other, Schema):
            try:
                other = Schema(other)
            except Exception:
                return False
        return self.names == other.names and all(
            a.type == b.type for a, b in zip(self.fields, other.fields)
        )

    def __ne__(self, other: Any) -> bool:
        return not self.__eq__(other)

    def __hash__(self) -> int:
        return hash(str(self))

    # ---- algebra ---------------------------------------------------------
    def __add__(self, other: Any) -> "Schema":
        return Schema(self, other)

    def __sub__(self, other: Any) -> "Schema":
        return self.exclude(other)

    def exclude(self, other: Any) -> "Schema":
        """Remove columns by name(s) (or schema whose names+types must match)."""
        names = self._to_names(other, require_type_match=True)
        return Schema([f for f in self.fields if f.name not in names])

    def remove(self, other: Any, ignore_type_mismatch: bool = True) -> "Schema":
        names = self._to_names(other, require_type_match=not ignore_type_mismatch)
        return Schema([f for f in self.fields if f.name not in names])

    def extract(self, other: Any, ignore_type_mismatch: bool = False) -> "Schema":
        """Select a subset (ordered as requested)."""
        names = self._to_names(other, require_type_match=not ignore_type_mismatch,
                               keep_order=True)
        return Schema([self._fields[n] for n in names if n in self._fields])

    def intersect(self, other: Any) -> "Schema":
        names = set(self._to_names(other, require_type_match=False))
        return Schema([f for f in self.fields if f.name in names])

    def union(self, other: Any, require_type_match: bool = False) -> "Schema":
        res = Schema(self)
        o = other if isinstance(other, Schema) else Schema(other)
        for f in o.fields:
            if f.name not in res._fields:
                res._fields[f.name] = f
            elif require_type_match:
                assert_or_throw(
                    res._fields[f.name].type == f.type,
                    ValueError(f"type mismatch on {f.name}"),
                )
        return res

    def rename(self, columns: Dict[str, str], ignore_missing: bool = False) -> "Schema":
        if not ignore_missing:
            for k in columns:
                assert_or_throw(k in self._fields, KeyError(f"{k} not in schema"))
        new_names = [columns.get(n, n) for n in self.names]
        assert_or_throw(
            len(set(new_names)) == len(new_names),
            ValueError(f"rename causes duplicated names {new_names}"),
        )
        return Schema([pa.field(nn, f.type) for nn, f in zip(new_names, self.fields)])

    def alter(self, subschema: Any) -> "Schema":
        """Return a new schema with types of the named subset changed."""
        if subschema is None:
            return Schema(self)
        sub = subschema if isinstance(subschema, Schema) else Schema(subschema)
        for n in sub.names:
            assert_or_throw(n in self._fields, KeyError(f"{n} not in schema"))
        return Schema(
            [sub[f.name] if f.name in sub._fields else f for f in self.fields]
        )

    def _to_names(
        self, other: Any, require_type_match: bool, keep_order: bool = False
    ) -> List[str]:
        if other is None:
            return []
        if isinstance(other, str) and ("," in other or ":" in other):
            other = Schema(other)
        if isinstance(other, str):
            return [other]
        if isinstance(other, Schema):
            for f in other.fields:
                if require_type_match and f.name in self._fields:
                    assert_or_throw(
                        self._fields[f.name].type == f.type,
                        ValueError(
                            f"type mismatch on {f.name}: "
                            f"{self._fields[f.name].type} vs {f.type}"
                        ),
                    )
            return other.names
        if isinstance(other, Iterable):
            res: List[str] = []
            for x in other:
                res.extend(self._to_names(x, require_type_match, keep_order))
            return res
        raise ValueError(f"can't interpret {other!r} as column names")

    # ---- representations -------------------------------------------------
    def __repr__(self) -> str:
        return str(self)

    def __str__(self) -> str:
        return ",".join(
            f"{self._quote(f.name)}:{type_to_expr(f.type)}" for f in self.fields
        )

    def _quote(self, name: str) -> str:
        return name if _NAME_RE.match(name) else f"`{name}`"

    def create_empty_pandas(self) -> pd.DataFrame:
        return self.pa_schema.empty_table().to_pandas()

    def create_empty_arrow(self) -> pa.Table:
        return self.pa_schema.empty_table()

    def assert_not_empty(self) -> "Schema":
        assert_or_throw(len(self) > 0, ValueError("schema is empty"))
        return self

    def transform(self, *args: Any, **kwargs: Any) -> "Schema":
        """Schema arithmetic used by transformers' schema hints: each arg can be
        a new schema expression, ``"*"`` (all input columns), ``"-col1,col2"``
        (exclusion) or ``"+a:int"`` (addition)."""
        res = Schema()
        for a in args:
            if isinstance(a, str):
                s = a.strip()
                if s == "*":
                    res += self
                    continue
                if s.startswith("-"):
                    res = res.remove([x.strip() for x in s[1:].split(",") if x.strip()])
                    continue
                if s.startswith("+"):
                    res += s[1:]
                    continue
            res += a
        if len(kwargs) > 0:
            res += Schema(**kwargs)
        return res
