"""The lake table format: URIs, manifests, per-column statistics.

A lake table is a directory on any ``engine.fs`` backend::

    <root>/data/part-<uuid>-<seq>.parquet   immutable data files
    <root>/_meta/manifest-<version>.json    the commit log (one per snapshot)
    <root>/_meta/_head.json                 head-version HINT (best effort)

Each ``manifest-<V>.json`` is a complete snapshot description: the
current field list (stable integer field ids — the rename/widen anchor)
and every live data file with per-column stats (min/max, null count,
distinct estimate) plus row/byte counts. The MANIFEST CHAIN is the
truth: writing ``manifest-(V+1).json`` through the fs layer's
fail-if-exists CAS *is* the commit point, so of N racing writers exactly
one owns version V+1 and the losers re-read the new head and retry.
``_head.json`` is only a probe hint — it may lag, never lead.

Snapshots are immutable by construction (data files are never rewritten
in place, manifests are write-once), which is what makes ``AS OF``
reads deterministic and result-cacheable.

URI scheme: ``lake://<underlying-path-or-URI>[?version=N|timestamp=T]``
— e.g. ``lake:///warehouse/events``, ``lake://memory://tables/t1?version=3``.
The prefix is stripped before any fs call; the remainder is the table
root on whatever backend it names.
"""

import json
from typing import Any, Dict, List, Optional, Tuple

import pyarrow as pa

from fugue_tpu.schema import parse_type, type_to_expr
from fugue_tpu.utils.assertion import assert_or_throw

LAKE_URI_PREFIX = "lake://"

#: manifest file name pattern (zero-padded so name order == version order)
MANIFEST_FMT = "manifest-%010d.json"
HEAD_FILE = "_head.json"
META_DIR = "_meta"
DATA_DIR = "data"


class LakeError(Exception):
    """Base class for lake-format errors."""


class LakeCommitConflict(LakeError):
    """An optimistic commit lost the CAS on its manifest slot more times
    than the retry budget allows. Classified TRANSIENT by the workflow
    fault classifier (the fix is re-read head + retry, not a traceback)."""


class LakeCompactionConflict(LakeError):
    """A concurrent overwrite/compaction removed files this compaction
    meant to rewrite; the plan is stale and must be rebuilt from the
    new head."""


class LakeIntegrityError(LakeError):
    """A data file's bytes no longer match the sha256 its manifest
    recorded at commit time (bit rot, truncation or tampering). Raised
    on scan only when ``fugue.lake.verify`` is enabled; the read fails —
    silently returning corrupt rows is never an option."""


def is_lake_uri(path: Any) -> bool:
    return isinstance(path, str) and path.startswith(LAKE_URI_PREFIX)


def parse_lake_uri(uri: str) -> Tuple[str, Dict[str, Any]]:
    """``"lake://memory://t/x?version=3"`` ->
    ``("memory://t/x", {"version": 3})``. Recognized query keys:
    ``version`` (int) and ``timestamp`` (float epoch seconds)."""
    assert_or_throw(is_lake_uri(uri), ValueError(f"not a lake URI: {uri!r}"))
    rest = uri[len(LAKE_URI_PREFIX):]
    params: Dict[str, Any] = {}
    if "?" in rest:
        rest, qs = rest.split("?", 1)
        for part in qs.split("&"):
            if not part:
                continue
            key, _, value = part.partition("=")
            if key == "version":
                params["version"] = int(value)
            elif key == "timestamp":
                params["timestamp"] = float(value)
            else:
                raise ValueError(
                    f"unknown lake URI query key {key!r} in {uri!r} "
                    "(expected version=N or timestamp=T)"
                )
    assert_or_throw(
        rest.strip() != "", ValueError(f"empty table path in {uri!r}")
    )
    return rest, params


def format_lake_uri(table_uri: str, version: Optional[int] = None) -> str:
    """The canonical pinned form: ``lake://<root>?version=N``."""
    base = f"{LAKE_URI_PREFIX}{table_uri}"
    return base if version is None else f"{base}?version={int(version)}"


# ---- fields & schema evolution ---------------------------------------------

class LakeField:
    """One table column: a STABLE integer id plus the current name and
    type. Renames change ``name`` under the same id; widenings change
    ``type``; data files map ids to the name/type they were written
    with, so old snapshots resolve old files forever."""

    def __init__(self, field_id: int, name: str, type_expr: str):
        self.id = int(field_id)
        self.name = str(name)
        self.type_expr = str(type_expr)

    @property
    def pa_type(self) -> pa.DataType:
        return parse_type(self.type_expr)

    def to_dict(self) -> Dict[str, Any]:
        return {"id": self.id, "name": self.name, "type": self.type_expr}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LakeField":
        return cls(d["id"], d["name"], d["type"])


# widening lattice: a type may evolve to any type RIGHT of it in its row
# (int widening, float widening, int -> double). Everything else is a
# conflict the append must surface, not silently coerce.
_WIDEN_CHAINS = (
    ["byte", "short", "int", "long"],
    ["float", "double"],
)


def widens_to(old_expr: str, new_expr: str) -> bool:
    """True when ``old`` may evolve to ``new`` without data loss."""
    if old_expr == new_expr:
        return True
    for chain in _WIDEN_CHAINS:
        if old_expr in chain and new_expr in chain:
            return chain.index(old_expr) < chain.index(new_expr)
    # integer -> double is allowed (pandas/arrow aggregate convention)
    if old_expr in _WIDEN_CHAINS[0] and new_expr == "double":
        return True
    return False


def merge_fields(
    current: List[LakeField], incoming: pa.Schema
) -> List[LakeField]:
    """Schema-evolve ``current`` against an appended batch's schema:
    same-name columns must match or widen (widening updates the field
    type in place), unseen columns get fresh ids appended, and columns
    the batch omits stay (null-filled at read). Raises on a
    non-widenable type change."""
    by_name = {f.name: f for f in current}
    next_id = max((f.id for f in current), default=0) + 1
    out = [LakeField(f.id, f.name, f.type_expr) for f in current]
    for field in incoming:
        expr = type_to_expr(field.type)
        cur = by_name.get(field.name)
        if cur is None:
            out.append(LakeField(next_id, field.name, expr))
            next_id += 1
            continue
        tgt = next(f for f in out if f.id == cur.id)
        if widens_to(cur.type_expr, expr):
            tgt.type_expr = expr  # widen in place
        elif not widens_to(expr, cur.type_expr):
            raise LakeError(
                f"column {field.name!r} cannot evolve from "
                f"{cur.type_expr} to {expr}: only int/float widening is "
                "a schema evolution; anything else needs an explicit "
                "overwrite"
            )
        # narrower incoming data is fine: it casts up to the current
        # type at read time
    return out


def overwrite_fields(
    current: List[LakeField], incoming: pa.Schema
) -> List[LakeField]:
    """Field list after an OVERWRITE: only the incoming columns survive,
    but same-name columns KEEP their ids (so rename history and old
    snapshots still resolve), and any type change is allowed — replacing
    the contents is the explicit escape hatch ``merge_fields`` points
    non-widenable changes at."""
    by_name = {f.name: f for f in current}
    next_id = max((f.id for f in current), default=0) + 1
    out: List[LakeField] = []
    for field in incoming:
        expr = type_to_expr(field.type)
        cur = by_name.get(field.name)
        if cur is None:
            out.append(LakeField(next_id, field.name, expr))
            next_id += 1
        else:
            out.append(LakeField(cur.id, field.name, expr))
    return out


# ---- per-column statistics -------------------------------------------------

def _json_scalar(v: Any) -> Any:
    """Stats values must survive JSON round-trips; anything exotic
    (timestamps, decimals, binary) is dropped rather than corrupted."""
    if isinstance(v, bool) or v is None:
        return v
    if isinstance(v, (int, str)):
        return v
    if isinstance(v, float):
        return v if v == v and v not in (float("inf"), float("-inf")) else None
    return None


def column_stats(table: pa.Table) -> Dict[str, Dict[str, Any]]:
    """min/max, null count and a distinct estimate per column of one
    data file's content. The distinct estimate comes from the same
    dictionary-style uniqueness pass streamed ingest builds (arrow's
    ``count_distinct``) — the catalog statistic the cost-based
    optimizer prunes files and sizes joins with."""
    import pyarrow.compute as pc

    out: Dict[str, Dict[str, Any]] = {}
    for i, field in enumerate(table.schema):
        col = table.column(i)
        stats: Dict[str, Any] = {
            "nulls": int(col.null_count),
            "min": None,
            "max": None,
            "distinct": None,
        }
        try:
            mm = pc.min_max(col)
            stats["min"] = _json_scalar(mm["min"].as_py())
            stats["max"] = _json_scalar(mm["max"].as_py())
        except pa.ArrowNotImplementedError:
            pass
        try:
            stats["distinct"] = int(
                pc.count_distinct(col, mode="only_valid").as_py()
            )
        except pa.ArrowNotImplementedError:
            pass
        out[field.name] = stats
    return out


_PRUNE_OPS = {">", ">=", "<", "<=", "==", "="}


def stats_exclude_file(
    stats: Optional[Dict[str, Any]], op: str, literal: Any
) -> bool:
    """True when a file's column stats PROVE no row satisfies
    ``col <op> literal`` — the whole-file analog of row-group pruning,
    answered from the manifest without opening a footer. Conservative:
    missing/partial stats never exclude. NULL rows never satisfy a
    comparison, so they don't block exclusion."""
    if not stats or op not in _PRUNE_OPS:
        return False
    lo, hi = stats.get("min"), stats.get("max")
    if not isinstance(lo, (int, float)) or isinstance(lo, bool):
        return False
    if not isinstance(hi, (int, float)) or isinstance(hi, bool):
        return False
    if not isinstance(literal, (int, float)) or isinstance(literal, bool):
        return False
    if op == ">":
        return hi <= literal
    if op == ">=":
        return hi < literal
    if op == "<":
        return lo >= literal
    if op == "<=":
        return lo > literal
    return literal < lo or literal > hi  # == / =


# ---- data files & manifests ------------------------------------------------

class DataFileEntry:
    """One immutable parquet file of a snapshot. ``columns`` maps the
    table's FIELD ID (as a string — JSON keys) to the column's
    name/type AS WRITTEN in this file plus its stats; read resolution
    renames/casts/null-fills from this mapping to the snapshot schema."""

    def __init__(
        self,
        path: str,
        rows: int,
        nbytes: int,
        columns: Dict[str, Dict[str, Any]],
        sha256: Optional[str] = None,
    ):
        self.path = str(path)  # RELATIVE to the table root
        self.rows = int(rows)
        self.nbytes = int(nbytes)
        self.columns = columns
        # content digest recorded at commit; files committed before the
        # field exists carry None and skip scan-time verification
        self.sha256 = str(sha256) if sha256 else None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "path": self.path,
            "rows": self.rows,
            "bytes": self.nbytes,
            "columns": self.columns,
        }
        if self.sha256 is not None:
            out["sha256"] = self.sha256
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DataFileEntry":
        return cls(
            d["path"], d["rows"], d["bytes"], dict(d.get("columns") or {}),
            sha256=d.get("sha256"),
        )

    @classmethod
    def from_pending(
        cls, pending: Dict[str, Any], fields: List[LakeField]
    ) -> "DataFileEntry":
        """Bind a name-keyed pending file (see
        :meth:`pending_file`) to field IDS under ``fields`` — done PER
        COMMIT ATTEMPT, not at write time, because a rebase against a
        concurrent commit can change which id a new column lands on."""
        by_name = {f.name: f for f in fields}
        columns: Dict[str, Dict[str, Any]] = {}
        for name, meta in pending["by_name"].items():
            columns[str(by_name[name].id)] = {"name": name, **meta}
        return cls(
            pending["path"], pending["rows"], pending["bytes"], columns,
            sha256=pending.get("sha256"),
        )


def pending_file(
    path: str, nbytes: int, table: pa.Table, sha256: Optional[str] = None
) -> Dict[str, Any]:
    """A written-but-uncommitted data file, stats keyed by COLUMN NAME
    (field-id binding happens at commit time — see
    :meth:`DataFileEntry.from_pending`)."""
    stats = column_stats(table)
    out = {
        "path": str(path),
        "rows": int(table.num_rows),
        "bytes": int(nbytes),
        "by_name": {
            f.name: {"type": type_to_expr(f.type), **stats[f.name]}
            for f in table.schema
        },
    }
    if sha256:
        out["sha256"] = str(sha256)
    return out


class Manifest:
    """One committed snapshot: the version, its full field list and its
    full live-file list (self-contained — no log replay needed), plus
    the optional idempotence token of the writer that produced it."""

    def __init__(
        self,
        version: int,
        parent: int,
        timestamp: float,
        operation: str,
        fields: List[LakeField],
        files: List[DataFileEntry],
        writer: Optional[Dict[str, Any]] = None,
    ):
        self.version = int(version)
        self.parent = int(parent)
        self.timestamp = float(timestamp)
        self.operation = str(operation)
        self.fields = fields
        self.files = files
        self.writer = writer
        #: sha256 of the serialized payload (filled at commit/read time)
        self.sha256: Optional[str] = None

    @property
    def rows(self) -> int:
        return sum(f.rows for f in self.files)

    @property
    def schema(self) -> pa.Schema:
        return pa.schema(
            [pa.field(f.name, f.pa_type) for f in self.fields]
        )

    def field_by_name(self, name: str) -> Optional[LakeField]:
        for f in self.fields:
            if f.name == name:
                return f
        return None

    def to_payload(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "format": "fugue-lake/1",
            "version": self.version,
            "parent": self.parent,
            "timestamp": self.timestamp,
            "operation": self.operation,
            "fields": [f.to_dict() for f in self.fields],
            "files": [f.to_dict() for f in self.files],
        }
        if self.writer is not None:
            out["writer"] = dict(self.writer)
        return out

    def to_bytes(self) -> bytes:
        return json.dumps(
            self.to_payload(), sort_keys=True, separators=(",", ":")
        ).encode("utf-8")

    @classmethod
    def from_payload(cls, d: Dict[str, Any]) -> "Manifest":
        assert_or_throw(
            str(d.get("format", "")).startswith("fugue-lake/"),
            LakeError(f"not a lake manifest: format={d.get('format')!r}"),
        )
        m = cls(
            d["version"],
            d.get("parent", 0),
            d.get("timestamp", 0.0),
            d.get("operation", "append"),
            [LakeField.from_dict(f) for f in (d.get("fields") or [])],
            [DataFileEntry.from_dict(f) for f in (d.get("files") or [])],
            writer=d.get("writer"),
        )
        return m

    def describe(self) -> Dict[str, Any]:
        return {
            "version": self.version,
            "operation": self.operation,
            "timestamp": self.timestamp,
            "files": len(self.files),
            "rows": self.rows,
            "bytes": sum(f.nbytes for f in self.files),
            "schema": ",".join(
                f"{f.name}:{f.type_expr}" for f in self.fields
            ),
        }
