"""Transactional versioned table storage — the lakehouse catalog.

``lake://<path>[?version=N|timestamp=T]`` URIs address snapshot-isolated
tables built from immutable parquet data files plus a write-once
manifest log; commits go through the fs layer's fail-if-exists CAS so
any number of fleet replicas and standing pipelines write safely.
See format.py (layout) and table.py (commit protocol).
"""

from fugue_tpu.lake.format import (
    LakeCommitConflict,
    LakeCompactionConflict,
    LakeError,
    LakeIntegrityError,
    Manifest,
    format_lake_uri,
    is_lake_uri,
    parse_lake_uri,
)
from fugue_tpu.lake.table import LakeTable

__all__ = [
    "LakeCommitConflict",
    "LakeCompactionConflict",
    "LakeError",
    "LakeIntegrityError",
    "LakeTable",
    "Manifest",
    "format_lake_uri",
    "is_lake_uri",
    "parse_lake_uri",
]
