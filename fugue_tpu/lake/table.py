"""Snapshot-isolated versioned tables over ``engine.fs``.

:class:`LakeTable` is the transactional surface of the lake format
(see format.py for the on-disk layout). The commit protocol is
two-phase optimistic concurrency:

1. WRITE PHASE (no coordination): data files go to ``data/`` under
   attempt-agnostic unique names through ``write_file_atomic``. An
   uncommitted file is invisible — no manifest references it — so a
   crash here costs garbage bytes, never correctness.
2. COMMIT PHASE (the CAS loop): read the head, build
   ``manifest-(V+1).json`` against it, and publish through the fs
   layer's ``write_file_if_absent``. Exactly one of N racing writers
   owns slot V+1; losers re-read the new head, REBASE and retry with
   jittered linear backoff. Appends rebase trivially (their files are
   disjoint by construction, field ids re-bind against the new head's
   schema); compaction rebases only if its rewrite set survived intact,
   else it aborts with :class:`LakeCompactionConflict` and replans.

The retry budget exhausting raises :class:`LakeCommitConflict`, which
the workflow fault classifier treats as TRANSIENT — a task-level retry
re-reads the head and usually wins.

Exactly-once for streaming: a writer may tag commits with
``writer_id``/``writer_batch``. Before each attempt the recent manifest
chain is scanned for that id at >= that batch; a hit means the batch
already committed (the writer crashed between its lake commit and its
own progress record) and the existing manifest is returned instead of
appending twice — the same dedupe contract Delta's ``txn`` action
gives streaming sinks.

``fault_point("lake.commit"/"lake.compact", table_uri)`` sit exactly at
the commit decision points so the chaos harness can kill or fail a
writer at its most vulnerable instant; the manifest CAS makes every
outcome either "old snapshot" or "new snapshot", never torn.
"""

import hashlib
import io
import json
import random
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import pyarrow as pa

from fugue_tpu.constants import (
    FUGUE_CONF_LAKE_COMMIT_BACKOFF,
    FUGUE_CONF_LAKE_COMMIT_RETRIES,
    FUGUE_CONF_LAKE_COMPACT_TARGET_ROWS,
    FUGUE_CONF_LAKE_VERIFY,
    typed_conf_get,
)
from fugue_tpu.fs import FileSystemRegistry, uri_basename
from fugue_tpu.lake.format import (
    DATA_DIR,
    HEAD_FILE,
    MANIFEST_FMT,
    META_DIR,
    _PRUNE_OPS,
    DataFileEntry,
    LakeCommitConflict,
    LakeCompactionConflict,
    LakeError,
    LakeField,
    LakeIntegrityError,
    Manifest,
    merge_fields,
    overwrite_fields,
    pending_file,
    stats_exclude_file,
)
from fugue_tpu.testing.faults import fault_point
from fugue_tpu.testing.locktrace import tracked_lock
from fugue_tpu.utils.assertion import assert_or_throw

#: how far back the writer-dedupe scan walks the manifest chain before
#: giving up (bounds commit cost on long histories; a streaming writer
#: that lost 200 commits of ground is not "recently crashed")
_DEDUPE_SCAN_LIMIT = 200

#: manifest memo cap — manifests are immutable so the cache is safe;
#: the cap only bounds memory on very long time-travel walks
_MANIFEST_CACHE_CAP = 128


def _uuid_token() -> str:
    from uuid import uuid4

    return uuid4().hex[:12]


class LakeTable:
    """One versioned table rooted at ``table_uri`` (scheme-less path or
    any registered fs URI — NOT the ``lake://`` wrapper; parse that with
    :func:`fugue_tpu.lake.parse_lake_uri` first).

    Thread/process safety: ``_lock`` guards only the in-memory manifest
    memo (O(1) get/put). All correctness across threads, processes and
    fleet replicas comes from the manifest CAS — two LakeTable instances
    on two machines are exactly as safe as one.
    """

    def __init__(
        self,
        table_uri: str,
        fs: Optional[FileSystemRegistry] = None,
        conf: Optional[Dict[str, Any]] = None,
        metrics: Optional[Any] = None,
    ):
        from fugue_tpu.utils.io import default_fs

        self._uri = table_uri.rstrip("/")
        self._fs = fs if fs is not None else default_fs()
        conf = conf or {}
        self._retries = typed_conf_get(conf, FUGUE_CONF_LAKE_COMMIT_RETRIES)
        self._backoff = typed_conf_get(conf, FUGUE_CONF_LAKE_COMMIT_BACKOFF)
        self._compact_target = typed_conf_get(
            conf, FUGUE_CONF_LAKE_COMPACT_TARGET_ROWS
        )
        self._verify = bool(typed_conf_get(conf, FUGUE_CONF_LAKE_VERIFY))
        self._lock = tracked_lock("lake.table.LakeTable._lock")
        self._manifest_memo: Dict[int, Manifest] = {}
        #: plain counters for benches/tests (metrics registry optional)
        self.counters: Dict[str, int] = {
            "commits": 0,
            "conflicts": 0,
            "dedupe_hits": 0,
            "files_scanned": 0,
            "files_pruned": 0,
            "files_vacuumed": 0,
            "vacuum_kept_grace": 0,
            "integrity_rejected": 0,
        }
        self._metrics = metrics
        if metrics is not None:
            self._m_commits = metrics.counter(
                "fugue_lake_commits_total",
                "committed lake snapshots by operation",
                ["operation"],
            )
            self._m_conflicts = metrics.counter(
                "fugue_lake_commit_conflicts_total",
                "lost manifest CAS races (each one retried)",
            )
            self._m_pruned = metrics.counter(
                "fugue_lake_files_pruned_total",
                "data files skipped via manifest stats before any footer read",
            )
            self._m_scanned = metrics.counter(
                "fugue_lake_files_scanned_total",
                "data files actually opened by lake scans",
            )
            self._m_integrity = metrics.counter(
                "fugue_lake_integrity_rejected",
                "scans failed because a data file's bytes no longer "
                "match its manifest-recorded sha256",
            )

    # ---- paths -----------------------------------------------------------

    @property
    def uri(self) -> str:
        return self._uri

    def _meta_uri(self, name: str) -> str:
        return self._fs.join(self._uri, META_DIR, name)

    def _manifest_uri(self, version: int) -> str:
        return self._meta_uri(MANIFEST_FMT % version)

    # ---- head discovery --------------------------------------------------

    def current_version(self) -> int:
        """The latest committed version (0 = table does not exist).
        Reads the ``_head.json`` hint, falls back to a ``_meta`` listing
        when the hint is missing/stale, then probes FORWARD — the hint
        may lag the truth (best-effort write) but the probe always lands
        on the real head."""
        from fugue_tpu.workflow.manifest import read_json

        hint = read_json(self._fs, self._meta_uri(HEAD_FILE)) or {}
        try:
            v = int(hint.get("version", 0) or 0)
        except (TypeError, ValueError):
            v = 0
        if v > 0 and not self._fs.exists(self._manifest_uri(v)):
            v = 0  # stale or corrupt hint: rebuild from the listing
        if v == 0:
            v = self._max_listed_version()
        while self._fs.exists(self._manifest_uri(v + 1)):
            v += 1
        return v

    def _max_listed_version(self) -> int:
        meta = self._fs.join(self._uri, META_DIR)
        if not self._fs.exists(meta):
            return 0
        best = 0
        for name in self._fs.listdir(meta):
            base = uri_basename(name)
            if base.startswith("manifest-") and base.endswith(".json"):
                try:
                    best = max(best, int(base[len("manifest-"):-len(".json")]))
                except ValueError:
                    continue
        return best

    def exists(self) -> bool:
        return self.current_version() > 0

    # ---- manifest reads --------------------------------------------------

    def read_manifest(self, version: int) -> Manifest:
        with self._lock:
            hit = self._manifest_memo.get(version)
        if hit is not None:
            return hit
        raw = self._fs.read_bytes(self._manifest_uri(version))
        m = Manifest.from_payload(json.loads(raw.decode("utf-8")))
        m.sha256 = hashlib.sha256(raw).hexdigest()
        assert_or_throw(
            m.version == version,
            LakeError(
                f"manifest {version} of {self._uri} claims version "
                f"{m.version}"
            ),
        )
        with self._lock:
            if len(self._manifest_memo) >= _MANIFEST_CACHE_CAP:
                self._manifest_memo.pop(min(self._manifest_memo))
            self._manifest_memo[version] = m
        return m

    def snapshot(
        self,
        version: Optional[int] = None,
        timestamp: Optional[float] = None,
    ) -> Manifest:
        """Resolve an ``AS OF`` target to a concrete manifest: a pinned
        version, the newest snapshot committed at-or-before a timestamp,
        or (neither given) the current head."""
        assert_or_throw(
            version is None or timestamp is None,
            ValueError("give AS OF a version OR a timestamp, not both"),
        )
        head = self.current_version()
        assert_or_throw(
            head > 0, FileNotFoundError(f"lake table not found: {self._uri}")
        )
        if version is not None:
            assert_or_throw(
                0 < int(version) <= head,
                LakeError(
                    f"version {version} of {self._uri} does not exist "
                    f"(head is {head})"
                ),
            )
            return self.read_manifest(int(version))
        if timestamp is None:
            return self.read_manifest(head)
        v = head
        while v > 0:
            m = self.read_manifest(v)
            if m.timestamp <= float(timestamp):
                return m
            v = m.parent
        raise LakeError(
            f"no snapshot of {self._uri} at or before timestamp {timestamp}"
        )

    def _head_or_none(self) -> Optional[Manifest]:
        v = self.current_version()
        return self.read_manifest(v) if v > 0 else None

    def history(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Newest-first snapshot descriptions (version, operation,
        rows/files/bytes, schema) — the audit view."""
        out: List[Dict[str, Any]] = []
        v = self.current_version()
        while v > 0 and len(out) < limit:
            m = self.read_manifest(v)
            out.append(m.describe())
            v = m.parent
        return out

    # ---- write phase -----------------------------------------------------

    def _write_data_file(self, table: pa.Table, seq: int, token: str
                         ) -> Dict[str, Any]:
        import pyarrow.parquet as pq

        rel = f"{DATA_DIR}/part-{token}-{seq:03d}.parquet"
        sink = io.BytesIO()
        pq.write_table(table, sink)
        data = sink.getvalue()
        self._fs.write_file_atomic(
            self._fs.join(self._uri, rel), lambda fp: fp.write(data)
        )
        return pending_file(
            rel, len(data), table,
            sha256=hashlib.sha256(data).hexdigest(),
        )

    def _write_tables(self, tables: Sequence[pa.Table]) -> List[Dict[str, Any]]:
        token = _uuid_token()
        return [
            self._write_data_file(t, i, token)
            for i, t in enumerate(tables)
            if t.num_rows > 0
        ]

    # ---- commit phase ----------------------------------------------------

    def _commit(
        self,
        build: Any,
        writer_id: Optional[str] = None,
        writer_batch: Optional[int] = None,
        writer_meta: Optional[Dict[str, Any]] = None,
    ) -> Manifest:
        """The CAS loop. ``build(base, version)`` makes the candidate
        manifest for one attempt (called fresh per attempt so rebases
        see the latest head); publishing it via fail-if-exists IS the
        commit. Returns the committed (or deduped) manifest."""
        attempts = max(1, int(self._retries) + 1)
        for attempt in range(attempts):
            base = self._head_or_none()
            if writer_id is not None and writer_batch is not None:
                dup = self._find_writer_commit(base, writer_id, writer_batch)
                if dup is not None:
                    self.counters["dedupe_hits"] += 1
                    return dup
            version = (base.version if base is not None else 0) + 1
            manifest = build(base, version)
            if writer_id is not None and writer_batch is not None:
                manifest.writer = {
                    **(writer_meta or {}),
                    "id": str(writer_id),
                    "batch": int(writer_batch),
                }
            raw = manifest.to_bytes()
            # the chaos harness's kill/fail window: an injected fault or
            # hard kill HERE must leave the table at the previous
            # snapshot with the retry converging — the parity tests'
            # whole point
            fault_point("lake.commit", self._uri)
            try:
                self._fs.write_file_if_absent(
                    self._manifest_uri(version), lambda fp: fp.write(raw)
                )
            except FileExistsError:
                self.counters["conflicts"] += 1
                if self._metrics is not None:
                    self._m_conflicts.labels().inc()
                if attempt + 1 < attempts:
                    # jittered linear backoff so k racing writers fan
                    # out instead of re-colliding in lockstep
                    time.sleep(
                        self._backoff
                        * (attempt + 1)
                        * random.uniform(0.5, 1.5)
                    )
                continue
            manifest.sha256 = hashlib.sha256(raw).hexdigest()
            self.counters["commits"] += 1
            if self._metrics is not None:
                self._m_commits.labels(operation=manifest.operation).inc()
            with self._lock:
                if len(self._manifest_memo) >= _MANIFEST_CACHE_CAP:
                    self._manifest_memo.pop(min(self._manifest_memo))
                self._manifest_memo[version] = manifest
            self._write_head_hint(version)
            return manifest
        raise LakeCommitConflict(
            f"lost the manifest CAS on {self._uri} {attempts} times "
            f"(head kept moving); classified transient — a task-level "
            f"retry re-reads the head and rebases"
        )

    def _write_head_hint(self, version: int) -> None:
        """Best effort: a failure here only slows the next reader's
        forward probe, never changes what the head IS."""
        try:
            data = json.dumps({"version": int(version)}).encode("utf-8")
            self._fs.write_file_atomic(
                self._meta_uri(HEAD_FILE), lambda fp: fp.write(data)
            )
        except Exception:  # noqa: BLE001  (hint only; CAS is the truth)
            pass

    def _find_writer_commit(
        self, head: Optional[Manifest], writer_id: str, writer_batch: int
    ) -> Optional[Manifest]:
        v = head.version if head is not None else 0
        scanned = 0
        while v > 0 and scanned < _DEDUPE_SCAN_LIMIT:
            m = self.read_manifest(v) if v != getattr(head, "version", -1) \
                else head
            w = m.writer or {}
            if w.get("id") == writer_id:
                try:
                    if int(w.get("batch", -1)) >= int(writer_batch):
                        return m
                except (TypeError, ValueError):
                    pass
            v = m.parent
            scanned += 1
        return None

    # ---- public write operations ----------------------------------------

    def append(
        self,
        table: pa.Table,
        writer_id: Optional[str] = None,
        writer_batch: Optional[int] = None,
        writer_meta: Optional[Dict[str, Any]] = None,
    ) -> Manifest:
        """Append rows as new files. Concurrent appends auto-merge: the
        files are disjoint by construction, so a rebase just re-binds
        field ids against the new head and stacks on top.
        ``writer_id``/``writer_batch`` make the append IDEMPOTENT (see
        the module docstring); ``writer_meta`` rides along in the
        writer token (e.g. a streaming sink's source-file list, the
        recovery anchor for a crash between lake append and progress
        commit)."""
        pendings = self._write_tables([table])

        def build(base: Optional[Manifest], version: int) -> Manifest:
            base_fields = base.fields if base is not None else []
            fields = merge_fields(base_fields, table.schema)
            entries = [DataFileEntry.from_pending(p, fields) for p in pendings]
            files = (list(base.files) if base is not None else []) + entries
            return Manifest(
                version,
                base.version if base is not None else 0,
                time.time(),
                "append" if base is not None else "create",
                fields,
                files,
            )

        return self._commit(build, writer_id, writer_batch, writer_meta)

    def find_writer_commit(
        self, writer_id: str, writer_batch: int
    ) -> Optional[Manifest]:
        """The committed manifest of an idempotent writer's batch (>=
        the given number), or None — how a restarted streaming sink
        discovers a DANGLING append (lake commit landed, the writer's
        own progress record did not)."""
        return self._find_writer_commit(
            self._head_or_none(), writer_id, int(writer_batch)
        )

    def overwrite(self, table: pa.Table) -> Manifest:
        """Replace the table's contents (and, if needed, its schema —
        the escape hatch for non-widenable changes). On conflict the
        overwrite LOSES and retries against the new head: last
        overwrite wins, appends racing it land either before (replaced)
        or after (kept) — a linear history either way."""
        pendings = self._write_tables([table])

        def build(base: Optional[Manifest], version: int) -> Manifest:
            base_fields = base.fields if base is not None else []
            fields = overwrite_fields(base_fields, table.schema)
            entries = [DataFileEntry.from_pending(p, fields) for p in pendings]
            return Manifest(
                version,
                base.version if base is not None else 0,
                time.time(),
                "overwrite" if base is not None else "create",
                fields,
                entries,
            )

        return self._commit(build)

    def rename_column(self, old: str, new: str) -> Manifest:
        """Metadata-only rename under the stable field id: no data file
        moves, old snapshots keep the old name, old FILES resolve under
        the new name forever."""

        def build(base: Optional[Manifest], version: int) -> Manifest:
            assert_or_throw(
                base is not None,
                FileNotFoundError(f"lake table not found: {self._uri}"),
            )
            assert_or_throw(
                base.field_by_name(old) is not None,
                LakeError(f"no column {old!r} in {self._uri}"),
            )
            assert_or_throw(
                base.field_by_name(new) is None,
                LakeError(f"column {new!r} already exists in {self._uri}"),
            )
            fields = [
                LakeField(f.id, new if f.name == old else f.name, f.type_expr)
                for f in base.fields
            ]
            return Manifest(
                version, base.version, time.time(), "evolve",
                fields, list(base.files),
            )

        return self._commit(build)

    def compact(self, target_rows: Optional[int] = None) -> Optional[Manifest]:
        """Rewrite small files into ~``target_rows`` files and commit
        the swap as a NORMAL snapshot — time travel to pre-compaction
        versions still reads the original files (nothing is deleted).
        Concurrent appends rebase cleanly (their files are kept);
        a concurrent overwrite invalidates the rewrite set and raises
        :class:`LakeCompactionConflict` (re-plan from the new head).
        Returns None when there is nothing to merge."""
        base = self._head_or_none()
        if base is None or len(base.files) <= 1:
            return None
        target = int(target_rows or self._compact_target)
        fault_point("lake.compact", self._uri)
        merged = self._read_snapshot(base, None, None)
        chunks: List[pa.Table] = []
        if merged.num_rows == 0:
            chunks = []
        else:
            for start in range(0, merged.num_rows, target):
                chunks.append(merged.slice(start, target))
        pendings = self._write_tables(chunks)
        rewritten = {f.path for f in base.files}

        def build(head: Optional[Manifest], version: int) -> Manifest:
            assert_or_throw(
                head is not None,
                LakeCompactionConflict(f"{self._uri} disappeared mid-compact"),
            )
            live = {f.path for f in head.files}
            if not rewritten <= live:
                raise LakeCompactionConflict(
                    f"compaction of {self._uri} planned at v{base.version} "
                    f"but a concurrent overwrite removed some of its input "
                    f"files; re-plan from v{head.version}"
                )
            entries = [
                DataFileEntry.from_pending(p, head.fields) for p in pendings
            ]
            kept = [f for f in head.files if f.path not in rewritten]
            return Manifest(
                version, head.version, time.time(), "compact",
                head.fields, entries + kept,
            )

        return self._commit(build)

    # ---- reads -----------------------------------------------------------

    def scan(
        self,
        columns: Optional[Sequence[str]] = None,
        version: Optional[int] = None,
        timestamp: Optional[float] = None,
        pruning: Optional[Sequence[Sequence[Any]]] = None,
    ) -> pa.Table:
        """Read a snapshot as one arrow table, resolving schema
        evolution (renames by field id, null-fill for pre-addition
        files, upcast for widened types) and pruning WHOLE FILES from
        manifest stats before any parquet footer is touched.
        ``pruning`` is the optimizer's conjunctive ``[col, op, literal]``
        triples — the same shape row-group pruning consumes; surviving
        rows are NOT filtered here, the engine's filter still runs."""
        m = self.snapshot(version=version, timestamp=timestamp)
        return self._read_snapshot(m, columns, pruning)

    def _read_snapshot(
        self,
        m: Manifest,
        columns: Optional[Sequence[str]],
        pruning: Optional[Sequence[Sequence[Any]]],
    ) -> pa.Table:
        if columns:
            sel: List[LakeField] = []
            for name in columns:
                f = m.field_by_name(name)
                assert_or_throw(
                    f is not None,
                    LakeError(
                        f"no column {name!r} in {self._uri} "
                        f"v{m.version}"
                    ),
                )
                sel.append(f)  # type: ignore[arg-type]
        else:
            sel = list(m.fields)
        out_schema = pa.schema([pa.field(f.name, f.pa_type) for f in sel])
        parts: List[pa.Table] = []
        for entry in m.files:
            if pruning and self._file_excluded(entry, m, pruning):
                self.counters["files_pruned"] += 1
                if self._metrics is not None:
                    self._m_pruned.labels().inc()
                continue
            self.counters["files_scanned"] += 1
            if self._metrics is not None:
                self._m_scanned.labels().inc()
            parts.append(self._read_file(entry, sel, out_schema))
        if not parts:
            return out_schema.empty_table()
        return pa.concat_tables(parts)

    def _file_excluded(
        self,
        entry: DataFileEntry,
        m: Manifest,
        triples: Sequence[Sequence[Any]],
    ) -> bool:
        for triple in triples:
            if len(triple) != 3:
                continue
            col, op, lit = triple
            f = m.field_by_name(str(col))
            if f is None:
                continue
            st = entry.columns.get(str(f.id))
            if st is None:
                # the file predates this column: every row is NULL and
                # NULL never satisfies a comparison -> whole file out
                if op in _PRUNE_OPS:
                    return True
                continue
            if stats_exclude_file(st, str(op), lit):
                return True
        return False

    def _read_file(
        self,
        entry: DataFileEntry,
        sel: List[LakeField],
        out_schema: pa.Schema,
    ) -> pa.Table:
        import pyarrow.parquet as pq

        # which selected fields exist in THIS file, under which name
        in_file: Dict[int, str] = {}
        for f in sel:
            meta = entry.columns.get(str(f.id))
            if meta is not None:
                in_file[f.id] = meta["name"]
        if in_file:
            raw = self._fs.read_bytes(self._fs.join(self._uri, entry.path))
            if self._verify and entry.sha256:
                digest = hashlib.sha256(raw).hexdigest()
                if digest != entry.sha256:
                    self.counters["integrity_rejected"] += 1
                    if self._metrics is not None:
                        self._m_integrity.labels().inc()
                    raise LakeIntegrityError(
                        f"data file {entry.path} of {self._uri} failed "
                        f"integrity verification: manifest recorded "
                        f"sha256 {entry.sha256} but the stored bytes "
                        f"hash to {digest} ({len(raw)} bytes read, "
                        f"{entry.nbytes} committed)"
                    )
            t = pq.read_table(
                pa.BufferReader(raw), columns=list(in_file.values())
            )
            nrows = t.num_rows
        else:
            t = None
            nrows = entry.rows
        arrays: List[Any] = []
        for f in sel:
            name = in_file.get(f.id)
            if name is None or t is None:
                arrays.append(pa.nulls(nrows, f.pa_type))
                continue
            col = t.column(name)
            if col.type != f.pa_type:
                col = col.cast(f.pa_type)
            arrays.append(col)
        return pa.Table.from_arrays(arrays, schema=out_schema)

    # ---- maintenance -----------------------------------------------------

    def vacuum(self, grace_secs: float = 3600.0) -> Dict[str, Any]:
        """Delete orphaned data files: parquet parts present in the
        ``data/`` listing but referenced by NO committed manifest of ANY
        version (compaction keeps old files referenced — time travel to
        pre-compaction versions must still read them, so the live set is
        the union over the WHOLE manifest chain, never just the head).

        Orphans are how crash-interrupted writers leave their mark: data
        files land before the manifest CAS, so a writer killed between
        the two (chaos site ``lake.commit``) leaves parts no manifest
        ever adopted. ``grace_secs`` protects the mirror-image race — a
        writer that has landed its parts but not yet WON its CAS looks
        identical to a corpse — by skipping anything younger than the
        grace window (mtime); concurrent in-flight commits are always
        younger than any sane grace.

        Safe to re-run and safe to crash mid-sweep: every delete is of a
        file no manifest references, so the worst outcome of a partial
        sweep is leftover orphans for the next vacuum. Returns/counts
        ``files_vacuumed`` and ``vacuum_kept_grace``."""
        head = self.current_version()
        out = {"removed": 0, "kept_grace": 0, "live_files": 0, "bytes": 0}
        if head == 0:
            return out
        live = set()
        for v in range(1, head + 1):
            live.update(f.path for f in self.read_manifest(v).files)
        out["live_files"] = len(live)
        data_dir = self._fs.join(self._uri, DATA_DIR)
        if not self._fs.exists(data_dir):
            return out
        now = time.time()
        for name in self._fs.listdir(data_dir):
            base = uri_basename(name)
            if f"{DATA_DIR}/{base}" in live:
                continue
            path = self._fs.join(data_dir, base)
            try:
                info = self._fs.info(path)
            except Exception:
                continue  # raced another sweep: already gone
            if now - float(info.mtime or 0.0) < grace_secs:
                # possibly a live writer between data land and CAS win
                self.counters["vacuum_kept_grace"] += 1
                out["kept_grace"] += 1
                continue
            self._fs.rm(path)
            self.counters["files_vacuumed"] += 1
            out["removed"] += 1
            out["bytes"] += int(info.size or 0)
        return out

    def describe(self) -> Dict[str, Any]:
        head = self.current_version()
        out: Dict[str, Any] = {"uri": self._uri, "version": head}
        if head > 0:
            out.update(self.read_manifest(head).describe())
        return out
