"""Deterministic testing utilities: the fault-injection harness that
exercises the workflow fault-tolerance layer (retry, degrade, resume)."""

from fugue_tpu.testing.faults import (
    FaultPlan,
    FaultSpec,
    fault_point,
    inject_faults,
)
