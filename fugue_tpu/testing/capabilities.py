"""Environment capability probes for tests whose prerequisites depend on
the container, not the code: multi-process CPU collectives (jax's CPU
backend only implements them in some builds) and real-accelerator
detection (on some hosts the unforced ``jax.devices()`` probe HANGS in
the platform plugin rather than failing).

Each probe runs in subprocesses with a hard timeout, caches its verdict
for the process lifetime, and returns ``(ok, reason)`` so tests can
``pytest.skip(reason)`` — a capability-check skip instead of a
container-dependent failure."""

import os
import socket
import subprocess
import sys
import textwrap
from typing import Dict, Optional, Tuple

_CACHE: Dict[str, Tuple[bool, str]] = {}

_COLLECTIVES_INNER = textwrap.dedent(
    """
    import sys
    import jax

    pid = int(sys.argv[1])
    jax.distributed.initialize(
        coordinator_address=sys.argv[2], num_processes=2, process_id=pid
    )
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    x = multihost_utils.process_allgather(jnp.ones((2,)) * (pid + 1))
    assert float(x.sum()) == 6.0, x
    print("COLLECTIVES_OK")
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _clean_env() -> Dict[str, str]:
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            env.pop(k)
    return env


def cpu_multiprocess_collectives(timeout: float = 90.0) -> Tuple[bool, str]:
    """Can two CPU-backend jax processes run a cross-process collective?
    Spawns two tiny subprocesses doing ``jax.distributed.initialize`` +
    ``process_allgather``; the known-bad container answer ("Multiprocess
    computations aren't implemented on the CPU backend") fails in a few
    seconds."""
    if "cpu_collectives" in _CACHE:
        return _CACHE["cpu_collectives"]
    env = _clean_env()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    coordinator = f"127.0.0.1:{_free_port()}"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _COLLECTIVES_INNER, str(pid), coordinator],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(2)
    ]
    ok, reason = True, ""
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                ok, reason = (
                    False, "cross-process collective probe timed out"
                )
                break
            if p.returncode != 0 or "COLLECTIVES_OK" not in out:
                tail = (
                    err.strip().splitlines()[-1]
                    if err.strip()
                    else "no output"
                )
                ok, reason = False, (
                    f"CPU backend lacks multiprocess collectives: {tail}"
                )
                break
    finally:
        # one peer failing fast leaves the other blocked in the
        # coordinator rendezvous: kill and reap EVERY survivor on any
        # exit path, not just the timeout branch
        for q in procs:
            if q.poll() is None:
                q.kill()
            try:
                q.communicate(timeout=5)
            except Exception:  # pragma: no cover - already reaped/wedged
                pass
    _CACHE["cpu_collectives"] = (ok, reason)
    return ok, reason


def default_platforms(timeout: float = 20.0) -> Tuple[Optional[str], str]:
    """The platform set jax picks with ``JAX_PLATFORMS`` unset, probed in
    a subprocess: ``("cpu|tpu", "")`` on success, ``(None, reason)`` when
    the probe errors or HANGS (some hosts block in the accelerator
    plugin's device enumeration — the reason these probes never run
    in-process)."""
    if "default_platforms" in _CACHE:
        cached = _CACHE["default_platforms"]
        return (cached[1] or None) if cached[0] else None, (
            "" if cached[0] else cached[1]
        )
    env = _clean_env()
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = " ".join(
        t
        for t in env.get("XLA_FLAGS", "").split()
        if not t.startswith("--xla_force_host_platform_device_count")
    )
    try:
        res = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; "
                "print('|'.join(sorted({d.platform for d in jax.devices()})))",
            ],
            capture_output=True,
            text=True,
            timeout=timeout,
            env=env,
        )
    except subprocess.TimeoutExpired:
        _CACHE["default_platforms"] = (
            False,
            f"accelerator probe hung for {timeout:g}s (platform plugin "
            "wedged during device enumeration)",
        )
        return None, _CACHE["default_platforms"][1]
    if res.returncode != 0:
        tail = (
            res.stderr.strip().splitlines()[-1]
            if res.stderr.strip()
            else "no output"
        )
        _CACHE["default_platforms"] = (False, f"device probe failed: {tail}")
        return None, _CACHE["default_platforms"][1]
    platforms = res.stdout.strip()
    _CACHE["default_platforms"] = (True, platforms)
    return platforms, ""


def has_real_accelerator(timeout: float = 20.0) -> Tuple[bool, str]:
    """(True, "") when the UNFORCED jax platform set contains something
    beyond CPU; (False, why) when it is CPU-only or unprobeable."""
    platforms, reason = default_platforms(timeout)
    if platforms is None:
        return False, reason
    non_cpu = [p for p in platforms.split("|") if p and p != "cpu"]
    if non_cpu:
        return True, ""
    return False, "no accelerator on this host (cpu-only jax platform)"
