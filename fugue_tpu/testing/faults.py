"""Deterministic fault-injection harness.

Production code is sprinkled with zero-cost *fault points* — named sites
(`"fs.open"`, `"fs.write"`, `"task"`, `"rpc"`, `"device.alloc"`) that
consult the active :class:`FaultPlan` and raise the planned error when a
site/key/invocation matches. No plan active (the normal case) is a
single ``None`` check.

**Serve-plane chaos sites** (ISSUE 7) extend the harness into the
long-lived daemon, where the interesting failures are *partial* — the
daemon must degrade, never die:

- ``serve.journal`` (key = journal URI): the durable state write in
  ``serve/state.py`` — an injected failure degrades durability (counted
  in ``write_failures``) while serving continues;
- ``serve.sweep`` (key = session id): TTL-expiry close in
  ``SessionManager.sweep`` — a failed sweep leaves the session for the
  next pass instead of wedging the caller;
- ``serve.dispatch`` (key = job id): worker pickup in the job scheduler
  — the fault lands on the job as a structured error, never as a dead
  worker thread;
- ``serve.http`` (key = ``"METHOD /path"``): request routing in the
  daemon — the fault answers as a structured 500 and the connection
  plane survives.
- ``serve.route`` (key = ``"<replica> METHOD /path"``): the fleet
  router's forward to a replica (serve/fleet.py) — the fault answers as
  a structured error from the ROUTER while the replicas stay untouched.

The ``device.alloc`` site fires in the memory governor's pre-allocation
gate (jax_backend/memory.py) with the placement TIER as its key, right
before a frame's device arrays are staged. A spec matching ``"device"``
with a :func:`resource_exhausted` error simulates an accelerator
allocation failure deterministically on CPU — and stays silent once the
degrade override re-places the retry onto the host tier — so every
governance path (admission, spill, OOM feedback, host degrade) is
testable without real HBM pressure.

A plan is a list of :class:`FaultSpec` rules. Each rule matches a site
and a key glob (the URI for fs sites, the task display name for task
sites, the handler key for rpc sites), and fires on specific invocations:
``skip`` matching calls pass through first, then ``times`` calls raise
the spec's error, then the site is clean again — so "fail the first two
reads, then succeed" (the retry-recovery shape) is one rule. A seeded
``probability`` mode exists for randomized soak tests; with the same
seed the plan replays identically.

Every matching invocation is counted per site:key (``attempts``,
``injected``), and the retry executor reports back ``retries``,
``recoveries`` and ``degradations`` — the same counter idiom as the jax
engine's strategy/fallback counters, so tests assert recovery paths
actually ran instead of trusting them on faith.

Usage::

    plan = FaultPlan(
        FaultSpec("fs.open", "memory://data/*", times=2,
                  error=lambda: OSError("injected read hiccup")),
        seed=7,
    )
    with inject_faults(plan):
        dag.run(engine)          # first two matching reads fail
    # counters key by the CONCRETE invocation key, not the spec glob:
    assert plan.counters["fs.open:memory://data/a.parquet"]["injected"] == 2
    assert plan.total("injected") == 2
"""

import fnmatch
import random
from contextlib import contextmanager

from fugue_tpu.testing.locktrace import tracked_lock
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

_ErrorLike = Union[BaseException, Callable[[], BaseException], type]

# the fault-point vocabulary embedded in production code, for plan
# authors and the chaos tests' self-checks (a typo'd site in a spec
# would otherwise silently never fire)
KNOWN_SITES = (
    "fs.open",
    "fs.write",
    "task",
    "rpc",
    "device.alloc",
    "serve.journal",
    "serve.sweep",
    "serve.dispatch",
    "serve.http",
    "serve.route",
    "serve.scale",
    "obs.trace",
    "cache.persist",
    "stream.commit",
    "lake.commit",
    "lake.compact",
    "device.lost",
)


class _InjectedXlaRuntimeError(Exception):
    """Stand-in for jaxlib's XlaRuntimeError in injected device faults.
    The classifier (workflow/fault.py) keys on the class NAME plus the
    RESOURCE_EXHAUSTED token, so renaming the class makes an injected
    instance triage exactly like the real thing."""


_InjectedXlaRuntimeError.__name__ = "XlaRuntimeError"
_InjectedXlaRuntimeError.__qualname__ = "XlaRuntimeError"


def resource_exhausted(nbytes: int = 0) -> BaseException:
    """An injectable device-OOM error for ``device.alloc`` fault specs:
    classifies as OOM and carries a parseable allocation size so the
    memory governor's OOM feedback path sees a measured request."""
    return _InjectedXlaRuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        f"{int(nbytes)} bytes."
    )


def device_lost(device_id: int = 0) -> BaseException:
    """An injectable device-loss error for ``device.lost`` fault specs:
    the DATA_LOSS shape a dead accelerator produces mid-collective. The
    classifier triages it DEVICE_LOST (real runtime-error types only)
    and the engine's degraded-mesh recovery parses the dead device id
    out of the text."""
    return _InjectedXlaRuntimeError(
        f"DATA_LOSS: device lost: device {int(device_id)} is in an "
        "error state and its core halted (hardware fault)"
    )


def collective_hang(device_id: int = 0) -> BaseException:
    """The hung-collective member of the ``device.lost`` chaos family: a
    DEADLINE_EXCEEDED shape (a peer stopped answering the all-reduce but
    the runtime can't yet prove it dead). Classifies TRANSIENT — the
    retry either succeeds (the peer was slow, not dead) or the runtime
    escalates to the DATA_LOSS shape above on a later attempt."""
    return _InjectedXlaRuntimeError(
        "DEADLINE_EXCEEDED: collective all-reduce timed out waiting for "
        f"participant {int(device_id)} (possible hung peer)"
    )


class FaultSpec:
    """One injection rule: where (``site`` + ``match`` glob), when
    (``skip``/``times`` invocation window, or seeded ``probability``),
    and what (``error`` — an exception instance, class, or factory)."""

    def __init__(
        self,
        site: str,
        match: str = "*",
        times: int = 1,
        skip: int = 0,
        probability: Optional[float] = None,
        error: _ErrorLike = OSError,
    ):
        self.site = site
        self.match = match
        self.times = times
        self.skip = skip
        self.probability = probability
        self._error = error
        self._seen = 0
        self._fired = 0

    def make_error(self) -> BaseException:
        if isinstance(self._error, BaseException):
            return self._error
        err = self._error()
        if isinstance(err, BaseException):
            return err
        raise TypeError(  # pragma: no cover - plan authoring bug
            f"fault error factory returned {err!r}"
        )

    def should_fire(self, rng: random.Random) -> bool:
        """Advance this spec's invocation counter and decide. Caller holds
        the plan lock."""
        self._seen += 1
        if self.probability is not None:
            return rng.random() < self.probability
        if self._seen <= self.skip:
            return False
        if self._fired >= self.times:
            return False
        self._fired += 1
        return True


class FaultPlan:
    """A seeded, replayable set of :class:`FaultSpec` rules plus the
    per-site counters that make recovery paths observable."""

    def __init__(self, *specs: FaultSpec, seed: int = 0):
        self.specs: List[FaultSpec] = list(specs)
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = tracked_lock("testing.faults.FaultPlan._lock")
        self.counters: Dict[str, Dict[str, int]] = {}

    def add(self, spec: FaultSpec) -> "FaultPlan":
        with self._lock:
            self.specs.append(spec)
        return self

    def _bump(self, key: str, counter: str, n: int = 1) -> None:
        slot = self.counters.setdefault(
            key,
            {
                "attempts": 0,
                "injected": 0,
                "retries": 0,
                "recoveries": 0,
                "degradations": 0,
                "device_recoveries": 0,
            },
        )
        slot[counter] += n

    def check(self, site: str, key: str) -> None:
        """Raise the planned error if any rule matches this invocation."""
        with self._lock:
            fired: Optional[FaultSpec] = None
            matched = False
            for spec in self.specs:
                if spec.site != site or not fnmatch.fnmatchcase(
                    key, spec.match
                ):
                    continue
                matched = True
                if fired is None and spec.should_fire(self._rng):
                    fired = spec
            if matched:
                self._bump(f"{site}:{key}", "attempts")
            if fired is not None:
                self._bump(f"{site}:{key}", "injected")
                err = fired.make_error()
        if fired is not None:
            raise err

    # ---- recovery observability (reported by the retry executor) --------
    def note_retry(self, site: str, key: str) -> None:
        with self._lock:
            self._bump(f"{site}:{key}", "retries")

    def note_recovery(self, site: str, key: str) -> None:
        with self._lock:
            self._bump(f"{site}:{key}", "recoveries")

    def note_degradation(self, site: str, key: str) -> None:
        with self._lock:
            self._bump(f"{site}:{key}", "degradations")

    def note_device_recovery(self, site: str, key: str) -> None:
        with self._lock:
            self._bump(f"{site}:{key}", "device_recoveries")

    def total(self, counter: str) -> int:
        with self._lock:
            return sum(c.get(counter, 0) for c in self.counters.values())


_ACTIVE: Optional[FaultPlan] = None
_ACTIVE_LOCK = tracked_lock("testing.faults._ACTIVE_LOCK")


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


def fault_point(site: str, key: str) -> None:
    """The hook embedded at injection sites. Free when no plan is active."""
    plan = _ACTIVE
    if plan is None:
        return
    plan.check(site, key)


@contextmanager
def inject_faults(plan: FaultPlan) -> Iterator[FaultPlan]:
    """Arm ``plan`` process-wide for the duration of the block. Nesting is
    rejected: overlapping plans would make the replay nondeterministic."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            raise RuntimeError("a FaultPlan is already active")
        _ACTIVE = plan
    try:
        yield plan
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE = None
