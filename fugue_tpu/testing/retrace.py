"""Runtime retrace sentinel (the dynamic half of the jit-hazard
correctness plane; the static half is :mod:`fugue_tpu.analysis.jitlint`).

The engine's central perf contract — "one XLA trace per logical program,
zero recompiles on the warm path" — is asserted by bench gates
(``zero_recompile_warm``) and streaming counters, but those only say *how
many* compiles happened, never *which program* retraced or *why*. The
sentinel closes that gap: armed (conf ``fugue.debug.retrace_sentinel``,
or :func:`retrace_sentinel` in tests), every jitted dispatch that XLA
actually re-traced — detected the same way the engine's compile counters
are, via per-shape cache growth — records a per-program-key trace count
plus the argument-aval signature of that trace. When one program key
exceeds ``fugue.debug.retrace_sentinel.max_traces`` the sentinel emits a
:class:`RetraceViolation` carrying:

- the **Python callsite** of the offending dispatch (engine frames
  stripped, like the lock sanitizer's reports);
- the **differing aval**: the first argument leaf whose shape/dtype (or
  host-scalar value — a Python int folded into a trace) changed between
  the previous trace and this one — the concrete retrace generator the
  static FJX201/FJX202 rules hunt for at lint time.

Violations are recorded and logged by default; conf
``fugue.debug.retrace_sentinel.raise`` upgrades them to
:class:`RetraceBudgetExceeded` so a CI bench dies at the first unstable
program instead of three PRs later. The engine exports violation counts
as ``fugue_engine_retrace_sentinel_total{program=...}``.

Disabled (the default, and the only mode production runs), the per-
dispatch cost is one module-global read on an already-compiled path:
nothing is wrapped, nothing retained — the same zero-overhead-off
contract as :mod:`fugue_tpu.testing.locktrace`.
"""

import logging
import threading
import traceback
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple

from fugue_tpu.constants import (
    FUGUE_CONF_DEBUG_RETRACE_SENTINEL,
    FUGUE_CONF_DEBUG_RETRACE_SENTINEL_MAX_TRACES,
    FUGUE_CONF_DEBUG_RETRACE_SENTINEL_RAISE,
    typed_conf_get,
)

_LOG = logging.getLogger("fugue_tpu.retrace")

_ACTIVE: Optional["RetraceSentinel"] = None
_ACTIVE_GUARD = threading.Lock()

#: frames from these files are the dispatch plumbing, not the caller
_PLUMBING_SUFFIXES = (
    "/testing/retrace.py",
    "/jax_backend/execution_engine.py",
    "/jax_backend/blocks.py",
)


class RetraceBudgetExceeded(RuntimeError):
    """Raised (conf ``fugue.debug.retrace_sentinel.raise``) when a jitted
    program exceeds its trace budget; the message IS the full report."""


def _callsite(limit: int = 8) -> List[str]:
    """The dispatching frames, innermost last, with the sentinel's and
    the engine dispatch plumbing's own frames stripped — the report must
    point at the *user* code whose inputs are shape-unstable."""
    out: List[str] = []
    for fs in traceback.extract_stack()[:-1]:
        if fs.filename.replace("\\", "/").endswith(_PLUMBING_SUFFIXES):
            continue
        out.append(f"{fs.filename}:{fs.lineno} in {fs.name}")
    return out[-limit:]


def _leaf_sig(x: Any) -> str:
    """One argument leaf's trace-identity: shape/dtype for arrays, the
    concrete value for host scalars (a changing Python int IS a new
    trace — jax hashes it into the program when static, and even traced
    weak scalars betray a host-side fold when their dtype flips)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        dims = ",".join(str(d) for d in shape)
        return f"{dtype}[{dims}]"
    if isinstance(x, (bool, int, float, complex, str, bytes, type(None))):
        r = repr(x)
        return f"py:{type(x).__name__}:{r[:40]}"
    return f"obj:{type(x).__name__}"


def args_signature(args: Any) -> Tuple[str, ...]:
    """Flattened per-leaf aval signature of one dispatch's arguments."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(args)
    except Exception:  # pragma: no cover - jax always present in-repo
        leaves = list(args)
    return tuple(_leaf_sig(leaf) for leaf in leaves)


def diff_signatures(
    prev: Tuple[str, ...], new: Tuple[str, ...]
) -> List[str]:
    """Human-readable per-leaf differences between two trace signatures
    (the 'differing aval' of the report)."""
    out: List[str] = []
    if len(prev) != len(new):
        out.append(f"arg count: {len(prev)} -> {len(new)} leaves")
    for i, (p, n) in enumerate(zip(prev, new)):
        if p != n:
            out.append(f"arg leaf {i}: {p} -> {n}")
    return out


class RetraceViolation:
    """One program key that exceeded its trace budget: the count, the
    dispatching Python callsite, and the aval diff vs the prior trace."""

    def __init__(
        self,
        program: str,
        key: Any,
        traces: int,
        max_traces: int,
        callsite: List[str],
        diff: List[str],
    ):
        self.program = program
        self.key = key
        self.traces = traces
        self.max_traces = max_traces
        self.callsite = callsite
        self.diff = diff

    def describe(self) -> str:
        lines = [
            f"retrace sentinel: program '{self.program}' traced "
            f"{self.traces} times (budget: {self.max_traces}) — a warm "
            "path must reuse ONE trace; an unstable shape/dtype or a "
            "host value folded into the program is forcing recompiles",
            "  differing aval vs previous trace:",
            *(
                ("    " + d for d in self.diff)
                if self.diff
                else ("    (first recorded trace for this key)",)
            ),
            "  dispatched from:",
            *("    " + s for s in self.callsite),
        ]
        return "\n".join(lines)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"RetraceViolation({self.program!r}, traces={self.traces})"


class _ProgramRecord:
    __slots__ = ("traces", "signature")

    def __init__(self) -> None:
        self.traces = 0
        self.signature: Optional[Tuple[str, ...]] = None


class RetraceSentinel:
    """Per-scope collector: per-program-key trace counts, last-trace aval
    signatures, and the violations found. ``note_trace`` is called by the
    engine/blocks dispatch paths only when a dispatch ACTUALLY compiled
    (per-shape cache growth), so counts are XLA's own, not a guess."""

    def __init__(
        self, max_traces: int = 4, raise_on_violation: bool = False
    ) -> None:
        self._guard = threading.Lock()
        self.max_traces = int(max_traces)
        self.raise_on_violation = bool(raise_on_violation)
        self._programs: Dict[Any, _ProgramRecord] = {}
        self.violations: List[RetraceViolation] = []

    # ---- recording -------------------------------------------------------
    def note_trace(
        self, program: str, key: Any, args: Any
    ) -> Optional[RetraceViolation]:
        """Record one fresh trace of ``(program, key)``; returns the
        violation when this trace exceeded the budget (already recorded
        and logged — the caller decides metrics and raising via
        :meth:`raise_if_armed`). Never raises itself."""
        sig = args_signature(args)
        try:
            record_key: Any = (program, key)
            hash(record_key)
        except TypeError:  # unhashable program key: fall back to name
            record_key = (program, None)
        with self._guard:
            rec = self._programs.get(record_key)
            if rec is None:
                rec = self._programs[record_key] = _ProgramRecord()
            rec.traces += 1
            prev, rec.signature = rec.signature, sig
            if rec.traces <= self.max_traces:
                return None
            violation = RetraceViolation(
                program=program,
                key=key,
                traces=rec.traces,
                max_traces=self.max_traces,
                callsite=_callsite(),
                diff=diff_signatures(prev, sig) if prev is not None else [],
            )
            self.violations.append(violation)
        _LOG.warning("fugue_tpu %s", violation.describe())
        return violation

    def raise_if_armed(self, violation: Optional[RetraceViolation]) -> None:
        if violation is not None and self.raise_on_violation:
            raise RetraceBudgetExceeded(violation.describe())

    # ---- introspection ---------------------------------------------------
    def trace_counts(self) -> Dict[str, int]:
        """Per-program total trace counts (keys collapsed to the program
        name — the report/metrics vocabulary)."""
        with self._guard:
            out: Dict[str, int] = {}
            for (program, _), rec in self._programs.items():
                out[program] = out.get(program, 0) + rec.traces
            return out

    def report(self) -> str:
        with self._guard:
            violations = list(self.violations)
        if not violations:
            return "retrace sentinel: no trace-budget violations"
        return "\n".join(v.describe() for v in violations)


def active_retrace_sentinel() -> Optional[RetraceSentinel]:
    return _ACTIVE


def enable_retrace_sentinel(
    max_traces: int = 4, raise_on_violation: bool = False
) -> RetraceSentinel:
    """Arm a process-wide sentinel (idempotent: an already-armed one is
    returned unchanged — first armer wins, mirroring the lock
    sanitizer). Arm BEFORE the dispatches under test run."""
    global _ACTIVE
    with _ACTIVE_GUARD:
        if _ACTIVE is None:
            _ACTIVE = RetraceSentinel(
                max_traces=max_traces, raise_on_violation=raise_on_violation
            )
        return _ACTIVE


def disable_retrace_sentinel() -> None:
    global _ACTIVE
    with _ACTIVE_GUARD:
        _ACTIVE = None


@contextmanager
def retrace_sentinel(
    max_traces: int = 4, raise_on_violation: bool = False
) -> Iterator[RetraceSentinel]:
    """Test scope: arm for the block, disarm after. The yielded sentinel
    keeps its counts/violations readable after exit."""
    san = enable_retrace_sentinel(
        max_traces=max_traces, raise_on_violation=raise_on_violation
    )
    try:
        yield san
    finally:
        disable_retrace_sentinel()


def maybe_enable_from_conf(conf: Any) -> Optional[RetraceSentinel]:
    """Conf-driven arming (``fugue.debug.retrace_sentinel``): long-lived
    owners (the serving daemon) call this before constructing their
    engine so the first dispatch is already watched. Off (the default)
    touches nothing and returns None."""
    try:
        enabled = typed_conf_get(conf, FUGUE_CONF_DEBUG_RETRACE_SENTINEL)
    except Exception:
        enabled = False
    if not enabled:
        return None
    try:
        max_traces = typed_conf_get(
            conf, FUGUE_CONF_DEBUG_RETRACE_SENTINEL_MAX_TRACES
        )
    except Exception:
        max_traces = 4
    try:
        raise_on = typed_conf_get(
            conf, FUGUE_CONF_DEBUG_RETRACE_SENTINEL_RAISE
        )
    except Exception:
        raise_on = False
    return enable_retrace_sentinel(
        max_traces=int(max_traces), raise_on_violation=bool(raise_on)
    )
