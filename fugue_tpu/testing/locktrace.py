"""Runtime lock-order sanitizer (the dynamic half of the concurrency
correctness plane; the static half is :mod:`fugue_tpu.analysis.codelint`).

Production modules create their locks through :func:`tracked_lock`, giving
every lock a stable dotted name (``"serve.scheduler.JobScheduler._lock"``)
— the SAME vocabulary the source linter's FLN101 lock registry uses. With
the sanitizer disabled (the default, and the only mode production ever
runs), ``tracked_lock`` returns a plain ``threading.Lock``/``RLock``
directly: **no wrapper object, no indirection, zero steady-state
overhead** — the disabled-mode identity the test suite asserts.

Enabled (conf ``fugue.debug.lock_sanitizer``, or :func:`lock_sanitizer`
in tests), every lock created inside the scope is wrapped. At each
acquisition the sanitizer:

- tracks this thread's **held set** (names + the acquisition stack);
- records a directed edge ``outer -> inner`` for every lock already held
  (reentrant re-acquisition of the same lock records nothing — RLock
  nesting is legal by construction);
- reports an **ordering inversion** the moment an edge's reverse was
  ever observed (by any thread), carrying BOTH acquisition stacks — the
  site that established ``A -> B`` and the site now attempting
  ``B -> A``;
- reports **potential deadlock cycles** of length > 2 by walking the
  accumulated edge graph at insertion time.

Detection happens BEFORE the underlying acquire blocks, so a schedule
that would actually deadlock still produces its report. Violations are
recorded (and logged) rather than raised by default: the serve stress
and chaos suites run entire scenarios under the sanitizer and assert
``violations == []`` at the end.
"""

import logging
import threading
import traceback
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple

from fugue_tpu.constants import FUGUE_CONF_DEBUG_LOCK_SANITIZER, typed_conf_get

_LOG = logging.getLogger("fugue_tpu.locktrace")

_ACTIVE: Optional["LockSanitizer"] = None
_ACTIVE_GUARD = threading.Lock()


class LockOrderViolation:
    """One detected hazard: an inversion (2-cycle) or a longer potential
    deadlock cycle. Carries the acquisition stacks of BOTH sides so the
    report names the two code sites whose nesting disagrees."""

    def __init__(
        self,
        kind: str,
        cycle: Tuple[str, ...],
        thread_name: str,
        stack: List[str],
        other_thread_name: str,
        other_stack: List[str],
    ):
        self.kind = kind  # "inversion" | "cycle"
        self.cycle = cycle  # lock names, acquisition order of the new edge
        self.thread_name = thread_name
        self.stack = stack
        self.other_thread_name = other_thread_name
        self.other_stack = other_stack

    def describe(self) -> str:
        chain = " -> ".join(self.cycle)
        lines = [
            f"lock-order {self.kind}: {chain}",
            f"  this acquisition [{self.thread_name}]:",
            *("    " + s for s in self.stack),
            f"  conflicting order established at [{self.other_thread_name}]:",
            *("    " + s for s in self.other_stack),
        ]
        return "\n".join(lines)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"LockOrderViolation({self.kind}, {' -> '.join(self.cycle)})"


_THIS_FILE = __file__


def _site_stack(limit: int = 8) -> List[str]:
    """The acquiring frames, innermost last, with this module's own
    frames stripped (the report should point at the caller's site)."""
    out: List[str] = []
    for fs in traceback.extract_stack()[:-1]:
        if fs.filename == _THIS_FILE:
            continue
        out.append(f"{fs.filename}:{fs.lineno} in {fs.name}")
    return out[-limit:]


class _Edge:
    """First observation of ``outer -> inner``: who, and from where."""

    __slots__ = ("thread_name", "stack")

    def __init__(self, thread_name: str, stack: List[str]):
        self.thread_name = thread_name
        self.stack = stack


class LockSanitizer:
    """Per-scope collector: the held-set bookkeeping, the accumulated
    lock-order graph, and the violations found."""

    def __init__(self) -> None:
        self._guard = threading.Lock()
        self._tls = threading.local()
        # (outer, inner) -> first-observation record
        self._edges: Dict[Tuple[str, str], _Edge] = {}
        self.violations: List[LockOrderViolation] = []
        self.names: List[str] = []  # registration order, for reports

    # ---- registration ----------------------------------------------------
    def register(self, name: str) -> None:
        with self._guard:
            if name not in self.names:
                self.names.append(name)

    # ---- held-set bookkeeping (per thread) -------------------------------
    def _held(self) -> List[Tuple[str, int]]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def note_acquire(self, name: str, lock_id: int) -> None:
        """Called BEFORE the underlying acquire blocks: record edges from
        every currently-held lock and check them against the graph.
        Held entries key by (name, INSTANCE): only re-acquiring the SAME
        instance is RLock reentrancy — two per-instance locks sharing a
        class-level name (every ServeSession's ``_lock``) are peers, and
        nesting them records the self-edge ``name -> name``, which the
        cycle check reports immediately (peer-lock ABBA needs an ordered
        tiebreak, not silence)."""
        held = self._held()
        if any(hid == lock_id for _, hid in held):
            # reentrant re-acquisition (RLock nesting): legal, no edges
            held.append((name, lock_id))
            return
        if held:
            stack = _site_stack()
            tname = threading.current_thread().name
            for outer in dict.fromkeys(n for n, _ in held):
                self._check_edge(outer, name, tname, stack)
        held.append((name, lock_id))

    def note_release(self, name: str, lock_id: int) -> None:
        held = self._held()
        # remove the LAST occurrence: reentrant releases unwind inner-first
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] == lock_id:
                del held[i]
                return

    def note_acquire_failed(self, name: str, lock_id: int) -> None:
        """A non-blocking/timed acquire that returned False: undo the
        held-set push (the edges stay — the *attempted* order is real)."""
        self.note_release(name, lock_id)

    # ---- graph -----------------------------------------------------------
    def _check_edge(
        self, outer: str, inner: str, tname: str, stack: List[str]
    ) -> None:
        with self._guard:
            key = (outer, inner)
            if key in self._edges:
                return  # identical-order re-acquisition: never flagged
            rev = self._edges.get((inner, outer))
            if rev is not None:
                self.violations.append(
                    LockOrderViolation(
                        "inversion",
                        (outer, inner, outer),
                        tname,
                        stack,
                        rev.thread_name,
                        rev.stack,
                    )
                )
            else:
                cycle = self._find_path(inner, outer)
                if cycle is not None:
                    # len-1 path = the degenerate self-edge (two peer
                    # instances sharing one name nested in one thread)
                    nxt = cycle[1] if len(cycle) > 1 else cycle[0]
                    first_hop = self._edges.get((inner, nxt))
                    self.violations.append(
                        LockOrderViolation(
                            "cycle",
                            tuple(cycle) + (inner,),
                            tname,
                            stack,
                            first_hop.thread_name if first_hop else "?",
                            first_hop.stack if first_hop else [],
                        )
                    )
            self._edges[key] = _Edge(tname, stack)
        if self.violations and self.violations[-1].stack is stack:
            _LOG.warning(
                "fugue_tpu lock sanitizer: %s", self.violations[-1].describe()
            )

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS over recorded edges: a path src ~> dst means adding
        dst -> src would close a cycle. Caller holds ``_guard``."""
        adjacency: Dict[str, List[str]] = {}
        for a, b in self._edges:
            adjacency.setdefault(a, []).append(b)
        seen = {src}
        path = [src]

        def dfs(node: str) -> Optional[List[str]]:
            if node == dst:
                return list(path)
            for nxt in adjacency.get(node, ()):
                if nxt in seen:
                    continue
                seen.add(nxt)
                path.append(nxt)
                hit = dfs(nxt)
                if hit is not None:
                    return hit
                path.pop()
            return None

        return dfs(src)

    def report(self) -> str:
        with self._guard:
            violations = list(self.violations)
        if not violations:
            return "lock sanitizer: no ordering violations"
        return "\n".join(v.describe() for v in violations)


class _SanitizedLock:
    """The wrapper a :func:`tracked_lock` call returns while a sanitizer
    is active. Mirrors the ``threading.Lock``/``RLock`` surface the
    codebase uses (``with``, ``acquire``/``release``)."""

    __slots__ = ("_lock", "_san", "name", "reentrant")

    def __init__(self, san: LockSanitizer, name: str, reentrant: bool):
        self._lock: Any = (
            threading.RLock() if reentrant else threading.Lock()
        )
        self._san = san
        self.name = name
        self.reentrant = reentrant
        san.register(name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._san.note_acquire(self.name, id(self))
        ok = self._lock.acquire(blocking, timeout)
        if not ok:
            self._san.note_acquire_failed(self.name, id(self))
        return ok

    def release(self) -> None:
        self._lock.release()
        self._san.note_release(self.name, id(self))

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *args: Any) -> None:
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._lock, "locked", None)
        return bool(locked()) if callable(locked) else False

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"_SanitizedLock({self.name!r}, reentrant={self.reentrant})"


def tracked_lock(name: str, reentrant: bool = False) -> Any:
    """The ONE lock constructor of the concurrency plane: production
    modules call this instead of ``threading.Lock()``/``RLock()`` so
    every lock carries the stable dotted name the FLN101 lock registry
    and the sanitizer's reports share. Disabled (the default) this IS
    ``threading.Lock()``/``RLock()`` — no wrapper, nothing retained."""
    san = _ACTIVE
    if san is None:
        return threading.RLock() if reentrant else threading.Lock()
    return _SanitizedLock(san, name, reentrant)


def active_sanitizer() -> Optional[LockSanitizer]:
    return _ACTIVE


def enable_lock_sanitizer() -> LockSanitizer:
    """Arm a process-wide sanitizer (idempotent: an already-armed one is
    returned). Locks created while armed are wrapped; pre-existing plain
    locks stay plain — arm BEFORE constructing the engine/daemon under
    test."""
    global _ACTIVE
    with _ACTIVE_GUARD:
        if _ACTIVE is None:
            _ACTIVE = LockSanitizer()
        return _ACTIVE


def disable_lock_sanitizer() -> None:
    global _ACTIVE
    with _ACTIVE_GUARD:
        _ACTIVE = None


@contextmanager
def lock_sanitizer() -> Iterator[LockSanitizer]:
    """Test scope: arm the sanitizer for the block, disarm after. The
    yielded sanitizer keeps its graph/violations readable after exit."""
    san = enable_lock_sanitizer()
    try:
        yield san
    finally:
        disable_lock_sanitizer()


def maybe_enable_from_conf(conf: Any) -> Optional[LockSanitizer]:
    """Conf-driven arming (``fugue.debug.lock_sanitizer``): long-lived
    owners (the serving daemon) call this before constructing their
    locks. Off (the default) touches nothing and returns None."""
    try:
        enabled = typed_conf_get(conf, FUGUE_CONF_DEBUG_LOCK_SANITIZER)
    except Exception:
        enabled = False
    if not enabled:
        return None
    return enable_lock_sanitizer()
