"""The backend-author surface: everything needed to write a new
execution-engine backend, custom extension parameter types, or RPC
handlers, re-exported from one place so backend code never imports
internal module paths (role parity: ``/root/reference/fugue/dev.py:1-47``).

A minimal backend implements :class:`ExecutionEngine` (with its
:class:`MapEngine` and :class:`SQLEngine` facets), registers it via
:func:`register_execution_engine`, and optionally adds annotated
transformer parameter types with :func:`fugue_annotated_param` — see
``fugue_tpu/jax_backend/registry.py`` for the in-tree example.
"""

# flake8: noqa

from fugue_tpu.bag.bag import BagDisplay
from fugue_tpu.collections.partition import PartitionCursor, PartitionSpec
from fugue_tpu.collections.sql import (
    StructuredRawSQL,
    TempTableName,
    transpile_sql,
)
from fugue_tpu.collections.yielded import PhysicalYielded, Yielded
from fugue_tpu.dataframe.function_wrapper import (
    AnnotatedParam,
    DataFrameFunctionWrapper,
    FunctionSignatureError,
    fugue_annotated_param,
)
from fugue_tpu.dataset.dataset import DatasetDisplay
from fugue_tpu.exceptions import (
    FugueBug,
    FugueError,
    FugueInterfacelessError,
    FugueWorkflowCompileError,
    FugueWorkflowRuntimeError,
    TaskCancelledError,
    TaskFailure,
    TaskTimeoutError,
    WorkflowRuntimeError,
)
from fugue_tpu.execution.execution_engine import (
    EngineFacet,
    ExecutionEngine,
    MapEngine,
    SQLEngine,
)
from fugue_tpu.execution.factory import (
    make_execution_engine,
    make_sql_engine,
    register_default_execution_engine,
    register_default_sql_engine,
    register_execution_engine,
    register_sql_engine,
)
from fugue_tpu.execution.native_execution_engine import (
    NativeExecutionEngine,
    PandasMapEngine,
)
from fugue_tpu.plugins import fugue_plugin, fugue_tpu_plugin
from fugue_tpu.rpc.base import (
    EmptyRPCHandler,
    RPCClient,
    RPCFunc,
    RPCHandler,
    RPCServer,
    make_rpc_server,
    to_rpc_handler,
)
from fugue_tpu.testing.faults import (
    FaultPlan,
    FaultSpec,
    fault_point,
    inject_faults,
    resource_exhausted,
)
from fugue_tpu.workflow.fault import (
    CancelToken,
    RetryPolicy,
    classify_error,
    execute_with_policy,
)
from fugue_tpu.workflow.manifest import RunManifest
from fugue_tpu.workflow.module import module
from fugue_tpu.workflow.workflow import FugueWorkflow, WorkflowDataFrame
