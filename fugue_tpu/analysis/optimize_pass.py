"""Optimizer-report rule: surface the DAG optimizer's decisions without
executing anything.

FWF501 dry-runs the rewrite phase (:mod:`fugue_tpu.optimize`) over the
analyzed task graph — the optimizer clones internally, so the user's
workflow is untouched — and reports one info-level diagnostic per
applied/declined rewrite with the offending task's name and user
callsite. ``lint_sql()`` and the CLI therefore show what the optimizer
WOULD do to a query before it ever runs."""

from typing import Any, Iterable

from fugue_tpu.analysis.diagnostics import (
    JAX,
    Diagnostic,
    Rule,
    Severity,
    register_rule,
)


@register_rule
class OptimizerRewriteReportRule(Rule):
    code = "FWF501"
    severity = Severity.INFO
    scope = JAX  # the rewrite phase is jax-gated (fugue.optimize=auto)
    # excluded from the pre-run fugue.analysis gate: run() performs the
    # rewrite for real right after and logs the applied notes itself —
    # dry-running here too would double every run's planning cost
    lint_only = True
    description = (
        "reports each rewrite the DAG optimizer would apply or decline "
        "(dry run: projection/filter pushdown, fusion, CSE)"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        from fugue_tpu.constants import FUGUE_CONF_OPTIMIZE
        from fugue_tpu.optimize import optimize_enabled, optimize_tasks
        from fugue_tpu.optimize.rewrite import OFF_VALUES

        mode = str(ctx.conf.get(FUGUE_CONF_OPTIMIZE, "auto")).strip().lower()
        if mode in OFF_VALUES:
            return
        try:
            optimize_enabled(ctx.conf, ctx.engine)
        except ValueError as ex:
            # the same conf makes run() raise before executing anything:
            # the lint surface must flag it, not cheerfully report
            # rewrites for a run that will crash
            yield self.diag(str(ex), severity=Severity.ERROR)
            return
        # engine-agnostic lint mode (engine=None) still dry-runs under
        # "auto": the jax scope selection already narrows when a real
        # non-jax engine is known
        plan = optimize_tasks(ctx.tasks, conf=ctx.conf, engine=ctx.engine)
        for note in plan.notes:
            yield Diagnostic(
                code=self.code,
                severity=self.severity,
                message=note.describe(),
                task_name=note.task_name,
                callsite=note.callsite,
                rule=type(self).__name__,
            )
