"""EXPLAIN: the static plan report over a built-but-unexecuted DAG.

One call renders what ``run()`` would actually execute, without running
anything:

- the **optimizer-rewritten task tree** — the same clone-and-pin rewrite
  phase ``run()`` performs (FWF501's dry-run machinery), so the tree
  shows the fused/pruned/narrowed plan with every applied and declined
  rewrite note attached to its task;
- **propagated schemas** from the analyzer's shared ``schema_pass``
  sweep (full schema, names-only, or unknown-with-reason);
- **estimated rows and device bytes** — statically-known create sizes
  through the FWF303 estimator (the PR 4 dtype-widening admission
  estimate), propagated through row-preserving edges.

The report renders as a text tree (``to_text``) and as JSON
(``to_dict``). EXPLAIN ANALYZE is the same tree with a
:class:`~fugue_tpu.obs.profile.RunProfile` merged in
(:meth:`ExplainReport.attach_profile`): each node gains the observed
rows in/out, device bytes, wall/compile/execute/transfer split, queue
wait and cache events of the run, attributed by the pinned task uuids —
rewrites never change identities, so the static and runtime halves key
on the same ids by construction.
"""

from typing import Any, Dict, List, Optional

from fugue_tpu.analysis.schema_pass import SchemaInfo, propagate
from fugue_tpu.extensions import builtins as _b
from fugue_tpu.workflow.tasks import FugueTask

# extensions that preserve their input's row count exactly — enough to
# thread statically-known create sizes through projection-ish chains
_ROW_PRESERVING = (
    _b.Rename,
    _b.AlterColumns,
    _b.DropColumns,
    _b.SelectColumnsP,
    _b.Assign,
    _b.Fillna,
)


def _ext_name(task: FugueTask) -> str:
    ext = task.extension
    if isinstance(ext, type):
        return ext.__name__
    if callable(ext) and hasattr(ext, "__name__"):
        return ext.__name__
    return type(ext).__name__


def _schema_text(info: SchemaInfo) -> str:
    if info.schema is not None:
        return str(info.schema)
    if info.columns is not None:
        return "columns[" + ",".join(info.columns) + "]"
    return f"unknown({info.reason})" if info.reason else "unknown"


def _fmt_bytes(n: Optional[int]) -> str:
    if n is None:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024.0
    return f"{n}B"  # pragma: no cover - unreachable


class ExplainNode:
    """One task in the (optimizer-rewritten) plan tree."""

    __slots__ = (
        "task",
        "uuid",
        "name",
        "task_type",
        "extension",
        "callsite",
        "schema_text",
        "est_rows",
        "est_device_bytes",
        "rewrites",
        "inputs",
        "profile",
    )

    def __init__(self, task: FugueTask, info: SchemaInfo):
        self.task = task
        self.uuid = task.__uuid__()
        self.name = task.name
        self.task_type = task.task_type
        self.extension = _ext_name(task)
        self.callsite = list(task.callsite or [])
        self.schema_text = _schema_text(info)
        self.est_rows: Optional[int] = None
        self.est_device_bytes: Optional[int] = None
        self.rewrites: List[str] = []
        self.inputs: List[str] = [t.__uuid__() for t in task.inputs]
        self.profile: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "uuid": self.uuid,
            "name": self.name,
            "type": self.task_type,
            "extension": self.extension,
            "callsite": list(self.callsite),
            "schema": self.schema_text,
            "est_rows": self.est_rows,
            "est_device_bytes": self.est_device_bytes,
            "inputs": list(self.inputs),
        }
        if self.rewrites:
            out["rewrites"] = list(self.rewrites)
        if self.profile is not None:
            out["profile"] = dict(self.profile)
        return out


class ExplainReport:
    """The plan report: nodes in dependency order + rewrite notes."""

    def __init__(
        self,
        nodes: List[ExplainNode],
        notes: List[Any],
        optimized: bool,
    ):
        self.nodes = nodes
        self.notes = list(notes)
        self.optimized = optimized
        self._by_uuid = {n.uuid: n for n in nodes}
        self.analyzed = False  # flips when a RunProfile is merged in

    def node(self, uuid: str) -> Optional[ExplainNode]:
        return self._by_uuid.get(uuid)

    @property
    def applied_rewrites(self) -> List[str]:
        return [n.describe() for n in self.notes if n.applied]

    def attach_profile(self, run_profile: Any) -> "ExplainReport":
        """Merge a run's per-task observations (EXPLAIN ANALYZE). Keyed
        by task uuid — the pinned-uuid rewrite invariant is what makes
        the static and runtime trees line up."""
        self.analyzed = True
        for node in self.nodes:
            rec = run_profile.task(node.uuid)
            if rec is not None:
                node.profile = rec.as_dict()
        return self

    # ---- rendering -------------------------------------------------------
    def _node_line(self, node: ExplainNode) -> str:
        head = f"{node.name} [{node.task_type}]"
        parts = [f"schema={node.schema_text}"]
        if node.est_rows is not None:
            parts.append(f"est_rows={node.est_rows}")
        if node.est_device_bytes is not None:
            parts.append(
                f"est_device_bytes={_fmt_bytes(node.est_device_bytes)}"
            )
        p = node.profile
        if p is not None:
            obs = [
                f"rows_in={p.get('rows_in')}",
                f"rows_out={p.get('rows_out')}",
                f"bytes={_fmt_bytes(p.get('device_bytes'))}",
                f"wall={p.get('wall_ms')}ms",
            ]
            phases = p.get("phases") or {}
            for k in ("compile_ms", "execute_ms", "transfer_ms"):
                if k in phases:
                    obs.append(f"{k.split('_')[0]}={phases[k]}ms")
            if p.get("queue_wait_ms"):
                obs.append(f"queued={p['queue_wait_ms']}ms")
            cache = p.get("cache") or {}
            if cache:
                obs.append(f"cache={cache}")
            parts.append("actual(" + " ".join(obs) + ")")
        return head + " " + " ".join(parts)

    def to_text(self) -> str:
        """The plan as an indented tree rooted at the sink tasks (tasks
        no other task consumes). A node with several consumers renders
        its subtree once; later references are ``(ref)`` lines."""
        consumed = {u for n in self.nodes for u in n.inputs}
        sinks = [n for n in self.nodes if n.uuid not in consumed]
        title = "EXPLAIN ANALYZE" if self.analyzed else "EXPLAIN"
        lines: List[str] = [
            f"{title} ({'optimized' if self.optimized else 'unoptimized'} "
            f"plan, {len(self.nodes)} tasks)"
        ]
        rendered: set = set()
        # explicit stack, not recursion: a deep linear DAG the runner
        # executes fine must EXPLAIN fine too (no RecursionError)
        stack = [(sink, 0) for sink in reversed(sinks)]
        while stack:
            node, depth = stack.pop()
            pad = "  " * depth
            if node.uuid in rendered:
                lines.append(f"{pad}(ref) {node.name}")
                continue
            rendered.add(node.uuid)
            lines.append(pad + self._node_line(node))
            for note in node.rewrites:
                lines.append(f"{pad}  * {note}")
            if node.callsite:
                lines.append(f"{pad}  @ {node.callsite[0].strip()}")
            for dep in reversed(node.inputs):
                child = self._by_uuid.get(dep)
                if child is not None:
                    stack.append((child, depth + 1))
        unattached = [
            n.describe()
            for n in self.notes
            if not getattr(n, "task_name", "")
        ]
        if unattached:
            lines.append("rewrites:")
            lines.extend(f"  * {d}" for d in unattached)
        return "\n".join(lines)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "optimized": self.optimized,
            "analyzed": self.analyzed,
            "tasks": [n.to_dict() for n in self.nodes],
            "rewrites": {
                "applied": [n.describe() for n in self.notes if n.applied],
                "declined": [
                    n.describe() for n in self.notes if not n.applied
                ],
            },
        }


def _estimate_rows(tasks: List[FugueTask]) -> Dict[str, Optional[int]]:
    """Statically-known row counts: create sizes (the FWF303 estimator's
    sources) threaded through row-preserving edges."""
    import pandas as pd

    from fugue_tpu.dataframe import DataFrame

    rows: Dict[str, Optional[int]] = {}
    for t in tasks:
        est: Optional[int] = None
        if t.task_type == "create" and t.extension is _b.CreateData:
            data = t.params.get("data", None)
            if isinstance(data, pd.DataFrame):
                est = len(data)
            elif isinstance(data, DataFrame):
                try:
                    if data.is_bounded and data.is_local:
                        est = data.count()
                except Exception:
                    est = None
            elif isinstance(data, (list, tuple)):
                est = len(data)
        elif t.extension in _ROW_PRESERVING and len(t.inputs) == 1:
            est = rows.get(t.inputs[0].__uuid__())
        rows[t.__uuid__()] = est
    return rows


def explain_tasks(
    tasks: List[FugueTask], conf: Any = None, engine: Any = None
) -> ExplainReport:
    """Build the EXPLAIN report for a task list: dry-run the optimizer
    under the same gate semantics as ``run()`` (clone-and-pin — the
    caller's tasks are untouched), propagate schemas, estimate sizes.
    An invalid ``fugue.optimize`` mode raises the same ValueError the
    run would."""
    from fugue_tpu.constants import FUGUE_CONF_OPTIMIZE
    from fugue_tpu.optimize import optimize_enabled, optimize_tasks
    from fugue_tpu.optimize.rewrite import OFF_VALUES

    notes: List[Any] = []
    plan_tasks = list(tasks)
    optimized = False
    # FWF501's gate semantics: "auto" with no known engine still
    # dry-runs (lint mode must show the jax plan), an explicit off stays
    # off, and an invalid mode raises exactly like run() would
    mode = str(
        (conf or {}).get(FUGUE_CONF_OPTIMIZE, "auto")
    ).strip().lower()
    if mode not in OFF_VALUES:
        optimize_enabled(conf, engine)  # raises on an invalid mode
        plan = optimize_tasks(tasks, conf=conf, engine=engine)
        plan_tasks = plan.tasks
        notes = plan.notes
        optimized = True
    infos, _issues = propagate(plan_tasks)
    from fugue_tpu.analysis.schema_pass import UNKNOWN

    nodes = [
        ExplainNode(t, infos.get(id(t), UNKNOWN)) for t in plan_tasks
    ]
    report = ExplainReport(nodes, notes, optimized)
    # attach rewrite notes to the task they describe (by display name —
    # the attribution RewriteNote already carries)
    by_name: Dict[str, ExplainNode] = {}
    for n in nodes:
        by_name.setdefault(n.name, n)
    for note in notes:
        target = by_name.get(getattr(note, "task_name", ""))
        if target is not None:
            target.rewrites.append(note.describe())
    # size estimates: rows through row-preserving edges, bytes via the
    # admission estimator over the propagated full schemas
    est_rows = _estimate_rows(plan_tasks)
    for n in nodes:
        n.est_rows = est_rows.get(n.uuid)
        info = infos.get(id(n.task))
        if (
            n.est_rows is not None
            and info is not None
            and info.schema is not None
        ):
            try:
                from fugue_tpu.jax_backend.memory import (
                    estimate_schema_device_bytes,
                )

                n.est_device_bytes = int(
                    estimate_schema_device_bytes(info.schema, n.est_rows)
                )
            except Exception:
                n.est_device_bytes = None
    return report


def explain_workflow(
    workflow: Any, conf: Any = None, engine: Any = None
) -> ExplainReport:
    """EXPLAIN a built workflow (see :meth:`FugueWorkflow.explain`)."""
    from fugue_tpu.utils.params import ParamDict

    merged = ParamDict(getattr(workflow, "_conf", None))
    engine_conf = getattr(engine, "conf", None)
    if engine_conf is not None:
        merged.update(ParamDict(engine_conf))
    # re-apply the workflow's fugue.optimize* precedence AFTER the
    # engine merge: an engine value still equal to the registered
    # default must not shadow an explicit compile-conf setting, or
    # EXPLAIN would describe a plan run() never executes
    overlay = getattr(workflow, "_overlay_optimize_conf", None)
    if overlay is not None:
        merged = overlay(merged)
    merged.update(ParamDict(conf))
    tasks = getattr(workflow, "tasks", None)
    if tasks is None:
        tasks = list(getattr(workflow, "_tasks", []))
    return explain_tasks(tasks, conf=merged, engine=engine)
