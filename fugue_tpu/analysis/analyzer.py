"""The Analyzer: walks a built-but-unexecuted FugueWorkflow task graph and
runs every registered rule over it.

One schema-propagation sweep is shared by all rules via the
:class:`AnalysisContext`; rules are side-effect free and independently
sandboxed — a crashing rule degrades to a skipped check (logged at
warning so a weakened ``fugue.analysis=error`` gate stays visible),
never a broken run. Typical cost is well under a millisecond per
task: a 50-task DAG analyzes in single-digit milliseconds.
"""

import logging
from typing import Any, Dict, List, Optional, Sequence, Set, Type

import fugue_tpu.analysis.conf_pass  # noqa: F401  (register rules)
import fugue_tpu.analysis.cost_pass  # noqa: F401  (register rules)
import fugue_tpu.analysis.optimize_pass  # noqa: F401  (register rules)
from fugue_tpu.analysis.diagnostics import (
    GENERIC,
    JAX,
    Diagnostic,
    Rule,
    Severity,
    all_rules,
)
from fugue_tpu.analysis.schema_pass import (
    UNKNOWN,
    PropagationIssue,
    SchemaInfo,
    propagate,
)
from fugue_tpu.utils.params import ParamDict
from fugue_tpu.workflow.tasks import FugueTask

_LOG = logging.getLogger("fugue_tpu.analysis")


def _is_jax_engine(engine: Any) -> bool:
    return engine is not None and any(
        c.__name__ == "JaxExecutionEngine" for c in type(engine).__mro__
    )


class AnalysisContext:
    """Everything a rule may consult: the task list (build = dependency
    order), the effective conf, the (optional) target engine, and the
    propagated static schema knowledge."""

    def __init__(
        self,
        tasks: Sequence[FugueTask],
        conf: Any = None,
        engine: Any = None,
    ):
        self.tasks: List[FugueTask] = list(tasks)
        self.conf: ParamDict = conf if isinstance(conf, ParamDict) else ParamDict(conf)
        self.engine = engine
        self.schema_infos: Dict[int, SchemaInfo]
        self.issues: List[PropagationIssue]
        self.schema_infos, self.issues = propagate(self.tasks)

    def info(self, task: FugueTask) -> SchemaInfo:
        """The task's statically-known OUTPUT schema."""
        return self.schema_infos.get(id(task), UNKNOWN)

    def input_info(self, task: FugueTask, index: int = 0) -> SchemaInfo:
        """The statically-known schema of one of the task's inputs."""
        if index >= len(task.inputs):
            return UNKNOWN
        return self.info(task.inputs[index])


class Analyzer:
    """Run rules over a workflow. ``rules=None`` uses the full registry;
    pass explicit rule classes to narrow (e.g. per-rule tests)."""

    def __init__(self, rules: Optional[Sequence[Type[Rule]]] = None):
        self._rules = list(rules) if rules is not None else None

    def analyze(
        self,
        workflow: Any,
        conf: Any = None,
        engine: Any = None,
        scopes: Optional[Set[str]] = None,
        exclude_lint_only: bool = False,
    ) -> List[Diagnostic]:
        """Analyze a built (unexecuted) workflow. ``scopes`` defaults to
        engine-appropriate: with a non-jax engine only generic rules run;
        with no engine at all (lint mode) every scope runs.
        ``exclude_lint_only`` skips rules marked ``lint_only`` — the
        pre-run gate sets it (those rules duplicate work ``run()`` is
        about to do anyway)."""
        if scopes is None:
            if engine is None:
                scopes = {GENERIC, JAX}
            else:
                scopes = {GENERIC} | ({JAX} if _is_jax_engine(engine) else set())
        tasks = getattr(workflow, "tasks", None)
        if tasks is None:
            tasks = getattr(workflow, "_tasks", [])
        ctx = AnalysisContext(tasks, conf=conf, engine=engine)
        out: List[Diagnostic] = []
        for rule_cls in self._rules if self._rules is not None else all_rules():
            if rule_cls.scope not in scopes:
                continue
            if exclude_lint_only and rule_cls.lint_only:
                continue
            try:
                out.extend(rule_cls().check(ctx))
            except Exception as ex:  # defensive: a broken rule is a skipped
                # check, never a broken run — but a skipped check under a
                # fugue.analysis=error gate silently weakens the gate, so
                # the skip itself must be VISIBLE at default log levels
                _LOG.warning(
                    "analysis rule %s crashed and was skipped (its checks "
                    "did not run): %s: %s",
                    rule_cls.__name__,
                    type(ex).__name__,
                    ex,
                )
        out.sort(key=lambda d: -int(d.severity))
        return out


def analyze_workflow(
    workflow: Any,
    conf: Any = None,
    engine: Any = None,
    scopes: Optional[Set[str]] = None,
) -> List[Diagnostic]:
    """Convenience wrapper: full-registry analysis of a workflow."""
    return Analyzer().analyze(workflow, conf=conf, engine=engine, scopes=scopes)


def max_severity(diagnostics: Sequence[Diagnostic]) -> Optional[Severity]:
    return max((d.severity for d in diagnostics), default=None)
