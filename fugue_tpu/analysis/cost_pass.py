"""Engine cost/compatibility prediction + workflow-safety rules.

The jax-scoped rules statically predict expensive engine behavior from
schemas and conf alone, BEFORE ingest or compile:

- FWF301: columns whose dtype has no device representation (decimal,
  binary, nested, null) stay host arrow columns — every op touching them
  pays a host fallback (the engine counts these at runtime in
  ``engine.fallbacks``; this rule predicts them from the schema).
- FWF302: with ``fugue.jax.row_bucket`` at 0, every distinct row count
  compiles its own XLA program; data-dependent row counts (filter,
  dropna, sample, take, distinct, joins) make shapes unbounded, so the
  compile cache can never converge — a recompile hazard.
- FWF303: estimated ingest working set (dtype-widened bytes, same
  estimator the admission controller uses) exceeds the configured
  device-memory budget — spills/host admissions are predicted, not a
  surprise mid-run.

The generic rules catch resume/retry patterns that are unsafe regardless
of engine: non-deterministic checkpoints under ``fugue.workflow.resume``
(FWF401) and retries wrapping non-idempotent outputters (FWF402).
"""

from typing import Any, Iterable, List, Optional, Tuple

from fugue_tpu.analysis.diagnostics import (
    JAX,
    Diagnostic,
    Rule,
    Severity,
    register_rule,
)
from fugue_tpu.constants import (
    FUGUE_CONF_JAX_MEMORY_BUDGET_BYTES,
    FUGUE_CONF_JAX_MEMORY_BUDGET_FRACTION,
    FUGUE_CONF_JAX_ROW_BUCKET,
    FUGUE_CONF_WORKFLOW_RESUME,
    FUGUE_CONF_WORKFLOW_RETRY_MAX_ATTEMPTS,
)
from fugue_tpu.extensions import builtins as _b
from fugue_tpu.workflow.checkpoint import StrongCheckpoint, TableCheckpoint

def _row_varying_exts() -> Tuple[Any, ...]:
    return (
        _b.Filter,
        _b.Dropna,
        _b.Sample,
        _b.Take,
        _b.Distinct,
        _b.RunJoin,
        _b.RunSetOperation,
    )


def _host_only_columns(schema: Any) -> List[str]:
    # the jax backend's own ingest-widening estimator is the single source
    # of truth for what has a device representation (width 0 = host-only);
    # importing it is free — fugue_tpu's package import already loads jax
    from fugue_tpu.jax_backend.memory import _field_device_width

    return [f.name for f in schema.fields if _field_device_width(f.type) == 0]


@register_rule
class HostFallbackDtypeRule(Rule):
    code = "FWF301"
    severity = Severity.WARN
    scope = JAX
    description = (
        "dtypes with no device representation force host fallbacks on the "
        "jax engine"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        for t in ctx.tasks:
            info = ctx.info(t)
            if info.schema is None:
                continue
            offending = _host_only_columns(info.schema)
            if not offending:
                continue
            # only the task that INTRODUCES the columns is flagged — a
            # passthrough chain would repeat the same finding per task
            inherited = set()
            for i in range(len(t.inputs)):
                src = ctx.input_info(t, i)
                if src.schema is not None:
                    inherited.update(_host_only_columns(src.schema))
            fresh = [c for c in offending if c not in inherited]
            if not fresh:
                continue
            extra = ""
            fb = getattr(ctx.engine, "fallbacks", None)
            if fb:
                # the counter dict also carries mem_* memory-governance
                # events (PR 4); only genuine host fallbacks belong here
                host_fb = {
                    k: v for k, v in fb.items() if not k.startswith("mem_")
                }
                if host_fb:
                    extra = (
                        " (engine has already recorded host fallbacks: "
                        f"{host_fb})"
                    )
            yield self.diag(
                f"column(s) {fresh} have no jax device representation "
                "(decimal/binary/nested stay host arrow columns): every op "
                f"touching them runs on the host tier{extra}",
                task=t,
            )


@register_rule
class RecompileHazardRule(Rule):
    code = "FWF302"
    severity = Severity.INFO
    scope = JAX
    description = (
        "data-dependent row counts with row bucketing off: each distinct "
        "shape compiles its own XLA program"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        try:
            bucket = int(ctx.conf.get(FUGUE_CONF_JAX_ROW_BUCKET, 0))
        except Exception:
            return
        if bucket > 0:
            return
        varying = [t for t in ctx.tasks if t.extension in _row_varying_exts()]
        if not varying:
            return
        names = [t.name for t in varying[:3]]
        yield self.diag(
            f"{len(varying)} task(s) produce data-dependent row counts "
            f"(e.g. {', '.join(names)}) while fugue.jax.row_bucket is 0: "
            "every distinct intermediate shape compiles its own XLA "
            "program; set a row bucket to make nearby shapes share "
            "compiled programs",
            task=varying[0],
        )


def _estimate_create_bytes(task: Any) -> Optional[int]:
    """Dtype-widened device estimate of a CreateData task's data, or None
    when rows/schema aren't statically known. Never materializes arrow."""
    import pandas as pd

    from fugue_tpu.dataframe import DataFrame
    from fugue_tpu.schema import Schema

    data = task.params.get("data", None)
    schema = task.params.get("schema", None)
    rows: Optional[int] = None
    sch: Optional[Schema] = None
    if isinstance(data, pd.DataFrame):
        rows = len(data)
        sch = Schema(schema) if schema is not None else Schema(data)
    elif isinstance(data, DataFrame):
        try:
            if data.is_bounded and data.is_local:
                rows = data.count()
        except Exception:
            rows = None
        sch = data.schema
    elif isinstance(data, (list, tuple)) and schema is not None:
        rows = len(data)
        sch = Schema(schema)
    if rows is None or sch is None:
        return None
    from fugue_tpu.jax_backend.memory import estimate_schema_device_bytes

    return estimate_schema_device_bytes(sch, rows)


@register_rule
class MemoryBudgetRule(Rule):
    code = "FWF303"
    severity = Severity.WARN
    scope = JAX
    description = (
        "estimated device working set exceeds the memory budget: spills / "
        "host admissions predicted"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        try:
            budget = int(ctx.conf.get(FUGUE_CONF_JAX_MEMORY_BUDGET_BYTES, 0))
        except Exception:
            return
        if budget <= 0:
            mem = getattr(ctx.engine, "memory_stats", None)
            if isinstance(mem, dict) and mem.get("enabled"):
                budget = int(mem.get("budget_bytes", 0) or 0)
        if budget <= 0:
            # governance enabled via budget_fraction alone: resolve it
            # against the default (all-devices) capacity, the same
            # detection a lint-mode run has no engine/mesh to ask
            try:
                frac = float(
                    ctx.conf.get(FUGUE_CONF_JAX_MEMORY_BUDGET_FRACTION, 0.0)
                )
            except Exception:
                frac = 0.0
            if frac > 0:
                import jax

                from fugue_tpu.jax_backend.memory import detect_devices_capacity

                budget = int(detect_devices_capacity(jax.devices()) * frac)
        if budget <= 0:
            return
        total = 0
        biggest: Tuple[int, Any] = (0, None)
        for t in ctx.tasks:
            if not (t.task_type == "create" and t.extension is _b.CreateData):
                continue
            est = _estimate_create_bytes(t)
            if est is None:
                continue
            if est > budget:
                # the admission controller never places this frame on the
                # device tier, so it contributes nothing to the DEVICE
                # working set — flag it and keep it out of the spill math
                yield self.diag(
                    f"a single ingested frame is estimated at ~{est} device "
                    f"bytes, above the {budget}-byte budget: the admission "
                    "controller will place it on the host tier directly",
                    task=t,
                )
                continue
            total += est
            if est > biggest[0]:
                biggest = (est, t)
        if total > budget and biggest[1] is not None:
            yield self.diag(
                f"estimated ingest working set ~{total} device bytes "
                f"exceeds the {budget}-byte budget "
                f"(fugue.jax.memory.budget_bytes): LRU spills to the host "
                "tier are predicted under admission pressure",
                task=biggest[1],
            )


def _max_attempts(ctx: Any, task: Any) -> int:
    try:
        attempts = int(ctx.conf.get(FUGUE_CONF_WORKFLOW_RETRY_MAX_ATTEMPTS, 1))
    except Exception:
        attempts = 1
    ov = getattr(task, "fault_override", None) or {}
    return int(ov.get("max_attempts", attempts))


@register_rule
class ResumeNonDeterministicCheckpointRule(Rule):
    code = "FWF401"
    severity = Severity.ERROR
    description = (
        "non-deterministic checkpoint under fugue.workflow.resume: the "
        "manifest can never serve it, so a resumed run silently recomputes"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        try:
            resume = bool(ctx.conf.get(FUGUE_CONF_WORKFLOW_RESUME, False))
        except Exception:
            resume = False
        if not resume:
            return
        for t in ctx.tasks:
            cp = t.checkpoint
            if isinstance(cp, (StrongCheckpoint, TableCheckpoint)) and not getattr(
                cp, "_deterministic", True
            ):
                yield self.diag(
                    "fugue.workflow.resume is on but this task's checkpoint "
                    "is non-deterministic (random id, temp storage): a "
                    "crashed run can never resume from it — use "
                    "deterministic_checkpoint() for resume-safe artifacts",
                    task=t,
                )


@register_rule
class RetryNonIdempotentOutputterRule(Rule):
    code = "FWF402"
    severity = Severity.WARN
    description = (
        "retries wrap a non-idempotent outputter: a partial side effect "
        "may be applied more than once"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        for t in ctx.tasks:
            if _max_attempts(ctx, t) <= 1:
                continue
            # SaveAndUse is a PROCESS task but shares Save's append hazard:
            # the retry loop wraps its side-effecting write all the same
            if t.extension in (_b.Save, _b.SaveAndUse):
                if str(t.params.get("mode", "overwrite")).lower() == "append":
                    yield self.diag(
                        "retries are enabled and this append-mode save is "
                        "not idempotent: a retried attempt can append the "
                        "same rows twice — use overwrite mode or "
                        "max_attempts=1 for this task",
                        task=t,
                    )
            elif t.task_type == "output" and t.extension not in (
                _b.Show, _b.AssertEqFunc, _b.AssertNotEqFunc
            ):
                yield self.diag(
                    "retries are enabled around a user outputter whose side "
                    "effects the framework cannot prove idempotent; a "
                    "transient failure after a partial write replays them",
                    task=t,
                    severity=Severity.INFO,
                )
