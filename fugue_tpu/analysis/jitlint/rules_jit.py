"""The FJX jit-hazard rules.

Every rule runs over the :class:`~fugue_tpu.analysis.jitlint.boundaries.
JitContext` — discovered jit regions with taint-annotated frames — and
emits :class:`SourceDiagnostic` findings. The division of labor with the
runtime retrace sentinel (:mod:`fugue_tpu.testing.retrace`): these rules
see hazards *lexically* before any dispatch happens; the sentinel counts
the retraces that actually occur. Same hazard, two planes.

Codes:

* **FJX201** shape-from-value: a traced value in a shape position is a
  trace-time crash; a host-varying value there recompiles per distinct
  value unless laundered through a pow2 bucket.
* **FJX202** host sync inside jit: ``float()``/``int()``/``bool()``/
  ``.item()``/``np.asarray`` on a traced value, or python control flow
  branching on one.
* **FJX203** dtype promotion: literal ``jnp.array`` without an explicit
  dtype, and float python literals in arithmetic with traced operands.
* **FJX204** donation miss: a jitted updater whose return overwrites its
  own argument at every call site should donate that argument.
* **FJX205** in-jit side effects: ``print``/``fault_point``/mutation of
  closed-over state executes at trace time only and is silently absent
  from the compiled program.
"""

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from fugue_tpu.analysis.codelint.engine import call_name, dotted_name
from fugue_tpu.analysis.codelint.model import SourceDiagnostic
from fugue_tpu.analysis.diagnostics import Severity
from fugue_tpu.analysis.jitlint.boundaries import JitContext, JitFrame
from fugue_tpu.analysis.jitlint.model import JitRule, register_jit_rule

#: module-alias prefixes of the array namespaces (distinguishes
#: ``jnp.reshape(x, shape)`` from the method form ``x.reshape(*shape)``)
_ARRAY_NAMESPACES = {"jnp", "np", "numpy", "jax.numpy", "lax", "jax.lax", "jax"}

#: host-numpy prefixes: materializing a traced value through these is a
#: device->host sync (FJX202)
_HOST_NP = {"np", "numpy", "onp"}

#: fn-last-component -> positional shape-arg indices ("all" = every arg)
_SHAPE_POSITIONS: Dict[str, object] = {
    "zeros": (0,),
    "ones": (0,),
    "empty": (0,),
    "full": (0,),
    "arange": "all",
    "eye": (0, 1),
    "resize": (1,),
    "broadcast_to": (1,),
    "tile": (1,),
    "linspace": (2,),
}

#: kwargs that are shape positions wherever they appear on these calls
_SHAPE_KWARGS = {"shape", "total_repeat_length", "size", "fill_value_shape"}

_MUTATORS = {
    "append",
    "extend",
    "insert",
    "remove",
    "pop",
    "clear",
    "update",
    "add",
    "setdefault",
    "popitem",
    "discard",
}


def _ns_of(name: str) -> Optional[str]:
    """``jnp.zeros`` -> ``jnp``; bare ``zeros`` -> None."""
    return name.rsplit(".", 1)[0] if "." in name else None


def _shape_exprs(call: ast.Call, name: str) -> List[ast.AST]:
    """The argument expressions of ``call`` that land in shape
    positions, or [] when the call doesn't build/reshape arrays."""
    last = name.rsplit(".", 1)[-1]
    ns = _ns_of(name)
    out: List[ast.AST] = []
    if last == "reshape":
        if ns in _ARRAY_NAMESPACES:
            if len(call.args) >= 2:
                out.append(call.args[1])
        else:  # method form: every positional arg is a dim
            out.extend(call.args)
    elif last == "dynamic_slice":
        out.extend(call.args[2:])
    elif last in _SHAPE_POSITIONS:
        spec = _SHAPE_POSITIONS[last]
        if spec == "all":
            out.extend(call.args)
        else:
            for i in spec:  # type: ignore[union-attr]
                if i < len(call.args):
                    out.append(call.args[i])
    for kw in call.keywords:
        if kw.arg in _SHAPE_KWARGS:
            out.append(kw.value)
    return out


def _frame_calls(frame: JitFrame) -> Iterable[ast.Call]:
    for node in ast.walk(frame.node):
        if isinstance(node, ast.Call):
            yield node


def _dedup(diags: Iterable[SourceDiagnostic]) -> List[SourceDiagnostic]:
    seen: Set[Tuple[str, str, int, str]] = set()
    out: List[SourceDiagnostic] = []
    for d in diags:
        key = (d.code, d.path, d.line, d.message[:60])
        if key in seen:
            continue
        seen.add(key)
        out.append(d)
    return out


@register_jit_rule
class ShapeFromValue(JitRule):
    code = "FJX201"
    severity = Severity.ERROR
    description = (
        "traced or host-varying value flows into a shape position inside "
        "a jit boundary (trace-time crash / per-value recompile)"
    )

    def check(self, ctx: JitContext) -> List[SourceDiagnostic]:
        out: List[SourceDiagnostic] = []
        for frame in ctx.iter_frames():
            mod = frame.mod
            for call in _frame_calls(frame):
                name = call_name(call)
                if name is None:
                    continue
                for expr in _shape_exprs(call, name):
                    traced, host = frame.expr_taint(expr)
                    if traced:
                        out.append(
                            self.diag(
                                f"traced value in shape position of {name}(): "
                                "shapes must be concrete at trace time — this "
                                "raises ConcretizationTypeError; hoist the "
                                "shape computation out of the jit or make the "
                                "driving argument static",
                                path=mod.rel,
                                line=expr.lineno,
                                qualname=mod.qualname(call),
                            )
                        )
                    elif host:
                        out.append(
                            self.diag(
                                f"host-varying value in shape position of "
                                f"{name}(): every distinct value recompiles "
                                "the program — launder it through a pow2 "
                                "bucket helper (padded_len/pad_spans/"
                                "row_bucket) so lengths collapse onto "
                                "O(log n) programs",
                                path=mod.rel,
                                line=expr.lineno,
                                qualname=mod.qualname(call),
                            )
                        )
            # slice bounds are shape positions too: x[:n] with traced n
            # fails concretization, host-varying n retraces
            for node in ast.walk(frame.node):
                if not isinstance(node, ast.Subscript) or not isinstance(
                    node.slice, ast.Slice
                ):
                    continue
                for part in (node.slice.lower, node.slice.upper, node.slice.step):
                    if part is None:
                        continue
                    traced, host = frame.expr_taint(part)
                    if traced:
                        out.append(
                            self.diag(
                                "traced value as a slice bound: static slices "
                                "need concrete bounds — use "
                                "lax.dynamic_slice with a bucketed size or a "
                                "mask instead",
                                path=mod.rel,
                                line=part.lineno,
                                qualname=mod.qualname(node),
                            )
                        )
                    elif host:
                        out.append(
                            self.diag(
                                "host-varying slice bound inside jit: every "
                                "distinct bound recompiles — bucket it "
                                "(padded_len/row_bucket) or slice outside "
                                "the boundary",
                                path=mod.rel,
                                line=part.lineno,
                                qualname=mod.qualname(node),
                            )
                        )
        return _dedup(out)


@register_jit_rule
class HostSyncInJit(JitRule):
    code = "FJX202"
    severity = Severity.ERROR
    description = (
        "device->host sync inside a jit boundary (float()/int()/.item()/"
        "np.asarray on a traced value, or python branching on one)"
    )

    def check(self, ctx: JitContext) -> List[SourceDiagnostic]:
        out: List[SourceDiagnostic] = []
        for frame in ctx.iter_frames():
            mod = frame.mod
            for call in _frame_calls(frame):
                name = call_name(call)
                if name is None:
                    continue
                last = name.rsplit(".", 1)[-1]
                ns = _ns_of(name)
                if (
                    name in ("float", "int", "bool")
                    and call.args
                    and any(frame.is_traced(a) for a in call.args)
                ):
                    out.append(
                        self.diag(
                            f"{name}() on a traced value inside jit forces a "
                            "device sync at trace time (and fails under "
                            "abstract tracing) — keep it as a jnp scalar or "
                            "compute it outside the boundary",
                            path=mod.rel,
                            line=call.lineno,
                            qualname=mod.qualname(call),
                        )
                    )
                elif last in ("item", "tolist") and isinstance(
                    call.func, ast.Attribute
                ):
                    if frame.is_traced(call.func.value):
                        out.append(
                            self.diag(
                                f".{last}() on a traced value inside jit is a "
                                "host materialization — it cannot execute "
                                "under tracing; return the array and read it "
                                "outside the boundary",
                                path=mod.rel,
                                line=call.lineno,
                                qualname=mod.qualname(call),
                            )
                        )
                elif (
                    ns in _HOST_NP
                    and last in ("asarray", "array")
                    and any(frame.is_traced(a) for a in call.args)
                ):
                    out.append(
                        self.diag(
                            f"{name}() on a traced value inside jit pulls the "
                            "array to host numpy — use jnp and keep the value "
                            "on device",
                            path=mod.rel,
                            line=call.lineno,
                            qualname=mod.qualname(call),
                        )
                    )
            for node in ast.walk(frame.node):
                if isinstance(node, (ast.If, ast.While)) and frame.is_traced(
                    node.test
                ):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    out.append(
                        self.diag(
                            f"python `{kind}` on a traced value inside jit: "
                            "abstract tracers have no truth value — use "
                            "jnp.where / lax.cond / lax.while_loop",
                            path=mod.rel,
                            line=node.lineno,
                            qualname=mod.qualname(node),
                        )
                    )
                elif isinstance(node, ast.Assert) and frame.is_traced(node.test):
                    out.append(
                        self.diag(
                            "assert on a traced value inside jit branches on "
                            "a tracer — use checkify or move the check "
                            "outside the boundary",
                            path=mod.rel,
                            line=node.lineno,
                            qualname=mod.qualname(node),
                        )
                    )
        return _dedup(out)


def _literal_float(node: ast.AST) -> bool:
    """True when the expression is (a nest of) python literals containing
    at least one float — the implicit-dtype hazard case."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, float)
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        return all(_literal_only(el) for el in node.elts) and any(
            _literal_float(el) for el in node.elts
        )
    if isinstance(node, ast.UnaryOp):
        return _literal_float(node.operand)
    return False


def _literal_only(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        return all(_literal_only(el) for el in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _literal_only(node.operand)
    return False


@register_jit_rule
class DtypePromotion(JitRule):
    code = "FJX203"
    severity = Severity.ERROR
    description = (
        "dtype-promotion hazard inside a jit boundary (literal jnp.array "
        "without dtype; float literal arithmetic with traced operands)"
    )

    def check(self, ctx: JitContext) -> List[SourceDiagnostic]:
        out: List[SourceDiagnostic] = []
        for frame in ctx.iter_frames():
            mod = frame.mod
            for call in _frame_calls(frame):
                name = call_name(call)
                if name is None:
                    continue
                last = name.rsplit(".", 1)[-1]
                ns = _ns_of(name)
                if (
                    ns in ("jnp", "jax.numpy")
                    and last in ("array", "asarray")
                    and call.args
                    and _literal_float(call.args[0])
                    and not any(kw.arg == "dtype" for kw in call.keywords)
                ):
                    out.append(
                        self.diag(
                            f"{name}() over float literals without an "
                            "explicit dtype inside jit: the result is "
                            "weakly-typed and its width follows the x64 "
                            "flag — pass dtype= so programs hash identically "
                            "across configurations",
                            path=mod.rel,
                            line=call.lineno,
                            qualname=mod.qualname(call),
                        )
                    )
            for node in ast.walk(frame.node):
                if not isinstance(node, ast.BinOp):
                    continue
                for lit, other in (
                    (node.left, node.right),
                    (node.right, node.left),
                ):
                    if (
                        isinstance(lit, ast.Constant)
                        and isinstance(lit.value, float)
                        and frame.is_traced(other)
                    ):
                        out.append(
                            self.diag(
                                "float python literal in arithmetic with a "
                                "traced operand: integer operands promote to "
                                "weak float — pin the dtype (jnp.float32("
                                "...)) if the promotion is intended",
                                path=mod.rel,
                                line=node.lineno,
                                qualname=mod.qualname(node),
                                severity=Severity.WARN,
                            )
                        )
                        break
        return _dedup(out)


@register_jit_rule
class DonationMiss(JitRule):
    code = "FJX204"
    severity = Severity.ERROR
    description = (
        "jitted updater overwritten by its own return at every call site "
        "without donate_argnums (double-buffers the state)"
    )

    def check(self, ctx: JitContext) -> List[SourceDiagnostic]:
        out: List[SourceDiagnostic] = []
        for b in ctx.bindings:
            if b.kind != "jax.jit" or b.donated:
                continue
            if not b.call_sites:
                continue
            if all(overwrite for _, overwrite in b.call_sites):
                sites = ", ".join(str(line) for line, _ in b.call_sites[:4])
                out.append(
                    self.diag(
                        f"jitted updater '{b.target}' is overwritten by its "
                        f"own return at every call site (line {sites}): pass "
                        "donate_argnums=0 so XLA reuses the input buffer "
                        "instead of double-buffering the state",
                        path=b.mod.rel,
                        line=b.line,
                        qualname=b.qualname,
                    )
                )
        return _dedup(out)


@register_jit_rule
class InJitSideEffects(JitRule):
    code = "FJX205"
    severity = Severity.ERROR
    description = (
        "side effect inside a jit boundary (print/fault_point/mutation of "
        "closed-over state) executes at trace time only"
    )

    def check(self, ctx: JitContext) -> List[SourceDiagnostic]:
        out: List[SourceDiagnostic] = []
        for frame in ctx.iter_frames():
            mod = frame.mod
            for call in _frame_calls(frame):
                name = call_name(call)
                if name is None:
                    continue
                last = name.rsplit(".", 1)[-1]
                if name in ("print", "breakpoint"):
                    out.append(
                        self.diag(
                            f"{name}() inside jit runs at trace time only — "
                            "silent on every cached dispatch; use "
                            "jax.debug.print for traced values",
                            path=mod.rel,
                            line=call.lineno,
                            qualname=mod.qualname(call),
                        )
                    )
                elif last == "fault_point":
                    out.append(
                        self.diag(
                            "fault_point() inside a traced program fires at "
                            "trace time only and is absent from the compiled "
                            "executable — hoist the hook to the dispatch "
                            "site",
                            path=mod.rel,
                            line=call.lineno,
                            qualname=mod.qualname(call),
                        )
                    )
                elif (
                    last in _MUTATORS
                    and isinstance(call.func, ast.Attribute)
                ):
                    base = call.func.value
                    while isinstance(base, ast.Attribute):
                        base = base.value
                    if (
                        isinstance(base, ast.Name)
                        and base.id not in frame.bound
                        and base.id not in frame.inherited_bound
                    ):
                        out.append(
                            self.diag(
                                f"mutation of closed-over '{base.id}' inside "
                                "jit happens once at trace time and never "
                                "again on cached dispatches — return the new "
                                "value instead",
                                path=mod.rel,
                                line=call.lineno,
                                qualname=mod.qualname(call),
                            )
                        )
        return _dedup(out)
