"""Rule model of the JIT-HAZARD linter — the third static-analysis
plane (codes ``FJX###``). Findings reuse the source linter's
:class:`~fugue_tpu.analysis.codelint.model.SourceDiagnostic` (same
``file:line`` + qualname attribution, same baseline match key); the rule
registry is separate so the FJX sweep and the FLN sweep stay independent
front doors with independent baselines."""

from typing import Dict, List, Optional, Type

from fugue_tpu.analysis.codelint.model import SourceDiagnostic
from fugue_tpu.analysis.diagnostics import Severity


class JitRule:
    """One jit-hazard check with a stable ``FJX###`` code. Rules are
    side-effect free; ``check`` runs over the whole :class:`JitContext`
    (module set + discovered jit regions + taint), not per file."""

    code: str = "FJX000"
    severity: Severity = Severity.ERROR
    description: str = ""

    def check(self, ctx):  # pragma: no cover - abstract
        raise NotImplementedError

    def diag(
        self,
        message: str,
        path: str = "",
        line: int = 0,
        qualname: str = "",
        severity: Optional[Severity] = None,
    ) -> SourceDiagnostic:
        return SourceDiagnostic(
            code=self.code,
            severity=self.severity if severity is None else severity,
            message=message,
            path=path,
            line=line,
            qualname=qualname,
            rule=type(self).__name__,
        )


_JIT_RULES: Dict[str, Type[JitRule]] = {}


def register_jit_rule(cls: Type[JitRule]) -> Type[JitRule]:
    """Class decorator: register by stable code (re-registering a code
    replaces the rule, same contract as the FLN/FWF registries)."""
    _JIT_RULES[cls.code] = cls
    return cls


def all_jit_rules() -> List[Type[JitRule]]:
    return [_JIT_RULES[k] for k in sorted(_JIT_RULES)]


def registered_jit_codes() -> List[str]:
    """Stable rule codes, for the baseline completeness check: a
    baseline entry naming an unregistered FJX code is rot (the rule was
    renamed/removed) and must be reported, never silently ignored."""
    return sorted(_JIT_RULES)
