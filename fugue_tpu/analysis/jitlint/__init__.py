"""The JIT-HAZARD linter — the third static-analysis plane (``FJX###``).

Where the workflow analyzer (``FWF``) reads user DAGs and the source
linter (``FLN``) reads the codebase's concurrency/vocabulary discipline,
this plane reads every **jit boundary**: the callables jax will trace
(direct ``jax.jit``/``shard_map`` calls, ``blocks.jit_row_sharded``,
``engine._jit_cached`` call sites, plus their same-module call-graph
closure) and runs an intra-procedural two-taint dataflow over them for
the recompile/host-sync/dtype/donation/side-effect hazards that bench
gates like ``zero_recompile_warm`` only catch after the fact.

Static scope is honest: same-module resolution, no cross-module data
flow, attribute access breaks taint. The runtime twin —
:mod:`fugue_tpu.testing.retrace` — counts the retraces that actually
happen; a hazard should trip both planes (see the seeded two-plane test
in ``tests/fugue_tpu/jax_backend/test_retrace_sentinel.py``).

Front door::

    python -m fugue_tpu.analysis --lint-jit [dir]

Exit codes follow the established contract: 0 clean (warnings allowed),
1 error findings, 2 the lint itself could not run.
"""

from typing import List, Optional

from fugue_tpu.analysis.codelint.engine import (
    ModuleInfo,
    load_tree,
)
from fugue_tpu.analysis.codelint.model import SourceDiagnostic
from fugue_tpu.analysis.diagnostics import Severity
from fugue_tpu.analysis.jitlint.boundaries import (
    BUCKET_SANITIZERS,
    JitBinding,
    JitContext,
    JitFrame,
    JitRegion,
)
from fugue_tpu.analysis.jitlint.model import (
    JitRule,
    all_jit_rules,
    register_jit_rule,
    registered_jit_codes,
)

__all__ = [
    "JitRule",
    "JitContext",
    "JitRegion",
    "JitFrame",
    "JitBinding",
    "BUCKET_SANITIZERS",
    "register_jit_rule",
    "all_jit_rules",
    "registered_jit_codes",
    "lint_modules_jit",
    "lint_tree_jit",
    "lint_text_jit",
]


def lint_modules_jit(modules: List[ModuleInfo]) -> List[SourceDiagnostic]:
    import fugue_tpu.analysis.jitlint.rules_jit  # noqa: F401

    ctx = JitContext(modules)
    out: List[SourceDiagnostic] = []
    for rule_cls in all_jit_rules():
        out.extend(rule_cls().check(ctx))
    out.sort(key=lambda d: (-int(d.severity), d.path, d.line))
    return out


def lint_tree_jit(root: Optional[str] = None) -> List[SourceDiagnostic]:
    """Lint every ``.py`` under ``root`` (default: the installed
    fugue_tpu package). Parse failures surface as FJX001 errors, never a
    crashed lint."""
    modules, problems = load_tree(root)
    remapped = [
        SourceDiagnostic(
            "FJX001", p.severity, p.message, path=p.path, line=p.line, rule="parse"
        )
        for p in problems
    ]
    return remapped + lint_modules_jit(modules)


def lint_text_jit(source: str, rel: str = "fugue_tpu/fixture.py") -> List[SourceDiagnostic]:
    """Lint one in-memory module (the fixture-corpus entry point)."""
    return lint_modules_jit([ModuleInfo(rel, rel, source)])
