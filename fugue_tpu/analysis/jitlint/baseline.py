"""Justification-required baseline for accepted FJX exceptions.

Same contract as the FLN baseline (:mod:`fugue_tpu.analysis.codelint.
baseline`), same entry shape — ``code``/``file``/``context``/
``justification`` — but its own file and its own meta-codes so the two
planes gate independently:

* **FJX002** — the baseline itself is broken (unreadable JSON, entry
  without a justification). Error.
* **FJX003** — stale entry: matched nothing, the hazard was fixed,
  prune it. Warn (the baseline can only shrink).
* **FJX004** — entry names an FJX code no registered rule owns: the
  rule was renamed or removed and the entry is dead weight that would
  otherwise suppress nothing forever. Error.
"""

import json
import os
from typing import List, Optional, Tuple

from fugue_tpu.analysis.codelint.baseline import BaselineEntry, apply_baseline
from fugue_tpu.analysis.codelint.model import SourceDiagnostic
from fugue_tpu.analysis.diagnostics import Severity
from fugue_tpu.analysis.jitlint.model import registered_jit_codes

__all__ = [
    "BaselineEntry",
    "apply_baseline",
    "DEFAULT_BASELINE",
    "load_jit_baseline",
    "stale_jit_diags",
    "completeness_diags",
]

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)


def load_jit_baseline(
    path: Optional[str] = None,
) -> Tuple[List[BaselineEntry], List[SourceDiagnostic]]:
    """Entries plus any problems with the baseline ITSELF as error
    diagnostics (unreadable file -> FJX002, missing justification ->
    FJX002, unregistered rule code -> FJX004)."""
    path = path or DEFAULT_BASELINE
    problems: List[SourceDiagnostic] = []
    if not os.path.isfile(path):
        return [], problems
    try:
        with open(path, "r") as fp:
            payload = json.load(fp)
    except (OSError, ValueError) as ex:
        return [], [
            SourceDiagnostic(
                "FJX002",
                Severity.ERROR,
                f"unreadable jit baseline: {type(ex).__name__}: {ex}",
                path=path,
                rule="baseline",
            )
        ]
    import fugue_tpu.analysis.jitlint.rules_jit  # noqa: F401  (registers FJX rules)

    known = set(registered_jit_codes())
    entries: List[BaselineEntry] = []
    for i, raw in enumerate(payload.get("entries", [])):
        entry = BaselineEntry(
            str(raw.get("code", "")),
            str(raw.get("file", "")),
            str(raw.get("context", "")),
            str(raw.get("justification", "")).strip(),
        )
        if entry.justification == "":
            problems.append(
                SourceDiagnostic(
                    "FJX002",
                    Severity.ERROR,
                    f"jit baseline entry #{i} ({entry.code} {entry.file}) "
                    "has no justification: accepted exceptions must say WHY",
                    path=path,
                    rule="baseline",
                )
            )
            continue
        if entry.code not in known:
            problems.append(
                SourceDiagnostic(
                    "FJX004",
                    Severity.ERROR,
                    f"jit baseline entry #{i} names '{entry.code}' which no "
                    "registered FJX rule owns — the rule was renamed or "
                    "removed; update or prune the entry",
                    path=path,
                    rule="baseline",
                )
            )
            continue
        entries.append(entry)
    return entries, problems


def stale_jit_diags(
    stale: List[BaselineEntry], path: Optional[str] = None
) -> List[SourceDiagnostic]:
    return [
        SourceDiagnostic(
            "FJX003",
            Severity.WARN,
            f"stale jit baseline entry: {e.code} {e.file} [{e.context}] no "
            "longer matches any finding — the hazard was fixed, prune the "
            "entry",
            path=path or DEFAULT_BASELINE,
            rule="baseline",
        )
        for e in stale
    ]


def completeness_diags(path: Optional[str] = None) -> List[SourceDiagnostic]:
    """Standalone FJX004 sweep for the self-test: every code in the
    shipped baseline must be a registered rule."""
    _, problems = load_jit_baseline(path)
    return [p for p in problems if p.code == "FJX004"]
