"""Jit-boundary discovery and the two-taint dataflow the FJX rules run
on.

A *region* is one callable that jax will trace: the target of a direct
``jax.jit``/``shard_map`` call, of the engine's ``_jit_cached`` wrapper,
of ``blocks.jit_row_sharded``, or a ``@jax.jit``/``@partial(jax.jit,
...)``-decorated function. Each region expands into *frames*: the root
function plus every same-module function it calls (taint propagates
through the call arguments), so a hazard buried one helper deep is still
attributed to the jit boundary that traces it.

Two taints flow through each frame, and they mean different failures:

* **traced** — the value is (derived from) a traced parameter. In a
  shape position it is a trace-time crash (ConcretizationTypeError);
  fed to ``float()``/``if`` it is a host sync.
* **host** — the value varies per call but is folded into program
  identity: a ``static_argnums`` parameter, a ``partial``-bound value,
  or an enclosing function's parameter captured by closure. In a shape
  position it recompiles per distinct value unless laundered through a
  pow2 bucket.

Laundering is modeled: a call to a bucket helper (``padded_len``,
``pad_spans``, ``row_bucket``, ...) clears both taints, attribute access
(``x.shape``) breaks taint (shapes are static at trace time), and
assignment replaces a variable's taint. The walk is flow-sensitive in
statement order with a second pass for loop-carried values.
"""

import ast
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from fugue_tpu.analysis.codelint.engine import (
    LintContext,
    ModuleInfo,
    call_name,
    dotted_name,
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

#: call last-components that launder a host/traced value into a bounded
#: bucket (clears both taints): the pow2 discipline the engine uses so
#: per-length values collapse onto O(log n) programs.
BUCKET_SANITIZERS = {
    "padded_len",
    "pad_spans",
    "row_bucket",
    "bucket_len",
    "_bucket",
    "_bucket_len",
    "next_pow2",
    "pow2",
    "pow2_bucket",
}

#: builtins whose result is static at trace time regardless of operands.
_CLEAN_CALLS = {"isinstance", "hasattr", "callable", "type", "len", "getattr"}


# ---------------------------------------------------------------------------
# regions / frames
# ---------------------------------------------------------------------------
class JitFrame:
    """One function body analyzed under a jit boundary, with its
    parameter classification and (after :meth:`run`) a per-expression
    taint map the rules query."""

    def __init__(
        self,
        region: "JitRegion",
        mod: ModuleInfo,
        node: ast.AST,
        traced: Set[str],
        host: Set[str],
        depth: int = 0,
    ):
        self.region = region
        self.mod = mod
        self.node = node  # FunctionDef / AsyncFunctionDef / Lambda
        self.traced_params = set(traced)
        self.host_params = set(host)
        self.depth = depth
        # id(expr) -> (traced, host) at evaluation time
        self.taint_at: Dict[int, Tuple[bool, bool]] = {}
        # every name bound inside the frame (params, assigns, for/with
        # targets, imports): a mutation of anything NOT here is a
        # closed-over side effect (FJX205)
        self.bound: Set[str] = set()
        # names bound in ANCESTOR frames of the same region: mutating
        # those is trace-local accumulation (the payload-dedup slot
        # pattern), not an escaping side effect
        self.inherited_bound: Set[str] = set()
        self._ran = False

    @property
    def qualname(self) -> str:
        name = getattr(self.node, "name", "<lambda>")
        enclosing = self.mod.qualname(self.node)
        return f"{enclosing}.{name}" if enclosing else name

    def body(self) -> List[ast.stmt]:
        body = getattr(self.node, "body", None)
        if isinstance(body, list):
            return body
        # Lambda: wrap the expression as a statement-like list
        return [ast.Expr(value=self.node.body)]  # type: ignore[attr-defined]

    def run(self) -> None:
        if self._ran:
            return
        self._ran = True
        _TaintWalker(self).run()

    def expr_taint(self, node: ast.AST) -> Tuple[bool, bool]:
        return self.taint_at.get(id(node), (False, False))

    def is_traced(self, node: ast.AST) -> bool:
        return self.expr_taint(node)[0]

    def is_host(self, node: ast.AST) -> bool:
        return self.expr_taint(node)[1]


class JitRegion:
    """One discovered jit boundary and the frames it traces."""

    def __init__(self, mod: ModuleInfo, kind: str, line: int, qualname: str):
        self.mod = mod
        self.kind = kind  # jax.jit / shard_map / _jit_cached / ...
        self.line = line
        self.qualname = qualname  # enclosing qualname of the boundary
        self.frames: List[JitFrame] = []


class JitBinding:
    """One ``name = jax.jit(...)``-style binding, for the FJX204 donation
    check: ``target`` is the dotted name the jitted callable is bound
    to, call sites are classified later against the whole module."""

    def __init__(
        self,
        mod: ModuleInfo,
        line: int,
        qualname: str,
        target: str,
        donated: bool,
        kind: str,
    ):
        self.mod = mod
        self.line = line
        self.qualname = qualname
        self.target = target
        self.donated = donated
        self.kind = kind
        # (line, is_self_overwrite) per call site of `target(...)`
        self.call_sites: List[Tuple[int, bool]] = []


class JitContext:
    """Everything an FJX rule may consult: the module set, the function
    summaries (reused from the source-lint plane), every discovered jit
    region with taint-annotated frames, and every jitted binding."""

    def __init__(self, modules: List[ModuleInfo]):
        self.modules = modules
        self.lint = LintContext(modules)  # populates mod.functions
        self.regions: List[JitRegion] = []
        self.bindings: List[JitBinding] = []
        for mod in modules:
            _discover_module(self, mod)
        for frame in self.iter_frames():
            frame.run()
        for b in self.bindings:
            _classify_call_sites(b)

    def iter_frames(self) -> Iterable[JitFrame]:
        for region in self.regions:
            for frame in region.frames:
                yield frame


# ---------------------------------------------------------------------------
# discovery
# ---------------------------------------------------------------------------
def _const_int_set(node: Optional[ast.AST]) -> Set[int]:
    """static_argnums / donate_argnums literals -> set of ints."""
    out: Set[int] = set()
    if node is None:
        return out
    items = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    for it in items:
        if isinstance(it, ast.Constant) and isinstance(it.value, int):
            out.add(it.value)
    return out


def _const_str_set(node: Optional[ast.AST]) -> Set[str]:
    out: Set[str] = set()
    if node is None:
        return out
    items = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    for it in items:
        if isinstance(it, ast.Constant) and isinstance(it.value, str):
            out.add(it.value)
    return out


def _names_in(expr: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_jit_name(name: Optional[str]) -> bool:
    return name in ("jit", "jax.jit")


def _is_partial(name: Optional[str]) -> bool:
    return name in ("partial", "functools.partial")


class _BoundarySpec:
    """What one jit-construction call pins down before fn resolution."""

    def __init__(self, kind: str, fn: Optional[ast.AST]):
        self.kind = kind
        self.fn = fn
        self.static_nums: Set[int] = set()
        self.static_names: Set[str] = set()
        self.donated = False
        # extra host-tainted params bound by functools.partial
        self.partial_pos = 0
        self.partial_kw: Set[str] = set()
        # names folded into the program KEY (_jit_cached / jit_row_sharded):
        # a host capture that is part of program identity is deliberate
        # per-value specialization, not an accidental recompile — laundered
        self.key_names: Set[str] = set()


def _parse_jit_kwargs(spec: _BoundarySpec, call: ast.Call) -> None:
    spec.static_nums |= _const_int_set(_kw(call, "static_argnums"))
    spec.static_names |= _const_str_set(_kw(call, "static_argnames"))
    if _kw(call, "donate_argnums") is not None or _kw(call, "donate_argnames") is not None:
        spec.donated = True


def _boundary_from_call(call: ast.Call) -> Optional[_BoundarySpec]:
    name = call_name(call)
    if name is None:
        return None
    last = name.rsplit(".", 1)[-1]
    spec: Optional[_BoundarySpec] = None
    if _is_jit_name(name) and call.args:
        spec = _BoundarySpec("jax.jit", call.args[0])
        _parse_jit_kwargs(spec, call)
    elif last == "shard_map" and call.args:
        spec = _BoundarySpec("shard_map", call.args[0])
    elif last == "jit_row_sharded" and len(call.args) >= 3:
        spec = _BoundarySpec("jit_row_sharded", call.args[2])
        spec.key_names = _names_in(call.args[1])
    elif last == "_jit_cached" and len(call.args) >= 2:
        spec = _BoundarySpec("_jit_cached", call.args[1])
        spec.key_names = _names_in(call.args[0])
        spec.static_nums |= _const_int_set(_kw(call, "static_argnums"))
        if len(call.args) >= 3:
            spec.static_nums |= _const_int_set(call.args[2])
    if spec is None:
        return None
    # unwrap functools.partial: positionally-bound params and kwarg-bound
    # params are host values folded into the traced program
    fn = spec.fn
    if isinstance(fn, ast.Call) and _is_partial(call_name(fn)) and fn.args:
        spec.partial_pos = len(fn.args) - 1
        spec.partial_kw = {kw.arg for kw in fn.keywords if kw.arg}
        spec.fn = fn.args[0]
    return spec


def _boundary_from_decorator(fn_def: ast.AST) -> Optional[_BoundarySpec]:
    for dec in getattr(fn_def, "decorator_list", []):
        if _is_jit_name(dotted_name(dec)):
            return _BoundarySpec("jax.jit", None)
        if isinstance(dec, ast.Call):
            dname = call_name(dec)
            if _is_jit_name(dname):
                spec = _BoundarySpec("jax.jit", None)
                _parse_jit_kwargs(spec, dec)
                return spec
            if _is_partial(dname) and dec.args and _is_jit_name(dotted_name(dec.args[0])):
                spec = _BoundarySpec("jax.jit", None)
                _parse_jit_kwargs(spec, dec)
                return spec
    return None


def _resolve_fn(mod: ModuleInfo, at: ast.AST, expr: ast.AST) -> Optional[ast.AST]:
    """The FunctionDef/Lambda a jit-target expression names, resolved in
    this module (Lambda inline; ``f`` via progressively-stripped
    enclosing qualnames; ``self.m`` via the enclosing class)."""
    if isinstance(expr, ast.Lambda):
        return expr
    name = dotted_name(expr)
    if name is None:
        return None
    enclosing = mod.qualname(at)
    candidates: List[str] = []
    if name.startswith("self.") and name.count(".") == 1:
        cls = enclosing.split(".", 1)[0] if enclosing else ""
        if cls:
            candidates.append(f"{cls}.{name.split('.', 1)[1]}")
    elif "." not in name:
        parts = enclosing.split(".") if enclosing else []
        for i in range(len(parts), -1, -1):
            prefix = ".".join(parts[:i])
            candidates.append(f"{prefix}.{name}" if prefix else name)
    for cand in candidates:
        fs = mod.functions.get(cand)
        if fs is not None:
            return fs.node
    return None


def _param_names(node: ast.AST) -> List[str]:
    a = node.args  # type: ignore[attr-defined]
    names = [p.arg for p in getattr(a, "posonlyargs", [])] + [p.arg for p in a.args]
    if names and names[0] == "self":
        names = names[1:]
    names += [p.arg for p in a.kwonlyargs]
    return names


def _free_names(node: ast.AST) -> Set[str]:
    """Names the function reads but never binds — closure captures."""
    bound: Set[str] = set(_param_names(node)) | {"self"}
    loads: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            if isinstance(sub.ctx, ast.Load):
                loads.add(sub.id)
            else:
                bound.add(sub.id)
        elif isinstance(sub, _FUNC_NODES) and sub is not node:
            bound.add(sub.name)
            bound.update(_param_names(sub))
        elif isinstance(sub, (ast.Import, ast.ImportFrom)):
            for alias in sub.names:
                bound.add(alias.asname or alias.name.split(".", 1)[0])
        elif isinstance(sub, ast.arg):
            bound.add(sub.arg)
    return loads - bound


def _host_captures(mod: ModuleInfo, fn_node: ast.AST) -> Set[str]:
    """Free variables of the jitted fn that are parameters of its
    ENCLOSING function: values that vary per outer call but are baked
    into the trace — the classic per-call-recompile closure capture."""
    enclosing_qual = mod.qualname(fn_node)
    if not enclosing_qual:
        return set()
    fs = mod.functions.get(enclosing_qual)
    if fs is None:
        return set()
    outer_params = set(_param_names(fs.node))
    return _free_names(fn_node) & outer_params


def _discover_module(ctx: JitContext, mod: ModuleInfo) -> None:
    seen_fn_ids: Set[int] = set()
    for node in ast.walk(mod.tree):
        spec: Optional[_BoundarySpec] = None
        fn_node: Optional[ast.AST] = None
        line = getattr(node, "lineno", 0)
        if isinstance(node, ast.Call):
            spec = _boundary_from_call(node)
            if spec is None:
                continue
            if spec.fn is not None:
                fn_node = _resolve_fn(mod, node, spec.fn)
            _record_binding(ctx, mod, node, spec)
        elif isinstance(node, _FUNC_NODES):
            spec = _boundary_from_decorator(node)
            if spec is None:
                continue
            fn_node = node
        else:
            continue
        region = JitRegion(mod, spec.kind, line, mod.qualname(node))
        ctx.regions.append(region)
        if fn_node is None or id(fn_node) in seen_fn_ids:
            continue
        seen_fn_ids.add(id(fn_node))
        params = _param_names(fn_node)
        host: Set[str] = set()
        for i in sorted(spec.static_nums):
            if 0 <= i < len(params):
                host.add(params[i])
        host |= spec.static_names & set(params)
        for i in range(min(spec.partial_pos, len(params))):
            host.add(params[i])
        host |= spec.partial_kw & set(params)
        traced = set(params) - host
        host |= _host_captures(mod, fn_node) - spec.key_names
        root = JitFrame(region, mod, fn_node, traced, host, depth=0)
        region.frames.append(root)
        _expand_closure(region, root)


def _record_binding(ctx: JitContext, mod: ModuleInfo, call: ast.Call, spec: _BoundarySpec) -> None:
    """When the jit construction is the RHS of a simple assignment,
    remember the binding for the FJX204 donation check."""
    # find the Assign that owns this call: cheap parent scan limited to
    # single-target assigns whose value is exactly this call
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and node.value is call
        ):
            target = dotted_name(node.targets[0])
            if target:
                ctx.bindings.append(
                    JitBinding(
                        mod,
                        node.lineno,
                        mod.qualname(node),
                        target,
                        spec.donated,
                        spec.kind,
                    )
                )
            return


def _classify_call_sites(b: JitBinding) -> None:
    for node in ast.walk(b.mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        calls: List[ast.Call] = []
        if isinstance(value, ast.Call) and dotted_name(value.func) == b.target:
            calls.append(value)
        for call in calls:
            overwrite = False
            if len(node.targets) == 1 and call.args:
                tgt = dotted_name(node.targets[0])
                first = dotted_name(call.args[0])
                overwrite = tgt is not None and tgt == first
            b.call_sites.append((node.lineno, overwrite))
    # bare-expression / nested call sites: count as non-overwrite so the
    # rule stays conservative (donation only suggested when EVERY site
    # overwrites the argument with the return)
    for node in ast.walk(b.mod.tree):
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            if dotted_name(node.value.func) == b.target:
                b.call_sites.append((node.lineno, False))


def _expand_closure(region: JitRegion, root: JitFrame) -> None:
    """Same-module call-graph closure: a helper called from inside the
    boundary is traced too, with taint mapped through the call
    arguments."""
    mod = region.mod
    worklist = [root]
    visited: Set[Tuple[str, frozenset, frozenset]] = set()
    while worklist:
        frame = worklist.pop()
        if frame.depth >= 5 or len(region.frames) > 64:
            continue
        frame.run()
        for sub in ast.walk(frame.node):
            if not isinstance(sub, ast.Call):
                continue
            callee_node = _resolve_fn(mod, sub, sub.func)
            if callee_node is None or isinstance(callee_node, ast.Lambda):
                continue
            params = _param_names(callee_node)
            traced: Set[str] = set()
            host: Set[str] = set()
            for i, arg in enumerate(sub.args):
                if i >= len(params):
                    break
                t, h = frame.expr_taint(arg)
                if t:
                    traced.add(params[i])
                if h:
                    host.add(params[i])
            for kw in sub.keywords:
                if kw.arg and kw.arg in params:
                    t, h = frame.expr_taint(kw.value)
                    if t:
                        traced.add(kw.arg)
                    if h:
                        host.add(kw.arg)
            qual = mod.qualname(callee_node)
            name = getattr(callee_node, "name", "")
            key = (f"{qual}.{name}", frozenset(traced), frozenset(host))
            if key in visited:
                continue
            visited.add(key)
            child = JitFrame(region, mod, callee_node, traced, host, frame.depth + 1)
            child.inherited_bound = frame.bound | frame.inherited_bound
            region.frames.append(child)
            worklist.append(child)


# ---------------------------------------------------------------------------
# the taint walker
# ---------------------------------------------------------------------------
class _TaintWalker:
    """Flow-sensitive two-taint evaluator over one frame's body. Records
    the taint of every expression AT its evaluation point so rules can
    stay purely structural. Runs the body twice so loop-carried
    assignments reach their uses."""

    def __init__(self, frame: JitFrame):
        self.frame = frame
        self.traced: Set[str] = set(frame.traced_params)
        self.host: Set[str] = set(frame.host_params)
        frame.bound.update(_param_names(frame.node))

    def run(self) -> None:
        body = self.frame.body()
        for _pass in range(2):
            for stmt in body:
                self.exec_stmt(stmt)

    # ---- expressions -----------------------------------------------------
    def eval(self, node: Optional[ast.AST]) -> Tuple[bool, bool]:
        if node is None:
            return (False, False)
        t = self._eval(node)
        self.frame.taint_at[id(node)] = t
        return t

    def _eval(self, node: ast.AST) -> Tuple[bool, bool]:
        if isinstance(node, ast.Constant):
            return (False, False)
        if isinstance(node, ast.Name):
            return (node.id in self.traced, node.id in self.host)
        if isinstance(node, ast.Attribute):
            # x.shape / x.dtype are static at trace time: breaks taint
            self.eval(node.value)
            return (False, False)
        if isinstance(node, ast.Subscript):
            self.eval(node.slice)
            if isinstance(node.slice, ast.Slice):
                for part in (node.slice.lower, node.slice.upper, node.slice.step):
                    if part is not None:
                        self.eval(part)
            return self.eval(node.value)
        if isinstance(node, ast.Call):
            self.eval(node.func)
            arg_t = False
            arg_h = False
            for arg in node.args:
                t, h = self.eval(arg)
                arg_t, arg_h = arg_t or t, arg_h or h
            for kw in node.keywords:
                t, h = self.eval(kw.value)
                arg_t, arg_h = arg_t or t, arg_h or h
            name = call_name(node)
            last = name.rsplit(".", 1)[-1] if name else ""
            if last in BUCKET_SANITIZERS or last in _CLEAN_CALLS:
                return (False, False)
            return (arg_t, arg_h)
        if isinstance(node, ast.Compare):
            out = self.eval(node.left)
            for cmp in node.comparators:
                t, h = self.eval(cmp)
                out = (out[0] or t, out[1] or h)
            if all(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in node.ops
            ):
                # identity checks are static, and membership tests in
                # engine code are dict-key checks over static python
                # strings even when the VALUES are traced arrays
                return (False, False)
            return out
        if isinstance(node, (ast.BinOp,)):
            lt, lh = self.eval(node.left)
            rt, rh = self.eval(node.right)
            return (lt or rt, lh or rh)
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand)
        if isinstance(node, ast.BoolOp):
            out = (False, False)
            for v in node.values:
                t, h = self.eval(v)
                out = (out[0] or t, out[1] or h)
            return out
        if isinstance(node, ast.IfExp):
            tt, th = self.eval(node.test)
            bt, bh = self.eval(node.body)
            ot, oh = self.eval(node.orelse)
            return (tt or bt or ot, th or bh or oh)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out = (False, False)
            for el in node.elts:
                t, h = self.eval(el)
                out = (out[0] or t, out[1] or h)
            return out
        if isinstance(node, ast.Dict):
            out = (False, False)
            for el in list(node.keys) + list(node.values):
                if el is None:
                    continue
                t, h = self.eval(el)
                out = (out[0] or t, out[1] or h)
            return out
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        if isinstance(node, ast.NamedExpr):
            t = self.eval(node.value)
            self._assign(node.target, t)
            return t
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            for gen in node.generators:
                it = self.eval(gen.iter)
                self._assign(gen.target, it)
                for cond in gen.ifs:
                    self.eval(cond)
            if isinstance(node, ast.DictComp):
                kt = self.eval(node.key)
                vt = self.eval(node.value)
                return (kt[0] or vt[0], kt[1] or vt[1])
            return self.eval(node.elt)
        if isinstance(node, (ast.JoinedStr, ast.FormattedValue)):
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.expr):
                    self.eval(sub)
            return (False, False)
        if isinstance(node, ast.Lambda):
            return (False, False)
        # fallback: OR over child expressions
        out = (False, False)
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, ast.expr):
                t, h = self.eval(sub)
                out = (out[0] or t, out[1] or h)
        return out

    # ---- statements ------------------------------------------------------
    def _assign(self, target: ast.AST, taint: Tuple[bool, bool]) -> None:
        if isinstance(target, ast.Name):
            self.frame.bound.add(target.id)
            (self.traced.add if taint[0] else self.traced.discard)(target.id)
            (self.host.add if taint[1] else self.host.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._assign(el, taint)
        elif isinstance(target, ast.Starred):
            self._assign(target.value, taint)
        elif isinstance(target, (ast.Attribute, ast.Subscript)):
            self.eval(target.value)

    def exec_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign):
            t = self.eval(stmt.value)
            for tgt in stmt.targets:
                self._assign(tgt, t)
        elif isinstance(stmt, ast.AugAssign):
            vt = self.eval(stmt.value)
            ct = self.eval(stmt.target)
            self._assign(stmt.target, (vt[0] or ct[0], vt[1] or ct[1]))
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign(stmt.target, self.eval(stmt.value))
        elif isinstance(stmt, ast.For):
            it = self.eval(stmt.iter)
            self._assign(stmt.target, it)
            for s in stmt.body + stmt.orelse:
                self.exec_stmt(s)
        elif isinstance(stmt, (ast.While, ast.If)):
            self.eval(stmt.test)
            for s in stmt.body + stmt.orelse:
                self.exec_stmt(s)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                t = self.eval(item.context_expr)
                if item.optional_vars is not None:
                    self._assign(item.optional_vars, t)
            for s in stmt.body:
                self.exec_stmt(s)
        elif isinstance(stmt, (ast.Return, ast.Expr)):
            self.eval(stmt.value)
        elif isinstance(stmt, ast.Try):
            for s in stmt.body:
                self.exec_stmt(s)
            for handler in stmt.handlers:
                if handler.name:
                    self.frame.bound.add(handler.name)
                for s in handler.body:
                    self.exec_stmt(s)
            for s in stmt.orelse + stmt.finalbody:
                self.exec_stmt(s)
        elif isinstance(stmt, _FUNC_NODES):
            # a nested def is still traced when called: walk its body
            # with the params unbound (they shadow)
            self.frame.bound.add(stmt.name)
            inner = set(_param_names(stmt))
            saved = (set(self.traced), set(self.host))
            self.traced -= inner
            self.host -= inner
            self.frame.bound.update(inner)
            for s in stmt.body:
                self.exec_stmt(s)
            self.traced, self.host = saved
        elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
            for alias in stmt.names:
                self.frame.bound.add(alias.asname or alias.name.split(".", 1)[0])
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    self.traced.discard(tgt.id)
                    self.host.discard(tgt.id)
        elif isinstance(stmt, (ast.Raise,)):
            if stmt.exc is not None:
                self.eval(stmt.exc)
        elif isinstance(stmt, ast.Assert):
            self.eval(stmt.test)
        # Pass/Break/Continue/Global/Nonlocal/ClassDef: nothing to flow
