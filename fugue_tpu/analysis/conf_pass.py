"""Conf-key rules: every ``fugue.*`` key in effect is checked against the
declared registry in :mod:`fugue_tpu.constants` — unknown keys get a
did-you-mean suggestion (a typo'd conf key is otherwise SILENTLY ignored
by every engine getter), and values that the typed getters could not
coerce to the declared type are rejected before an engine trips on them
mid-run."""

import difflib
from typing import Any, Iterable

from fugue_tpu.analysis.diagnostics import (
    Diagnostic,
    Rule,
    Severity,
    register_rule,
)
from fugue_tpu.constants import (
    FUGUE_CONF_JAX_DEVICES,
    FUGUE_CONF_JAX_RECOVERY_ENABLED,
    FUGUE_CONF_LAKE_SERVE_PATH,
    FUGUE_CONF_OBS_ENABLED,
    FUGUE_CONF_OBS_PROFILE,
    FUGUE_CONF_OBS_SLOW_QUERY_MS,
    FUGUE_CONF_OBS_TRACE_PATH,
    FUGUE_CONF_SERVE_AUTOSCALE_MAX_REPLICAS,
    FUGUE_CONF_SERVE_FLEET_REPLICAS,
    FUGUE_CONF_SERVE_MAX_CONCURRENT,
    FUGUE_CONF_SERVE_STATE_PATH,
    FUGUE_CONF_STREAM_SOURCE,
    FUGUE_CONF_WORKFLOW_RESUME,
    declared_conf_keys,
)
from fugue_tpu.utils.params import _convert


@register_rule
class UnknownConfKeyRule(Rule):
    code = "FWF201"
    severity = Severity.ERROR
    description = "unknown fugue.* conf key (typo'd keys are silently ignored)"

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        declared = declared_conf_keys()
        for key in sorted(ctx.conf.keys()):
            if not key.startswith("fugue.") or key in declared:
                continue
            close = difflib.get_close_matches(key, declared.keys(), n=1, cutoff=0.6)
            hint = f" — did you mean '{close[0]}'?" if close else ""
            yield self.diag(
                f"unknown conf key '{key}'{hint} (unknown fugue.* keys are "
                "ignored by every engine)",
            )


@register_rule
class ConfValueTypeRule(Rule):
    code = "FWF202"
    severity = Severity.ERROR
    description = "conf value is not convertible to the key's declared type"

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        declared = declared_conf_keys()
        for key in sorted(ctx.conf.keys()):
            info = declared.get(key)
            if info is None or info.type is object:
                continue
            value = ctx.conf[key]
            try:
                _convert(value, info.type)
            except Exception:
                yield self.diag(
                    f"conf '{key}' = {value!r} is not convertible to the "
                    f"declared type {info.type.__name__} ({info.description})",
                )


@register_rule
class DaemonResumeOffRule(Rule):
    code = "FWF403"
    severity = Severity.WARN
    description = (
        "daemon-targeted workflow runs with fugue.workflow.resume off: "
        "an interrupted async job re-executes from scratch on failover"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        # a durable serve state path in the effective conf marks this as
        # a daemon-targeted run (the daemon's engine conf carries the
        # fugue.serve.* keys it was configured with)
        state_path = str(
            ctx.conf.get(FUGUE_CONF_SERVE_STATE_PATH, "") or ""
        ).strip()
        if state_path == "":
            return
        try:
            # _convert, not bool(): conf values legitimately arrive as
            # strings, and bool("false") is True
            resume = _convert(
                ctx.conf.get(FUGUE_CONF_WORKFLOW_RESUME, False), bool
            )
        except Exception:
            resume = False
        if not resume:
            yield self.diag(
                "the daemon journals interrupted async jobs for restart "
                "recovery, but fugue.workflow.resume is off: a resubmitted "
                "job re-executes every task instead of resuming at its "
                "checkpoint frontier — set fugue.workflow.resume=true (and "
                "a fugue.workflow.checkpoint.path) for cheap failover",
            )


@register_rule
class DaemonColdStartCacheRule(Rule):
    code = "FWF502"
    severity = Severity.WARN
    description = (
        "serve-targeted conf without a persistent executable cache dir: "
        "every daemon restart re-pays full XLA compilation before the "
        "first query"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        state_path = str(
            ctx.conf.get(FUGUE_CONF_SERVE_STATE_PATH, "") or ""
        ).strip()
        if state_path == "":
            return
        # the SAME resolution run() and the engine use (new key, then
        # the deprecated fugue.jax.compile.cache alias + env var), so
        # the gate and the engine can never disagree about whether the
        # disk tier is on
        from fugue_tpu.optimize.exec_cache import resolve_cache_dir

        if resolve_cache_dir(ctx.conf) != "":
            return
        yield self.diag(
            "the daemon journals sessions and jobs for restart recovery "
            "(fugue.serve.state_path is set), but no persistent "
            "executable cache dir is configured: a restarted daemon "
            "re-pays the full XLA compile of every hot query before its "
            "first answer — set fugue.optimize.cache.dir so restarts "
            "pre-warm from disk and time_to_first_query stays IO-bound",
        )


@register_rule
class ServeConcurrencyDispatchLockRule(Rule):
    code = "FWF503"
    severity = Severity.WARN
    description = (
        "serve-targeted conf with concurrent submissions but an engine "
        "whose task_execution_lock is None: concurrent device dispatch "
        "of collective programs can deadlock (XLA CPU rendezvous)"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        # only a conf that EXPLICITLY carries the serve concurrency key
        # is serve-targeted; and with no live engine the lock is unknowable
        if FUGUE_CONF_SERVE_MAX_CONCURRENT not in ctx.conf or ctx.engine is None:
            return
        try:
            max_concurrent = _convert(
                ctx.conf[FUGUE_CONF_SERVE_MAX_CONCURRENT], int
            )
        except Exception:
            return  # FWF202 already rejects the unconvertible value
        if max_concurrent <= 1:
            return
        if getattr(ctx.engine, "task_execution_lock", None) is None:
            yield self.diag(
                f"fugue.serve.max_concurrent={max_concurrent} but the "
                "target engine's task_execution_lock is None: two "
                "concurrently dispatched programs with collectives can "
                "starve each other's rendezvous participants and "
                "deadlock (the PR 6 shared-engine hazard) — serve "
                "through an engine that serializes task execution, or "
                "set fugue.serve.max_concurrent=1",
            )


@register_rule
class FleetSharedStateRule(Rule):
    code = "FWF504"
    severity = Severity.WARN
    description = (
        "fleet conf with replicas > 1 but no shared serve state path "
        "or no shared executable cache dir: failover and cross-replica "
        "warm starts silently degrade"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        if FUGUE_CONF_SERVE_FLEET_REPLICAS not in ctx.conf:
            return
        raw = ctx.conf[FUGUE_CONF_SERVE_FLEET_REPLICAS]
        try:
            replicas = _convert(raw, int)
        except Exception:
            return  # FWF202 already rejects the unconvertible value
        if replicas <= 1:
            return
        state_path = str(
            ctx.conf.get(FUGUE_CONF_SERVE_STATE_PATH, "") or ""
        ).strip()
        if state_path == "":
            yield self.diag(
                f"fugue.serve.fleet.replicas={replicas} but no shared "
                "fugue.serve.state_path: the per-replica journals under "
                "it are what a survivor adopts on replica death or a "
                "rolling-restart drain — without one, failover has "
                "nothing to migrate and every session dies with its "
                "replica",
            )
        # the SAME resolution run() and the engine use (new key, then
        # the deprecated alias), so this gate and FWF502 cannot drift
        from fugue_tpu.optimize.exec_cache import resolve_cache_dir

        if resolve_cache_dir(ctx.conf) == "":
            yield self.diag(
                f"fugue.serve.fleet.replicas={replicas} but no shared "
                "fugue.optimize.cache.dir: every replica (and every "
                "rolling-restart fresh daemon) re-pays full XLA "
                "compilation instead of warm-starting from the fleet's "
                "shared executable cache",
            )


@register_rule
class ObsDependentConfWithoutObsRule(Rule):
    code = "FWF505"
    severity = Severity.WARN
    description = (
        "fugue.obs.slow_query_ms or fugue.obs.profile is set but "
        "fugue.obs.enabled is off: the conf is silently inert"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        try:
            # _convert, not bool(): conf values legitimately arrive as
            # strings, and bool("false") is True (FWF404's idiom)
            enabled = _convert(
                ctx.conf.get(FUGUE_CONF_OBS_ENABLED, False), bool
            )
        except Exception:
            enabled = False
        if enabled:
            return
        try:
            slow_ms = float(
                ctx.conf.get(FUGUE_CONF_OBS_SLOW_QUERY_MS, 0.0) or 0.0
            )
        except Exception:
            slow_ms = 0.0
        if slow_ms > 0:
            yield self.diag(
                f"fugue.obs.slow_query_ms={slow_ms:g} but fugue.obs.enabled "
                "is off: embedded runs never open a trace, so no slow-query "
                "record (or span breakdown) is ever produced — set "
                "fugue.obs.enabled=true (or drop the threshold)",
            )
        try:
            profile = _convert(
                ctx.conf.get(FUGUE_CONF_OBS_PROFILE, False), bool
            )
        except Exception:
            profile = False
        if profile:
            yield self.diag(
                "fugue.obs.profile is on but fugue.obs.enabled is off: the "
                "profiler's conf gate needs the span tracer for the "
                "compile/execute/transfer split, so runs are NOT profiled "
                "and FugueWorkflowResult.profile() stays None — set "
                "fugue.obs.enabled=true (the serving 'profile' submission "
                "flag forces profiling per request instead)",
            )


@register_rule
class StreamConfRule(Rule):
    code = "FWF506"
    severity = Severity.WARN
    description = (
        "fugue.stream.* keys set without a streaming source (inert), or "
        "a standing pipeline without fugue.workflow.resume (a restart "
        "refolds every consumed file from scratch)"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        stream_keys = sorted(
            k for k in ctx.conf.keys() if k.startswith("fugue.stream.")
        )
        if not stream_keys:
            return
        source = str(
            ctx.conf.get(FUGUE_CONF_STREAM_SOURCE, "") or ""
        ).strip()
        if source == "":
            for key in stream_keys:
                if key == FUGUE_CONF_STREAM_SOURCE:
                    continue
                yield self.diag(
                    f"'{key}' is set but {FUGUE_CONF_STREAM_SOURCE} is "
                    "empty: no standing pipeline tails anything, so the "
                    "key is silently inert — set the source dir/URI (or "
                    "drop the fugue.stream.* keys)",
                )
            return
        try:
            # _convert, not bool(): conf values legitimately arrive as
            # strings, and bool("false") is True (FWF404's idiom)
            resume = _convert(
                ctx.conf.get(FUGUE_CONF_WORKFLOW_RESUME, False), bool
            )
        except Exception:
            resume = False
        if not resume:
            yield self.diag(
                f"{FUGUE_CONF_STREAM_SOURCE} configures a standing "
                "pipeline but fugue.workflow.resume is off: the pipeline "
                "keeps no durable progress manifest, so a killed driver "
                "restarts from scratch and refolds every consumed file "
                "(double-counted aggregates if the view was already "
                "published) — set fugue.workflow.resume=true for "
                "exactly-once restart",
            )


@register_rule
class LakeConfRule(Rule):
    code = "FWF507"
    severity = Severity.WARN
    description = (
        "fugue.lake.* keys set but nothing reads or writes a lake:// "
        "table (inert), or AS OF time travel against a non-lake path"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        from fugue_tpu.extensions import builtins as _b
        from fugue_tpu.lake.format import is_lake_uri

        def _task_path(t: Any) -> Any:
            p = t.params.get("path", None)
            if isinstance(p, (list, tuple)):
                p = p[0] if p else None
            return p if isinstance(p, str) else None

        touches_lake = False
        for t in ctx.tasks:
            if t.extension not in (_b.Load, _b.Save):
                continue
            path = _task_path(t)
            if path is not None and is_lake_uri(path):
                touches_lake = True
            if t.extension is _b.Load:
                params = dict(t.params.get("params", None) or {})
                pinned = [
                    k for k in ("version", "timestamp") if k in params
                ]
                if pinned and path is not None and not is_lake_uri(path):
                    yield self.diag(
                        f"AS OF ({'/'.join(pinned)}) on load of "
                        f"'{path}': time travel only applies to lake:// "
                        "tables — a plain file path has no snapshot "
                        "history, so this load fails at run time "
                        "(prefix the path with lake:// or drop AS OF)",
                        t,
                    )
        lake_keys = sorted(
            k for k in ctx.conf.keys() if k.startswith("fugue.lake.")
        )
        if not lake_keys:
            return
        # fugue.lake.serve.path anchors lake usage by itself: it turns
        # on the serve sessions' lake-backed durable tables, which no
        # workflow task would reveal
        serve_path = str(
            ctx.conf.get(FUGUE_CONF_LAKE_SERVE_PATH, "") or ""
        ).strip()
        if serve_path != "" or touches_lake:
            return
        for key in lake_keys:
            yield self.diag(
                f"'{key}' is set but no task loads or saves a lake:// "
                "table and fugue.lake.serve.path is empty: the key is "
                "silently inert — point a LOAD/SAVE at a lake:// URI "
                "(or drop the fugue.lake.* keys)",
            )


@register_rule
class AutoscaleConfRule(Rule):
    code = "FWF508"
    severity = Severity.WARN
    description = (
        "fugue.serve.autoscale.* keys set without an elastic fleet "
        "(inert), or autoscaling without a shared serve state path "
        "(scale-down drains have no journal for the survivor to adopt)"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        autoscale_keys = sorted(
            k for k in ctx.conf.keys()
            if k.startswith("fugue.serve.autoscale.")
        )
        if not autoscale_keys:
            return
        try:
            max_replicas = _convert(
                ctx.conf.get(FUGUE_CONF_SERVE_AUTOSCALE_MAX_REPLICAS, 0), int
            )
        except Exception:
            return  # FWF202 already rejects the unconvertible value
        if max_replicas <= 0:
            # the master switch is off (or absent): every other
            # autoscale key is silently inert
            for key in autoscale_keys:
                if key == FUGUE_CONF_SERVE_AUTOSCALE_MAX_REPLICAS:
                    continue
                yield self.diag(
                    f"'{key}' is set but "
                    f"{FUGUE_CONF_SERVE_AUTOSCALE_MAX_REPLICAS} is unset "
                    "(or <= 0): no autoscaler is ever constructed, so the "
                    "key is silently inert — set a positive max_replicas "
                    "(or drop the fugue.serve.autoscale.* keys)",
                )
            return
        if FUGUE_CONF_SERVE_FLEET_REPLICAS not in ctx.conf:
            yield self.diag(
                f"{FUGUE_CONF_SERVE_AUTOSCALE_MAX_REPLICAS}="
                f"{max_replicas} but {FUGUE_CONF_SERVE_FLEET_REPLICAS} "
                "is absent: the autoscaler only runs inside a ServeFleet, "
                "and this conf never constructs one — an embedded daemon "
                "ignores every fugue.serve.autoscale.* key (set "
                "fugue.serve.fleet.replicas, or drop the autoscale keys)",
            )
        state_path = str(
            ctx.conf.get(FUGUE_CONF_SERVE_STATE_PATH, "") or ""
        ).strip()
        if state_path == "":
            yield self.diag(
                f"{FUGUE_CONF_SERVE_AUTOSCALE_MAX_REPLICAS}="
                f"{max_replicas} but no shared fugue.serve.state_path: "
                "scale-down drains a replica's sessions to its journal "
                "for a survivor to adopt — without one there is nothing "
                "to adopt, so every autoscale retire loses the sessions "
                "it drains",
            )


@register_rule
class DeviceRecoveryConfRule(Rule):
    code = "FWF509"
    severity = Severity.WARN
    description = (
        "fugue.jax.recovery.* keys with a single-device mesh (recovery "
        "is inert: losing the only device leaves no survivors), or "
        "recovery enabled without a resumable checkpoint/lake lineage "
        "path (mid-flight frames fail their query on device loss)"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        recovery_keys = sorted(
            k for k in ctx.conf.keys()
            if k.startswith("fugue.jax.recovery.")
        )
        if not recovery_keys:
            return
        # single-device pin: degraded-mesh rebuild needs at least one
        # SURVIVOR, so a one-device mesh can never recover from a loss
        devices = str(ctx.conf.get(FUGUE_CONF_JAX_DEVICES, "") or "").strip()
        pinned = [p for p in devices.split(",") if p.strip() != ""]
        if len(pinned) == 1:
            for key in recovery_keys:
                yield self.diag(
                    f"'{key}' is set but {FUGUE_CONF_JAX_DEVICES}="
                    f"'{devices}' pins the mesh to a single device: "
                    "degraded-mesh recovery rebuilds onto the SURVIVORS "
                    "of a loss, and a one-device mesh has none — the key "
                    "is silently inert (widen the device slice or drop "
                    "the fugue.jax.recovery.* keys)",
                )
            return
        try:
            # _convert, not bool(): conf values legitimately arrive as
            # strings, and bool("false") is True
            enabled = _convert(
                ctx.conf.get(FUGUE_CONF_JAX_RECOVERY_ENABLED, True), bool
            )
        except Exception:
            enabled = True
        if not enabled:
            return
        try:
            resume = _convert(
                ctx.conf.get(FUGUE_CONF_WORKFLOW_RESUME, False), bool
            )
        except Exception:
            resume = False
        if resume:
            return
        # a PINNED lake load is deterministic lineage: recovery can
        # re-read the exact snapshot onto the degraded mesh
        from fugue_tpu.extensions import builtins as _b
        from fugue_tpu.lake.format import is_lake_uri, parse_lake_uri

        for t in ctx.tasks:
            if t.extension is not _b.Load:
                continue
            p = t.params.get("path", None)
            if isinstance(p, (list, tuple)):
                p = p[0] if p else None
            if not isinstance(p, str) or not is_lake_uri(p):
                continue
            params = dict(t.params.get("params", None) or {})
            try:
                _, uri_params = parse_lake_uri(p)
            except Exception:
                uri_params = {}
            if (
                "version" in params or "timestamp" in params
                or "version" in uri_params or "timestamp" in uri_params
            ):
                return  # pinned lake lineage: rematerializable
        yield self.diag(
            f"{FUGUE_CONF_JAX_RECOVERY_ENABLED} is on but the workflow "
            "has no resumable lineage path: no checkpointing "
            "(fugue.workflow.resume is off) and no pinned lake:// AS OF "
            "load — on device loss, frames whose shards cannot be "
            "evacuated have nothing durable to re-materialize from, so "
            "their owning query fails with DeviceLostError instead of "
            "recovering — set fugue.workflow.resume=true (with a "
            "checkpoint path) or pin lake reads to a version/timestamp",
        )


@register_rule
class ObsTracePathWithoutObsRule(Rule):
    code = "FWF404"
    severity = Severity.WARN
    description = (
        "fugue.obs.trace_path is set but fugue.obs.enabled is off: "
        "no trace file will ever be written"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        trace_path = str(
            ctx.conf.get(FUGUE_CONF_OBS_TRACE_PATH, "") or ""
        ).strip()
        if trace_path == "":
            return
        try:
            # _convert, not bool(): conf values legitimately arrive as
            # strings, and bool("false") is True
            enabled = _convert(
                ctx.conf.get(FUGUE_CONF_OBS_ENABLED, False), bool
            )
        except Exception:
            enabled = False
        if not enabled:
            yield self.diag(
                f"fugue.obs.trace_path is set to '{trace_path}' but "
                "fugue.obs.enabled is off: no trace is ever opened, so "
                "no trace file will be written there — set "
                "fugue.obs.enabled=true to get per-run Chrome-trace "
                "JSON (or drop the trace_path)",
            )
