"""Conf-key rules: every ``fugue.*`` key in effect is checked against the
declared registry in :mod:`fugue_tpu.constants` — unknown keys get a
did-you-mean suggestion (a typo'd conf key is otherwise SILENTLY ignored
by every engine getter), and values that the typed getters could not
coerce to the declared type are rejected before an engine trips on them
mid-run."""

import difflib
from typing import Any, Iterable

from fugue_tpu.analysis.diagnostics import (
    Diagnostic,
    Rule,
    Severity,
    register_rule,
)
from fugue_tpu.constants import declared_conf_keys
from fugue_tpu.utils.params import _convert


@register_rule
class UnknownConfKeyRule(Rule):
    code = "FWF201"
    severity = Severity.ERROR
    description = "unknown fugue.* conf key (typo'd keys are silently ignored)"

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        declared = declared_conf_keys()
        for key in sorted(ctx.conf.keys()):
            if not key.startswith("fugue.") or key in declared:
                continue
            close = difflib.get_close_matches(key, declared.keys(), n=1, cutoff=0.6)
            hint = f" — did you mean '{close[0]}'?" if close else ""
            yield self.diag(
                f"unknown conf key '{key}'{hint} (unknown fugue.* keys are "
                "ignored by every engine)",
            )


@register_rule
class ConfValueTypeRule(Rule):
    code = "FWF202"
    severity = Severity.ERROR
    description = "conf value is not convertible to the key's declared type"

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        declared = declared_conf_keys()
        for key in sorted(ctx.conf.keys()):
            info = declared.get(key)
            if info is None or info.type is object:
                continue
            value = ctx.conf[key]
            try:
                _convert(value, info.type)
            except Exception:
                yield self.diag(
                    f"conf '{key}' = {value!r} is not convertible to the "
                    f"declared type {info.type.__name__} ({info.description})",
                )
