"""Diagnostic framework for the pre-execution workflow analyzer.

- :class:`Severity` — info / warn / error ordering.
- :class:`Diagnostic` — one finding: a stable rule code, severity, message,
  and the offending task's display name + USER callsite (captured at DAG
  build time by ``FugueWorkflow.add``, same attribution the fault layer
  splices into runtime errors).
- :class:`Rule` — a pluggable check with a stable code (``FWF###``);
  subclasses registered via :func:`register_rule` run in every analysis.
  ``scope`` partitions rules: ``"generic"`` rules run for every engine,
  ``"jax"`` rules only when the target engine is the jax backend (or in
  engine-agnostic lint mode, e.g. the CLI).
"""

from enum import IntEnum
from typing import Any, Dict, Iterable, List, Optional, Type

GENERIC = "generic"
JAX = "jax"


class Severity(IntEnum):
    INFO = 0
    WARN = 1
    ERROR = 2

    def __str__(self) -> str:
        return self.name.lower()

    @staticmethod
    def parse(obj: Any) -> "Severity":
        if isinstance(obj, Severity):
            return obj
        s = str(obj).strip().lower()
        for sev in Severity:
            if s == sev.name.lower():
                return sev
        raise ValueError(f"invalid severity {obj!r}")


class Diagnostic:
    """One analyzer finding, printable as a single lint line."""

    __slots__ = ("code", "severity", "message", "task_name", "callsite", "rule")

    def __init__(
        self,
        code: str,
        severity: Severity,
        message: str,
        task_name: str = "",
        callsite: Optional[List[str]] = None,
        rule: str = "",
    ):
        self.code = code
        self.severity = Severity.parse(severity)
        self.message = message
        self.task_name = task_name
        self.callsite = list(callsite or [])
        self.rule = rule

    def describe(self, with_callsite: bool = True) -> str:
        head = f"{self.code} {self.severity}"
        if self.task_name:
            head += f" [task {self.task_name}]"
        lines = [f"{head}: {self.message}"]
        if with_callsite and self.callsite:
            lines.append("  defined at:")
            lines.extend("  " + c for c in self.callsite)
        return "\n".join(lines)

    def as_dict(self) -> Dict[str, Any]:
        return dict(
            code=self.code,
            severity=str(self.severity),
            message=self.message,
            task_name=self.task_name,
            callsite=list(self.callsite),
            rule=self.rule,
        )

    def __str__(self) -> str:
        return self.describe()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Diagnostic({self.code}, {self.severity}, {self.task_name})"


class Rule:
    """Base class of one analyzer check. Subclasses set the class attrs and
    implement :meth:`check`; ``self.diag(...)`` builds consistently-tagged
    diagnostics. Rules must be side-effect free and never execute tasks."""

    code: str = "FWF000"
    severity: Severity = Severity.WARN
    scope: str = GENERIC
    description: str = ""
    # lint_only rules run in analyze()/lint/CLI but are EXCLUDED from
    # the pre-run fugue.analysis gate — e.g. FWF501's optimizer dry-run,
    # which run() is about to perform for real anyway (running it in
    # the gate would double the per-run planning cost for no findings
    # the log doesn't already get from the optimizer itself)
    lint_only: bool = False

    def check(self, ctx: Any) -> Iterable[Diagnostic]:  # pragma: no cover
        raise NotImplementedError

    def diag(
        self,
        message: str,
        task: Any = None,
        severity: Optional[Severity] = None,
    ) -> Diagnostic:
        return Diagnostic(
            code=self.code,
            severity=self.severity if severity is None else severity,
            message=message,
            task_name=getattr(task, "name", "") if task is not None else "",
            callsite=getattr(task, "callsite", None) if task is not None else None,
            rule=type(self).__name__,
        )


_RULES: Dict[str, Type[Rule]] = {}


def register_rule(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator: add a Rule to the global registry (keyed by its
    stable code; re-registering a code replaces the rule — plugins may
    override a builtin check)."""
    _RULES[cls.code] = cls
    return cls


def all_rules() -> List[Type[Rule]]:
    """Registered rule classes, ordered by code."""
    return [_RULES[k] for k in sorted(_RULES)]
