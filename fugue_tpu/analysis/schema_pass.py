"""Static schema propagation + schema rules.

Threads declared/inferred schemas through the built-but-unexecuted DAG:
create -> transform -> select/rename/drop -> zip/join edges, without
executing anything. Knowledge is three-valued per task:

- full (:class:`SchemaInfo` with a ``Schema``),
- names-only (``columns``: order known, types not — e.g. an ``Assign``
  whose expression types can't be inferred),
- unknown (raw SQL output, schema-less loads, opaque processors).

Rules then check column references (partition specs, presorts, selects,
renames, join keys, subsets) against the propagated knowledge and flag
only DEFINITE misses — a reference into an unknown schema is reported
separately at info level (FWF104) as unverifiable, never as an error.
"""

from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

from fugue_tpu.analysis.diagnostics import (
    Diagnostic,
    Rule,
    Severity,
    register_rule,
)
from fugue_tpu.collections.partition import parse_presort_exp
from fugue_tpu.column.expressions import ColumnExpr, _NamedColumnExpr
from fugue_tpu.extensions import builtins as _b
from fugue_tpu.schema import Schema
from fugue_tpu.workflow.tasks import FugueTask


class SchemaInfo:
    """What the analyzer statically knows about one task's OUTPUT."""

    __slots__ = ("schema", "columns", "zipped", "reason")

    def __init__(
        self,
        schema: Optional[Schema] = None,
        columns: Optional[List[str]] = None,
        zipped: bool = False,
        reason: str = "",
    ):
        self.schema = schema
        self.columns = columns if schema is None else schema.names
        self.zipped = zipped
        self.reason = reason  # why unknown, for FWF104 messages

    @property
    def known(self) -> bool:
        return self.schema is not None or self.columns is not None

    def has_column(self, name: str) -> Optional[bool]:
        """True/False when knowable, None when the schema is opaque."""
        if self.columns is None:
            return None
        return name in self.columns

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        if self.schema is not None:
            return f"SchemaInfo({self.schema})"
        if self.columns is not None:
            return f"SchemaInfo(columns={self.columns})"
        return f"SchemaInfo(unknown: {self.reason})"


UNKNOWN = SchemaInfo(reason="unknown")


class PropagationIssue:
    """A problem discovered WHILE propagating (not a column reference):
    kind is ``"duplicate"`` (conflicting output columns) or ``"convert"``
    (the extension can't be statically adapted — which is exactly the
    runtime conversion path, so it will fail at execution too)."""

    __slots__ = ("kind", "task", "message")

    def __init__(self, kind: str, task: FugueTask, message: str):
        self.kind = kind
        self.task = task
        self.message = message


# ---- column-expression walking ---------------------------------------------
def expr_columns(expr: Any) -> Iterator[str]:
    """Named (non-wildcard) input columns referenced by a column expression
    tree, in depth-first order."""
    if isinstance(expr, _NamedColumnExpr):
        if not expr.wildcard:
            yield expr.name
        return
    if not isinstance(expr, ColumnExpr):
        return
    for attr in ("col", "left", "right"):
        sub = getattr(expr, attr, None)
        if isinstance(sub, ColumnExpr):
            yield from expr_columns(sub)
    for sub in getattr(expr, "args", None) or []:
        yield from expr_columns(sub)


def _dedup(names: Iterable[str]) -> List[str]:
    seen: Dict[str, None] = {}
    for n in names:
        seen.setdefault(n)
    return list(seen)


# ---- per-extension column references ---------------------------------------
class ColumnRef:
    """One static column reference: the name, where it appears, and which
    inputs it must resolve against (indices into ``task.inputs``)."""

    __slots__ = ("column", "where", "input_indices")

    def __init__(self, column: str, where: str, input_indices: List[int]):
        self.column = column
        self.where = where
        self.input_indices = input_indices


def column_refs(task: FugueTask) -> List[ColumnRef]:
    """Every column the task's spec references, beyond partition/presort
    (those have their own rules). Defensive: an unparseable spec yields no
    refs — the runtime will surface its own error."""
    refs: List[ColumnRef] = []
    ext = task.extension
    p = task.params
    first = [0]

    def add(names: Iterable[str], where: str, idx: Optional[List[int]] = None) -> None:
        for n in names:
            if isinstance(n, str):
                refs.append(ColumnRef(n, where, idx or first))

    try:
        if ext is _b.Rename:
            add((p.get("columns", None) or {}).keys(), "rename")
        elif ext is _b.AlterColumns:
            add(Schema(p.get("columns", "")).names, "alter_columns")
        elif ext is _b.DropColumns:
            if not p.get("if_exists", False):
                add(p.get("columns", None) or [], "drop")
        elif ext is _b.SelectColumnsP:
            add([c for c in p.get("columns", None) or [] if isinstance(c, str)],
                "select columns")
        elif ext is _b.Dropna:
            add(p.get("subset", None) or [], "dropna subset")
        elif ext is _b.Fillna:
            add(p.get("subset", None) or [], "fillna subset")
            value = p.get("value", None)
            if isinstance(value, dict):
                add(value.keys(), "fillna value")
        elif ext is _b.Select:
            cols = p.get("columns", None)
            for c in getattr(cols, "all_cols", None) or []:
                add(_dedup(expr_columns(c)), "select")
            # NOT `having`: it filters the aggregated OUTPUT (aliases), so
            # its references don't resolve against the input schema
            add(_dedup(expr_columns(p.get("where", None))), "where")
        elif ext is _b.Filter:
            add(_dedup(expr_columns(p.get("condition", None))), "filter")
        elif ext is _b.Assign:
            for c in p.get("columns", None) or []:
                add(_dedup(expr_columns(c)), "assign")
        elif ext is _b.Aggregate:
            for c in p.get("columns", None) or []:
                add(_dedup(expr_columns(c)), "aggregate")
        elif ext is _b.RunJoin:
            how = str(p.get("how", "")).lower()
            on = p.get("on", None) or []
            if how not in ("cross",):
                # join keys must exist on EVERY side
                add(on, "join on", list(range(len(task.inputs))))
    except Exception:  # pragma: no cover - malformed spec, runtime will raise
        return refs
    return refs


def partition_check_inputs(task: FugueTask) -> List[int]:
    """Which inputs a task's partition_by/presort must resolve against:
    zip keys must exist on every side, everything else partitions its
    first input."""
    if task.extension is _b.Zip:
        return list(range(len(task.inputs)))
    return [0]


# ---- schema transfer functions ---------------------------------------------
def _schema_of_data(data: Any, schema: Any) -> SchemaInfo:
    import pandas as pd

    from fugue_tpu.dataframe import DataFrame

    if schema is not None:
        return SchemaInfo(schema=Schema(schema))
    if isinstance(data, DataFrame):
        return SchemaInfo(schema=Schema(data.schema))
    if isinstance(data, pd.DataFrame):
        return SchemaInfo(schema=Schema(data))
    return SchemaInfo(reason="raw data without a declared schema")


def _transformer_output(
    task: FugueTask, inp: SchemaInfo, issues: List[PropagationIssue]
) -> SchemaInfo:
    from fugue_tpu.extensions.convert import (
        _FuncAsCoTransformer,
        _FuncAsTransformer,
        _to_output_transformer,
        _to_transformer,
    )
    from fugue_tpu.extensions.schema_hint import apply_schema_hint

    is_output = task.task_type == "output"
    to_conv = _to_output_transformer if is_output else _to_transformer
    try:
        tf = to_conv(
            task.params.get("transformer", None),
            *(() if is_output else (task.params.get("schema", None),)),
        )
    except Exception as ex:
        # the SAME conversion runs at execution: a failure here is a real
        # pre-execution catch, not an analyzer artifact
        issues.append(
            PropagationIssue(
                "convert", task, f"{type(ex).__name__}: {ex}"
            )
        )
        return SchemaInfo(reason="unconvertible transformer")
    if is_output:
        return SchemaInfo(reason="output transformer")
    if isinstance(tf, _FuncAsCoTransformer):
        try:
            return SchemaInfo(schema=Schema(tf._schema_hint))
        except Exception:
            return SchemaInfo(reason="cotransformer schema hint not static")
    if isinstance(tf, _FuncAsTransformer):
        hint = tf._schema_hint
        try:
            if inp.schema is not None:
                return SchemaInfo(schema=apply_schema_hint(inp.schema, hint))
            if isinstance(hint, str) and "*" not in hint and not hint.startswith(
                ("+", "-")
            ):
                # hint independent of the input schema
                return SchemaInfo(schema=Schema(hint))
        except Exception as ex:
            issues.append(
                PropagationIssue("duplicate", task, f"schema hint {hint!r}: {ex}")
            )
            return SchemaInfo(reason="inapplicable schema hint")
        return SchemaInfo(reason="schema hint needs the (unknown) input schema")
    # an interface Transformer: ask it, feeding a schema-only stub — user
    # implementations overwhelmingly only touch df.schema
    if inp.schema is not None and not inp.zipped:
        class _Stub:
            schema = inp.schema

        try:
            return SchemaInfo(schema=Schema(tf.get_output_schema(_Stub())))
        except Exception:
            return SchemaInfo(reason="get_output_schema is not static")
    return SchemaInfo(reason="transformer over an unknown input schema")


def _select_output(
    task: FugueTask, inp: SchemaInfo, issues: List[PropagationIssue]
) -> SchemaInfo:
    cols = task.params.get("columns", None)
    all_cols = getattr(cols, "all_cols", None) or []
    if inp.schema is None:
        names = [
            c.output_name
            for c in all_cols
            if getattr(c, "output_name", "") not in ("", "*")
        ]
        if len(names) == len(all_cols) and len(set(names)) == len(names):
            return SchemaInfo(columns=names)
        return SchemaInfo(reason="select over an unknown input schema")
    out = Schema()
    try:
        for c in all_cols:
            if isinstance(c, _NamedColumnExpr) and c.wildcard:
                out += inp.schema
            else:
                out += c.infer_schema_field(inp.schema)
        return SchemaInfo(schema=out)
    except KeyError as ex:
        issues.append(PropagationIssue("duplicate", task, f"select list: {ex}"))
        return SchemaInfo(reason="conflicting select output")
    except Exception:
        return SchemaInfo(reason="select output not inferable")


def _join_output(
    task: FugueTask, inputs: List[SchemaInfo], issues: List[PropagationIssue]
) -> SchemaInfo:
    how = str(task.params.get("how", "")).lower()
    on = [c for c in task.params.get("on", None) or [] if isinstance(c, str)]
    if any(not i.known for i in inputs):
        return SchemaInfo(reason="join side with unknown schema")
    if how in ("semi", "anti", "left_semi", "left_anti"):
        first = inputs[0]
        return (
            SchemaInfo(schema=first.schema)
            if first.schema is not None
            else SchemaInfo(columns=list(first.columns or []))
        )
    names: List[str] = []
    dup: List[str] = []
    for i, info in enumerate(inputs):
        for n in info.columns or []:
            if n in names:
                if i > 0 and n in on:
                    continue  # shared join key appears once
                dup.append(n)
            else:
                names.append(n)
    if dup:
        issues.append(
            PropagationIssue(
                "duplicate",
                task,
                f"{how} join would duplicate non-key column(s) {sorted(set(dup))}",
            )
        )
        return SchemaInfo(reason="conflicting join output")
    if all(i.schema is not None for i in inputs):
        fields: List[Any] = []
        by_name: Dict[str, Any] = {}
        for info in inputs:
            for f in info.schema.fields:  # type: ignore[union-attr]
                if f.name not in by_name:
                    by_name[f.name] = f
                    fields.append(f)
        return SchemaInfo(schema=Schema(fields))
    return SchemaInfo(columns=names)


def _passthrough(inp: SchemaInfo) -> SchemaInfo:
    if inp.schema is not None:
        return SchemaInfo(schema=inp.schema)
    if inp.columns is not None:
        return SchemaInfo(columns=list(inp.columns), zipped=inp.zipped)
    return SchemaInfo(zipped=inp.zipped, reason=inp.reason or "unknown input")


def _output_of(
    task: FugueTask,
    inputs: List[SchemaInfo],
    issues: List[PropagationIssue],
) -> SchemaInfo:
    ext = task.extension
    p = task.params
    inp = inputs[0] if inputs else UNKNOWN
    if task.task_type == "output":
        if ext is _b.RunOutputTransformer:
            return _transformer_output(task, inp, issues)
        return SchemaInfo(reason="output task")
    if task.task_type == "create":
        if ext is _b.CreateData:
            return _schema_of_data(p.get("data", None), p.get("schema", None))
        if ext is _b.Load:
            columns = p.get("columns", None)
            if isinstance(columns, str):
                return SchemaInfo(schema=Schema(columns))
            if isinstance(columns, (list, tuple)) and all(
                isinstance(c, str) for c in columns
            ) and len(columns) > 0:
                return SchemaInfo(columns=list(columns))
            return SchemaInfo(reason="load without declared columns")
        # custom creator: a static schema hint is the only knowledge source
        from fugue_tpu.extensions.convert import _to_creator

        try:
            creator = _to_creator(ext, task.schema)
            hint = getattr(creator, "_schema_hint", None)
            if hint is not None:
                return SchemaInfo(schema=Schema(hint))
        except Exception as ex:
            issues.append(PropagationIssue("convert", task, f"{type(ex).__name__}: {ex}"))
            return SchemaInfo(reason="unconvertible creator")
        return SchemaInfo(reason="creator without a schema hint")
    # ---- processors --------------------------------------------------------
    if ext is _b.RunTransformer:
        return _transformer_output(task, inp, issues)
    if ext in (
        _b.Distinct,
        _b.Dropna,
        _b.Fillna,
        _b.Sample,
        _b.Take,
        _b.Filter,
        _b.SaveAndUse,
        _b.RunSetOperation,
    ):
        return _passthrough(inp)
    if ext is _b.RunJoin:
        return _join_output(task, inputs, issues)
    if ext is _b.Zip:
        return SchemaInfo(zipped=True, reason="zipped (serialized) frame")
    if ext is _b.RunSQLSelect:
        return SchemaInfo(reason="raw SQL output")
    if ext is _b.Select:
        return _select_output(task, inp, issues)
    if ext is _b.Assign:
        if inp.columns is None:
            return SchemaInfo(reason="assign over an unknown input schema")
        cols = p.get("columns", None) or []
        if inp.schema is not None:
            try:
                fields = list(inp.schema.fields)
                by_name = {f.name: i for i, f in enumerate(fields)}
                for c in cols:
                    f = c.infer_schema_field(inp.schema)
                    if f.name in by_name:
                        fields[by_name[f.name]] = f
                    else:
                        by_name[f.name] = len(fields)
                        fields.append(f)
                return SchemaInfo(schema=Schema(fields))
            except Exception:
                pass
        names = list(inp.columns)
        for c in cols:
            n = getattr(c, "output_name", "")
            if n and n not in names:
                names.append(n)
        return SchemaInfo(columns=names)
    if ext is _b.Aggregate:
        keys = task.partition_spec.partition_by
        aliases = [
            getattr(c, "output_name", "") for c in p.get("columns", None) or []
        ]
        if inp.schema is not None and all(k in inp.schema for k in keys):
            try:
                out = Schema(inp.schema.extract(keys))
                for c in p.get("columns", None) or []:
                    out += c.infer_schema_field(inp.schema)
                return SchemaInfo(schema=out)
            except Exception:
                pass
        names = [k for k in keys] + [a for a in aliases if a]
        return SchemaInfo(columns=names) if names else SchemaInfo(
            reason="aggregate output not inferable"
        )
    if ext is _b.Rename:
        columns = p.get("columns", None) or {}
        if inp.schema is not None:
            # missing keys are FWF103's finding; propagate what resolves
            present = {k: v for k, v in columns.items() if k in inp.schema}
            try:
                return SchemaInfo(schema=inp.schema.rename(present))
            except Exception as ex:
                issues.append(PropagationIssue("duplicate", task, f"rename: {ex}"))
                return SchemaInfo(reason="conflicting rename output")
        if inp.columns is not None:
            names = [columns.get(n, n) for n in inp.columns]
            if len(set(names)) != len(names):
                issues.append(
                    PropagationIssue(
                        "duplicate", task, f"rename causes duplicated names {names}"
                    )
                )
                return SchemaInfo(reason="conflicting rename output")
            return SchemaInfo(columns=names)
        return _passthrough(inp)
    if ext is _b.AlterColumns:
        if inp.schema is not None:
            try:
                sub = Schema(p.get("columns", ""))
                present = Schema([f for f in sub.fields if f.name in inp.schema])
                return SchemaInfo(schema=inp.schema.alter(present))
            except Exception:
                return SchemaInfo(reason="alter_columns output not inferable")
        return _passthrough(inp)
    if ext is _b.DropColumns:
        names = [c for c in p.get("columns", None) or [] if isinstance(c, str)]
        if inp.schema is not None:
            return SchemaInfo(
                schema=Schema([f for f in inp.schema.fields if f.name not in names])
            )
        if inp.columns is not None:
            return SchemaInfo(columns=[n for n in inp.columns if n not in names])
        return _passthrough(inp)
    if ext is _b.SelectColumnsP:
        names = [c for c in p.get("columns", None) or [] if isinstance(c, str)]
        if inp.schema is not None:
            return SchemaInfo(
                schema=Schema([inp.schema[n] for n in names if n in inp.schema])
            )
        if inp.columns is not None:
            return SchemaInfo(columns=[n for n in names if n in inp.columns])
        return SchemaInfo(reason="column select over an unknown schema")
    # custom processor: only a declared schema hint is static knowledge
    from fugue_tpu.extensions.convert import _to_processor

    try:
        proc = _to_processor(ext, task.schema)
        hint = getattr(proc, "_schema_hint", None)
        if hint is not None:
            return SchemaInfo(schema=Schema(hint))
    except Exception as ex:
        issues.append(PropagationIssue("convert", task, f"{type(ex).__name__}: {ex}"))
        return SchemaInfo(reason="unconvertible processor")
    return SchemaInfo(reason="opaque processor")


def propagate(
    tasks: List[FugueTask],
) -> Tuple[Dict[int, SchemaInfo], List[PropagationIssue]]:
    """One topological sweep (workflow task lists are already in build =
    dependency order): id(task) -> output SchemaInfo, plus the issues
    discovered on the way. Never raises: an unhandled transfer failure
    degrades that task (and its consumers) to unknown."""
    infos: Dict[int, SchemaInfo] = {}
    issues: List[PropagationIssue] = []
    for t in tasks:
        inputs = [infos.get(id(i), UNKNOWN) for i in t.inputs]
        try:
            infos[id(t)] = _output_of(t, inputs, issues)
        except Exception as ex:  # pragma: no cover - defensive
            infos[id(t)] = SchemaInfo(reason=f"propagation failed: {ex}")
    return infos, issues


# ---- rules ------------------------------------------------------------------
def _check_names_against(
    ctx: Any,
    task: FugueTask,
    names: Iterable[str],
    input_indices: List[int],
    where: str,
    rule: Rule,
) -> Iterator[Diagnostic]:
    for name in names:
        for idx in input_indices:
            if idx >= len(task.inputs):
                continue
            info = ctx.input_info(task, idx)
            if info.has_column(name) is False:
                known = ", ".join(info.columns or [])
                yield rule.diag(
                    f"{where} references unknown column '{name}' "
                    f"(input columns: [{known}])",
                    task=task,
                )
                break  # one diagnostic per name


@register_rule
class PartitionColumnRule(Rule):
    code = "FWF101"
    severity = Severity.ERROR
    description = "partition_by references a column missing from the input schema"

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        for t in ctx.tasks:
            by = t.partition_spec.partition_by
            if not by or not t.inputs:
                continue
            yield from _check_names_against(
                ctx, t, by, partition_check_inputs(t), "partition_by", self
            )


@register_rule
class PresortColumnRule(Rule):
    code = "FWF102"
    severity = Severity.ERROR
    description = "presort references a column missing from the input schema"

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        for t in ctx.tasks:
            if not t.inputs:
                continue
            keys = list(t.partition_spec.presort.keys())
            if t.extension is _b.Take:
                try:
                    keys += list(parse_presort_exp(t.params.get("presort", "")).keys())
                except Exception:
                    pass
            if not keys:
                continue
            yield from _check_names_against(
                ctx, t, _dedup(keys), partition_check_inputs(t), "presort", self
            )


@register_rule
class ColumnReferenceRule(Rule):
    code = "FWF103"
    severity = Severity.ERROR
    description = (
        "select/rename/drop/subset/join-on references an unknown column"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        for t in ctx.tasks:
            if not t.inputs:
                continue
            for ref in column_refs(t):
                yield from _check_names_against(
                    ctx, t, [ref.column], ref.input_indices, ref.where, self
                )


@register_rule
class UnverifiableConsumerRule(Rule):
    code = "FWF104"
    severity = Severity.INFO
    description = (
        "a schema-less producer feeds a consumer that references specific "
        "columns (statically unverifiable)"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        for t in ctx.tasks:
            if not t.inputs:
                continue
            names = _dedup(
                list(t.partition_spec.partition_by)
                + list(t.partition_spec.presort.keys())
                + [r.column for r in column_refs(t)]
            )
            if not names:
                continue
            unknown_inputs = [
                i
                for i, inp in enumerate(t.inputs)
                if not ctx.input_info(t, i).known and not ctx.input_info(t, i).zipped
            ]
            if unknown_inputs:
                info = ctx.input_info(t, unknown_inputs[0])
                yield self.diag(
                    f"cannot statically verify column(s) {names}: input "
                    f"schema is unknown ({info.reason or 'opaque upstream'})",
                    task=t,
                )


@register_rule
class DuplicateOutputRule(Rule):
    code = "FWF105"
    severity = Severity.ERROR
    description = "duplicate/conflicting output columns (hint, rename, join)"

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        for issue in ctx.issues:
            if issue.kind == "duplicate":
                yield self.diag(issue.message, task=issue.task)


@register_rule
class ExtensionConvertRule(Rule):
    code = "FWF106"
    severity = Severity.ERROR
    description = (
        "an extension cannot be statically adapted (missing schema hint or "
        "bad signature) — the identical conversion runs at execution time"
    )

    def check(self, ctx: Any) -> Iterable[Diagnostic]:
        for issue in ctx.issues:
            if issue.kind == "convert":
                yield self.diag(issue.message, task=issue.task)
