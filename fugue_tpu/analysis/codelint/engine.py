"""The source-lint driver: parses a file set once, builds the shared
:class:`LintContext` (per-module ASTs, qualname attribution, lock
tables, function call/acquisition summaries) and runs every registered
``FLN###`` rule over it.

The analyses are deliberately *lexical and intra-module where they must
approximate*: FLN101's nesting edges come from ``with``-block
containment plus a same-module call-graph closure (a ``with self._lock``
block that calls a method acquiring another lock contributes an edge),
never from cross-module data flow — honest static scope, zero false
"proofs". The runtime sanitizer (:mod:`fugue_tpu.testing.locktrace`)
covers the interleavings the static view cannot.
"""

import ast
import os
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from fugue_tpu.analysis.codelint.model import (
    SourceDiagnostic,
    all_source_rules,
)
from fugue_tpu.analysis.diagnostics import Severity

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def _literal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class FunctionSummary:
    """What one function does, for the interprocedural closure: locks it
    acquires anywhere (name -> first site line), and the same-module
    callees it invokes (``self.m()`` -> method, ``f()`` -> module fn)."""

    def __init__(self, qualname: str, node: ast.AST):
        self.qualname = qualname
        self.node = node
        self.acquires: Dict[str, int] = {}
        self.calls: List[Tuple[str, int]] = []  # (callee key, line)
        # closure of `acquires` over same-module calls, filled by the
        # module fixpoint: lock name -> (line, via) of the witness site
        self.reachable: Dict[str, Tuple[int, str]] = {}


class ModuleInfo:
    """One parsed file plus the per-node attribution the rules share."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel  # package-relative display path (posix slashes)
        self.source = source
        self.tree = ast.parse(source)
        # id(node) -> enclosing qualname ("Class.method" / "fn" / "")
        self.qualnames: Dict[int, str] = {}
        # id(Constant) of module/class/function docstrings
        self.docstrings: Set[int] = set()
        # lock tables ------------------------------------------------------
        # module-level lock names: var name -> canonical lock name
        self.module_locks: Dict[str, str] = {}
        # (class, attr) -> canonical; attr -> [canonical, ...] fallback
        self.class_locks: Dict[Tuple[str, str], str] = {}
        self.attr_locks: Dict[str, List[str]] = {}
        # thread-locals / ContextVars --------------------------------------
        self.module_tls: Set[str] = set()  # module-level names
        self.attr_tls: Set[str] = set()  # self.<attr> names
        self.module_cvars: Set[str] = set()
        # function summaries: qualname -> FunctionSummary
        self.functions: Dict[str, FunctionSummary] = {}
        self._annotate()
        self._collect_locks()

    # ---- attribution -----------------------------------------------------
    def _annotate(self) -> None:
        def mark_docstring(node: ast.AST) -> None:
            body = getattr(node, "body", None)
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                self.docstrings.add(id(body[0].value))

        mark_docstring(self.tree)

        def walk(node: ast.AST, stack: List[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.ClassDef,) + _FUNC_NODES):
                    mark_docstring(child)
                    self.qualnames[id(child)] = ".".join(stack)
                    walk(child, stack + [child.name])
                else:
                    self.qualnames[id(child)] = ".".join(stack)
                    walk(child, stack)

        walk(self.tree, [])

    def qualname(self, node: ast.AST) -> str:
        return self.qualnames.get(id(node), "")

    def enclosing_class(self, node: ast.AST) -> str:
        q = self.qualname(node)
        return q.split(".", 1)[0] if q else ""

    # ---- lock / TLS / ContextVar discovery -------------------------------
    def _lock_ctor(self, value: ast.AST) -> Optional[Tuple[str, bool]]:
        """(canonical_or_None_marker, is_tracked) when ``value`` builds a
        lock: ``tracked_lock("name", ...)`` -> (name, True); a bare
        ``threading.Lock()/RLock()`` -> (None, False)."""
        if not isinstance(value, ast.Call):
            return None
        name = call_name(value)
        if name in ("tracked_lock", "locktrace.tracked_lock") or (
            name is not None and name.endswith(".tracked_lock")
        ):
            lit = _literal(value.args[0]) if value.args else None
            return (lit, True)
        if name in _LOCK_CTORS:
            return (None, False)
        return None

    def _collect_locks(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            ctor = self._lock_ctor(node.value)
            vname = call_name(node.value) if isinstance(node.value, ast.Call) else None
            is_tls = vname in ("threading.local", "local")
            is_cvar = vname in ("ContextVar", "contextvars.ContextVar")
            if ctor is None and not is_tls and not is_cvar:
                continue
            if isinstance(target, ast.Name):
                if is_tls:
                    self.module_tls.add(target.id)
                elif is_cvar:
                    self.module_cvars.add(target.id)
                else:
                    canonical = ctor[0] or f"{self.rel}:{target.id}"
                    self.module_locks[target.id] = canonical
            elif isinstance(target, ast.Attribute) and isinstance(
                target.value, ast.Name
            ) and target.value.id == "self":
                cls = self.enclosing_class(node)
                if is_tls:
                    self.attr_tls.add(target.attr)
                elif is_cvar:
                    pass  # instance ContextVars: out of static scope
                else:
                    canonical = ctor[0] or f"{self.rel}:{cls}.{target.attr}"
                    self.class_locks[(cls, target.attr)] = canonical
                    self.attr_locks.setdefault(target.attr, []).append(canonical)

    def resolve_lock(self, expr: ast.AST, at: ast.AST) -> Optional[str]:
        """The canonical lock name of an expression, or None when it is
        not (known to be) a lock. ``self.X`` resolves through the
        enclosing class; ``obj.X`` falls back to the attr name when it
        is unambiguous module-wide."""
        if isinstance(expr, ast.Name):
            return self.module_locks.get(expr.id)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                cls = self.enclosing_class(at)
                hit = self.class_locks.get((cls, expr.attr))
                if hit is not None:
                    return hit
            candidates = self.attr_locks.get(expr.attr, [])
            if len(candidates) == 1:
                return candidates[0]
        return None


class LintContext:
    """Everything a source rule may consult."""

    def __init__(self, modules: List[ModuleInfo]):
        self.modules = modules
        for m in modules:
            _summarize_functions(m)
            _close_acquires(m)

    def functions(self) -> Iterable[Tuple[ModuleInfo, FunctionSummary]]:
        for m in self.modules:
            for fs in m.functions.values():
                yield m, fs


# ---- function summaries -----------------------------------------------------
def _summarize_functions(mod: ModuleInfo) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, _FUNC_NODES):
            continue
        enclosing = mod.qualname(node)
        qual = f"{enclosing}.{node.name}" if enclosing else node.name
        fs = FunctionSummary(qual, node)
        cls = enclosing.split(".", 1)[0] if enclosing else ""
        for sub in ast.walk(node):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    lock = mod.resolve_lock(item.context_expr, sub)
                    if lock is not None:
                        fs.acquires.setdefault(lock, sub.lineno)
            elif isinstance(sub, ast.Call):
                name = call_name(sub)
                if name is None:
                    continue
                if name.endswith(".acquire"):
                    lock = mod.resolve_lock(sub.func.value, sub)  # type: ignore[attr-defined]
                    if lock is not None:
                        fs.acquires.setdefault(lock, sub.lineno)
                elif name.startswith("self.") and name.count(".") == 1:
                    meth = name.split(".", 1)[1]
                    fs.calls.append((f"{cls}.{meth}" if cls else meth, sub.lineno))
                elif "." not in name:
                    fs.calls.append((name, sub.lineno))
        mod.functions[qual] = fs


def _close_acquires(mod: ModuleInfo) -> None:
    """Fixpoint: a function 'reaches' every lock it acquires directly
    plus everything its same-module callees reach."""
    for fs in mod.functions.values():
        fs.reachable = {
            lock: (line, fs.qualname) for lock, line in fs.acquires.items()
        }
    changed = True
    while changed:
        changed = False
        for fs in mod.functions.values():
            for callee, line in fs.calls:
                target = mod.functions.get(callee)
                if target is None:
                    continue
                for lock, (_, via) in target.reachable.items():
                    if lock not in fs.reachable:
                        # witness: the CALL site inside fs, noting the
                        # callee that ultimately takes the lock
                        fs.reachable[lock] = (line, via)
                        changed = True


# ---- tree loading -----------------------------------------------------------
def package_root() -> str:
    """The installed ``fugue_tpu`` package directory (the default lint
    target: the tree gates itself)."""
    import fugue_tpu

    return os.path.dirname(os.path.abspath(fugue_tpu.__file__))


def _iter_py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d not in ("__pycache__",)
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def load_tree(root: Optional[str] = None) -> Tuple[List[ModuleInfo], List[SourceDiagnostic]]:
    """Parse every ``.py`` under ``root`` (default: the fugue_tpu
    package). Unparseable files become error diagnostics, never a
    crashed lint."""
    root = root or package_root()
    base = os.path.dirname(os.path.abspath(root))
    modules: List[ModuleInfo] = []
    problems: List[SourceDiagnostic] = []
    for path in _iter_py_files(root):
        rel = os.path.relpath(path, base).replace(os.sep, "/")
        try:
            with open(path, "r") as fp:
                source = fp.read()
            modules.append(ModuleInfo(path, rel, source))
        except (OSError, SyntaxError, ValueError) as ex:
            problems.append(
                SourceDiagnostic(
                    "FLN001",
                    Severity.ERROR,
                    f"could not parse: {type(ex).__name__}: {ex}",
                    path=rel,
                    rule="parse",
                )
            )
    return modules, problems


def lint_modules(modules: List[ModuleInfo]) -> List[SourceDiagnostic]:
    import fugue_tpu.analysis.codelint.rules_locks  # noqa: F401
    import fugue_tpu.analysis.codelint.rules_threads  # noqa: F401
    import fugue_tpu.analysis.codelint.rules_vocab  # noqa: F401

    ctx = LintContext(modules)
    out: List[SourceDiagnostic] = []
    for rule_cls in all_source_rules():
        out.extend(rule_cls().check(ctx))
    out.sort(key=lambda d: (-int(d.severity), d.path, d.line))
    return out


def lint_tree(root: Optional[str] = None) -> List[SourceDiagnostic]:
    modules, problems = load_tree(root)
    return problems + lint_modules(modules)


def lint_text(source: str, rel: str = "fugue_tpu/fixture.py") -> List[SourceDiagnostic]:
    """Lint one in-memory module (the fixture-corpus entry point)."""
    return lint_modules([ModuleInfo(rel, rel, source)])
