"""Source-level concurrency/invariant linter — the second static-
analysis plane. Where :mod:`fugue_tpu.analysis` lints USER workflow
DAGs (``FWF###``), this package lints the CODEBASE ITSELF (``FLN###``):
the concurrency invariants that previously lived only in changelog
prose, machine-checked on every PR.

Rules (each an error unless baselined with a justification):

- **FLN101** lock-order inversion/cycle over the statically-extracted
  lock-acquisition graph (canonical hierarchy: ``lockspec.py``)
- **FLN102** ``threading.Thread`` without a join-on-stop path or
  ``spawn_warm_thread``-style bounded atexit registration
- **FLN103** thread-local/ContextVar set without a paired restore
  (finally / ``__enter__``+``__exit__`` / token reset)
- **FLN104** blocking IO/sleep/network call while holding a registered
  lock
- **FLN105** raw ``open()``/``os.remove`` on engine/serve paths that
  must route through ``engine.fs``
- **FLN106** string-literal ``fugue.*`` conf key missing from the
  ``constants.py`` registry (source-side complement of FWF201)
- **FLN107** ``fault_point`` site missing from ``KNOWN_SITES`` / metric
  name outside ``METRIC_NAME_PREFIXES``

Front doors: ``python -m fugue_tpu.analysis --lint-source`` (exit-code
contract matching the workflow linter), :func:`lint_tree` /
:func:`lint_text` for embedding, and the tier-1 ``codelint`` test
module that lints the live tree — the gate enforces itself.

The runtime half of this plane is the opt-in lock-order sanitizer in
:mod:`fugue_tpu.testing.locktrace`.
"""

from fugue_tpu.analysis.codelint.baseline import (
    BaselineEntry,
    DEFAULT_BASELINE,
    apply_baseline,
    load_baseline,
    stale_diags,
)
from fugue_tpu.analysis.codelint.engine import (
    LintContext,
    ModuleInfo,
    lint_text,
    lint_tree,
    load_tree,
    package_root,
)
from fugue_tpu.analysis.codelint.model import (
    SourceDiagnostic,
    SourceRule,
    all_source_rules,
    register_source_rule,
)

__all__ = [
    "BaselineEntry",
    "DEFAULT_BASELINE",
    "LintContext",
    "ModuleInfo",
    "SourceDiagnostic",
    "SourceRule",
    "all_source_rules",
    "apply_baseline",
    "lint_text",
    "lint_tree",
    "load_tree",
    "load_baseline",
    "package_root",
    "register_source_rule",
    "stale_diags",
]
