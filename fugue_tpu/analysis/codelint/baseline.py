"""Justification-required baseline for accepted FLN exceptions.

The gate must be zero-error on the shipped tree, but some findings are
*intentional* (a fire-and-forget drain thread started from a signal
handler cannot be joined by design). Those live in ``baseline.json``
next to this module: every entry names the rule code, the file, the
enclosing qualname, and a NON-EMPTY one-line justification — an entry
without a justification is itself an error, and an entry that matches
nothing is reported stale (warn) so the baseline can only shrink.

Format::

    {"entries": [
      {"code": "FLN102",
       "file": "fugue_tpu/workflow/runner.py",
       "context": "DAGRunner._spawn",
       "justification": "why this exception is sound"}
    ]}
"""

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from fugue_tpu.analysis.codelint.model import SourceDiagnostic
from fugue_tpu.analysis.diagnostics import Severity

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)


class BaselineEntry:
    __slots__ = ("code", "file", "context", "justification", "used")

    def __init__(self, code: str, file: str, context: str, justification: str):
        self.code = code
        self.file = file
        self.context = context
        self.justification = justification
        self.used = 0

    def matches(self, d: SourceDiagnostic) -> bool:
        return (
            d.code == self.code
            and (d.path == self.file or d.path.endswith("/" + self.file))
            and (self.context == "" or self.context in (d.qualname or ""))
        )


def load_baseline(
    path: Optional[str] = None,
) -> Tuple[List[BaselineEntry], List[SourceDiagnostic]]:
    """Entries plus any problems with the baseline ITSELF (unreadable
    file, entry without justification) as error diagnostics."""
    path = path or DEFAULT_BASELINE
    problems: List[SourceDiagnostic] = []
    if not os.path.isfile(path):
        return [], problems
    try:
        with open(path, "r") as fp:
            payload = json.load(fp)
    except (OSError, ValueError) as ex:
        return [], [
            SourceDiagnostic(
                "FLN002",
                Severity.ERROR,
                f"unreadable baseline: {type(ex).__name__}: {ex}",
                path=path,
                rule="baseline",
            )
        ]
    entries: List[BaselineEntry] = []
    for i, raw in enumerate(payload.get("entries", [])):
        entry = BaselineEntry(
            str(raw.get("code", "")),
            str(raw.get("file", "")),
            str(raw.get("context", "")),
            str(raw.get("justification", "")).strip(),
        )
        if entry.justification == "":
            problems.append(
                SourceDiagnostic(
                    "FLN002",
                    Severity.ERROR,
                    f"baseline entry #{i} ({entry.code} {entry.file}) has "
                    "no justification: accepted exceptions must say WHY",
                    path=path,
                    line=0,
                    rule="baseline",
                )
            )
            continue
        entries.append(entry)
    return entries, problems


def apply_baseline(
    diags: List[SourceDiagnostic], entries: List[BaselineEntry]
) -> Tuple[List[SourceDiagnostic], List[SourceDiagnostic], List[BaselineEntry]]:
    """(kept, suppressed, stale_entries): each diagnostic is suppressed
    by the first matching entry; entries that matched nothing are stale."""
    kept: List[SourceDiagnostic] = []
    suppressed: List[SourceDiagnostic] = []
    for d in diags:
        hit = next((e for e in entries if e.matches(d)), None)
        if hit is not None:
            hit.used += 1
            suppressed.append(d)
        else:
            kept.append(d)
    stale = [e for e in entries if e.used == 0]
    return kept, suppressed, stale


def stale_diags(stale: List[BaselineEntry], path: Optional[str] = None) -> List[SourceDiagnostic]:
    return [
        SourceDiagnostic(
            "FLN003",
            Severity.WARN,
            f"stale baseline entry: {e.code} {e.file} [{e.context}] no "
            "longer matches any finding — the exception was fixed, prune "
            "the entry",
            path=path or DEFAULT_BASELINE,
            rule="baseline",
        )
        for e in stale
    ]
