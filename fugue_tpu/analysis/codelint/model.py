"""Diagnostic/rule model of the SOURCE linter — the second static-
analysis plane (codes ``FLN###``), mirroring the workflow analyzer's
``Rule``/``Diagnostic`` registry idiom (:mod:`fugue_tpu.analysis.
diagnostics`) but attributed to ``file:line`` instead of task/callsite:
the subject here is the codebase itself, not a user DAG."""

from typing import Any, Dict, Iterable, List, Optional, Type

from fugue_tpu.analysis.diagnostics import Severity


class SourceDiagnostic:
    """One source-lint finding: stable rule code, severity, message, and
    the offending ``file:line`` plus the enclosing ``Class.method``
    qualname (the baseline's match key)."""

    __slots__ = ("code", "severity", "message", "path", "line", "qualname", "rule")

    def __init__(
        self,
        code: str,
        severity: Severity,
        message: str,
        path: str = "",
        line: int = 0,
        qualname: str = "",
        rule: str = "",
    ):
        self.code = code
        self.severity = Severity.parse(severity)
        self.message = message
        self.path = path
        self.line = int(line)
        self.qualname = qualname
        self.rule = rule

    def describe(self) -> str:
        where = f"{self.path}:{self.line}"
        ctx = f" [in {self.qualname}]" if self.qualname else ""
        return f"{self.code} {self.severity} {where}{ctx}: {self.message}"

    def as_dict(self) -> Dict[str, Any]:
        return dict(
            code=self.code,
            severity=str(self.severity),
            message=self.message,
            path=self.path,
            line=self.line,
            qualname=self.qualname,
            rule=self.rule,
        )

    def __str__(self) -> str:
        return self.describe()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"SourceDiagnostic({self.code}, {self.path}:{self.line})"


class SourceRule:
    """One source-level check with a stable ``FLN###`` code. Rules are
    side-effect free; ``check`` runs over the whole :class:`LintContext`
    (not per file) so cross-module analyses — the FLN101 lock graph —
    see every acquisition site at once."""

    code: str = "FLN000"
    severity: Severity = Severity.ERROR
    description: str = ""

    def check(self, ctx: Any) -> Iterable[SourceDiagnostic]:  # pragma: no cover
        raise NotImplementedError

    def diag(
        self,
        message: str,
        path: str = "",
        line: int = 0,
        qualname: str = "",
        severity: Optional[Severity] = None,
    ) -> SourceDiagnostic:
        return SourceDiagnostic(
            code=self.code,
            severity=self.severity if severity is None else severity,
            message=message,
            path=path,
            line=line,
            qualname=qualname,
            rule=type(self).__name__,
        )


_SOURCE_RULES: Dict[str, Type[SourceRule]] = {}


def register_source_rule(cls: Type[SourceRule]) -> Type[SourceRule]:
    """Class decorator: register by stable code (re-registering a code
    replaces the rule, same contract as the workflow registry)."""
    _SOURCE_RULES[cls.code] = cls
    return cls


def all_source_rules() -> List[Type[SourceRule]]:
    return [_SOURCE_RULES[k] for k in sorted(_SOURCE_RULES)]
