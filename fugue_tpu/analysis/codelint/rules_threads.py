"""Thread-lifecycle and context-propagation rules.

FLN102 — every ``threading.Thread(...)`` must be joinable: bound to a
name/attribute that some code in the module ``.join()``s (directly, or
as the loop variable of a sweep over the bound collection), or it is a
fire-and-forget thread that can abort interpreter teardown (the PR 10
warm-thread lesson: a daemon thread frozen mid-XLA-deserialize at exit
kills the process from C++). Intentional fire-and-forget threads get a
justified baseline entry, not silence.

FLN103 — a thread-local slot or ContextVar set without a paired
restore leaks request state onto pooled worker threads (the PR 7
cross-thread ``as_context`` bug class). A set is paired when it sits in
a ``finally``/``__exit__`` restore path, when its enclosing function
restores the same slot in a ``finally``, when its ``__enter__`` has a
matching ``__exit__`` assignment, or — ContextVars — when the token is
captured and the module ``reset()``s it. Initializing a fresh per-thread
container (``tls.stack = []``) is state creation, not a scoped override,
and is allowed.
"""

import ast
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from fugue_tpu.analysis.codelint.engine import call_name
from fugue_tpu.analysis.codelint.model import (
    SourceDiagnostic,
    SourceRule,
    register_source_rule,
)

_THREAD_CTORS = ("threading.Thread", "Thread")


def _norm(token: str) -> str:
    return token.lstrip("_")


def _join_tokens(mod: Any) -> Set[str]:
    """Names/attrs the module joins: bases of ``X.join()`` calls plus
    the iterables of ``for v in X: ... v.join()`` sweeps."""
    tokens: Set[str] = set()
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
        ):
            base = node.func.value
            if isinstance(base, ast.Name):
                tokens.add(_norm(base.id))
            elif isinstance(base, ast.Attribute):
                tokens.add(_norm(base.attr))
        elif isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            var = node.target.id
            joins_var = any(
                isinstance(c, ast.Call)
                and isinstance(c.func, ast.Attribute)
                and c.func.attr == "join"
                and isinstance(c.func.value, ast.Name)
                and c.func.value.id == var
                for stmt in node.body
                for c in ast.walk(stmt)
            )
            if not joins_var:
                continue
            it = node.iter
            # unwrap list(X) / sorted(X) / reversed(X)
            if isinstance(it, ast.Call) and it.args:
                it = it.args[0]
            if isinstance(it, ast.Name):
                tokens.add(_norm(it.id))
            elif isinstance(it, ast.Attribute):
                tokens.add(_norm(it.attr))
    return tokens


def _thread_bindings(mod: Any) -> Dict[int, str]:
    """id(Thread Call node) -> the token it is bound to (assignment
    target, including threads built inside comprehensions/list
    literals of that assignment)."""
    bound: Dict[int, str] = {}
    for node in ast.walk(mod.tree):
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None:
            continue
        token = None
        for t in targets:
            if isinstance(t, ast.Name):
                token = _norm(t.id)
            elif isinstance(t, ast.Attribute):
                token = _norm(t.attr)
        if token is None:
            continue
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call) and call_name(sub) in _THREAD_CTORS:
                bound[id(sub)] = token
    return bound


@register_source_rule
class ThreadJoinRule(SourceRule):
    code = "FLN102"
    description = (
        "threading.Thread spawned without a join path (join-on-stop or "
        "spawn_warm_thread-style atexit registration)"
    )

    def check(self, ctx: Any) -> Iterable[SourceDiagnostic]:
        for mod in ctx.modules:
            joins = _join_tokens(mod)
            bound = _thread_bindings(mod)
            for node in ast.walk(mod.tree):
                if not (
                    isinstance(node, ast.Call)
                    and call_name(node) in _THREAD_CTORS
                ):
                    continue
                token = bound.get(id(node))
                if token is not None and token in joins:
                    continue
                detail = (
                    f"bound to '{token}' which is never joined"
                    if token is not None
                    else "never bound, so it can never be joined"
                )
                yield self.diag(
                    f"threading.Thread {detail}: an unjoined thread can "
                    "abort interpreter teardown mid-flight — join it on "
                    "stop, register a bounded atexit join "
                    "(spawn_warm_thread), or add a justified baseline "
                    "entry",
                    path=mod.rel,
                    line=node.lineno,
                    qualname=mod.qualname(node),
                )


class _TlsWrite:
    __slots__ = ("mod", "node", "token", "attr", "qualname", "fn")

    def __init__(self, mod, node, token, attr, qualname, fn):
        self.mod = mod
        self.node = node
        self.token = token  # the thread-local object's name/attr
        self.attr = attr  # the slot written
        self.qualname = qualname
        self.fn = fn  # enclosing function node (or None at module level)


def _tls_base_token(mod: Any, expr: ast.AST) -> Optional[str]:
    """Token of a known thread-local object, or None."""
    if isinstance(expr, ast.Name) and expr.id in mod.module_tls:
        return expr.id
    if isinstance(expr, ast.Attribute) and expr.attr in mod.attr_tls:
        return expr.attr
    return None


def _finally_nodes(root: ast.AST) -> Set[int]:
    out: Set[int] = set()
    for node in ast.walk(root):
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    out.add(id(sub))
                out.add(id(stmt))
    return out


def _is_container_init(value: ast.AST) -> bool:
    return isinstance(value, (ast.List, ast.Dict, ast.Set, ast.Tuple))


def _collect_tls_writes(mod: Any) -> List[_TlsWrite]:
    writes: List[_TlsWrite] = []
    fn_of: Dict[int, ast.AST] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                fn_of.setdefault(id(sub), node)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if not isinstance(target, ast.Attribute):
                continue
            token = _tls_base_token(mod, target.value)
            if token is None:
                continue
            writes.append(
                _TlsWrite(
                    mod,
                    node,
                    token,
                    target.attr,
                    mod.qualname(node),
                    fn_of.get(id(node)),
                )
            )
    return writes


@register_source_rule
class ContextRestoreRule(SourceRule):
    code = "FLN103"
    description = (
        "thread-local/ContextVar set without a paired restore on every "
        "exit path"
    )

    def check(self, ctx: Any) -> Iterable[SourceDiagnostic]:
        for mod in ctx.modules:
            yield from self._check_contextvars(mod)
            yield from self._check_thread_locals(mod)

    # ---- ContextVars -----------------------------------------------------
    def _check_contextvars(self, mod: Any) -> Iterable[SourceDiagnostic]:
        if not mod.module_cvars:
            return
        resets: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name is not None and name.endswith(".reset"):
                    base = name.rsplit(".", 1)[0]
                    resets.add(base)
        # sets whose token is DISCARDED (statement-level call) can never
        # be reset; capture their ids so the second pass skips them
        discarded: Set[int] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                name = call_name(node.value)
                if name is not None and name.endswith(".set"):
                    base = name.rsplit(".", 1)[0]
                    if base in mod.module_cvars:
                        discarded.add(id(node.value))
                        yield self.diag(
                            f"ContextVar '{base}'.set() token discarded: "
                            "without the token the var can never be "
                            "reset, leaking context onto reused threads",
                            path=mod.rel,
                            line=node.lineno,
                            qualname=mod.qualname(node),
                        )
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or id(node) in discarded:
                continue
            name = call_name(node)
            if name is None or not name.endswith(".set"):
                continue
            base = name.rsplit(".", 1)[0]
            if base in mod.module_cvars and base not in resets:
                yield self.diag(
                    f"ContextVar '{base}' is set but never reset in "
                    "this module: captured tokens must flow into a "
                    f"'{base}.reset(token)' on every exit path",
                    path=mod.rel,
                    line=node.lineno,
                    qualname=mod.qualname(node),
                )

    # ---- thread-locals ---------------------------------------------------
    def _check_thread_locals(self, mod: Any) -> Iterable[SourceDiagnostic]:
        writes = _collect_tls_writes(mod)
        if not writes:
            return
        in_finally = _finally_nodes(mod.tree)
        # (class, token, attr) -> method names that write the slot
        by_class: Dict[Tuple[str, str, str], Set[str]] = {}
        for w in writes:
            parts = w.qualname.split(".")
            if len(parts) >= 2:
                by_class.setdefault(
                    (parts[0], w.token, w.attr), set()
                ).add(parts[-1])
        for w in writes:
            if _is_container_init(w.node.value):
                continue  # fresh per-thread state, not a scoped override
            if id(w.node) in in_finally:
                continue  # this IS the restore
            method = w.qualname.split(".")[-1] if w.qualname else ""
            if method == "__exit__":
                continue  # CM restore path
            # enclosing function restores the slot in a finally?
            if w.fn is not None:
                fn_finally = _finally_nodes(w.fn)
                restored = any(
                    id(o.node) in fn_finally
                    for o in writes
                    if o.fn is w.fn
                    and o.token == w.token
                    and o.attr == w.attr
                    and o.node is not w.node
                )
                if restored:
                    continue
            # __enter__ paired with an __exit__ writing the same slot?
            parts = w.qualname.split(".")
            if method == "__enter__" and len(parts) >= 2:
                methods = by_class.get((parts[0], w.token, w.attr), set())
                if "__exit__" in methods:
                    continue
            yield self.diag(
                f"thread-local '{w.token}.{w.attr}' set without a paired "
                "restore: no finally-restore in this function, not an "
                "__enter__/__exit__ pair — the override leaks onto the "
                "next job this pooled thread runs",
                path=mod.rel,
                line=w.node.lineno,
                qualname=w.qualname,
            )
