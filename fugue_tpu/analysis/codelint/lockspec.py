"""The concurrency-invariant registries the FLN rules enforce — ONE
place declaring what CHANGES.md used to carry as prose.

- :data:`CANONICAL_LOCK_ORDER`: the repo-wide lock hierarchy, outermost
  first. A lock may only be acquired while holding locks that appear
  EARLIER in this tuple; FLN101 flags any statically-observed nesting
  that runs backwards, and any cycle among observed nestings (listed or
  not). Names are the ``tracked_lock`` names
  (:mod:`fugue_tpu.testing.locktrace`), so the static registry, the
  runtime sanitizer's reports and the source agree on vocabulary;
  locks created with a bare ``threading.Lock()`` get a synthesized
  ``<file>:<Class>.<attr>`` name and participate in cycle detection
  only.
- :data:`ENGINE_FS_PATHS`: package-relative prefixes of the engine/serve
  code that must route ALL file IO through ``engine.fs`` (the fault
  sites, URI support and chaos injection live there) — FLN105's scope.
- :data:`BLOCKING_CALLS`: dotted-name prefixes of calls that block on
  IO/sleep/network; FLN104 rejects them inside a held lock.
"""

# Outermost -> innermost. The serve plane sits above the engine plane:
# an HTTP/scheduler path may reach INTO the engine (dispatch under a
# session or scheduler lock) but engine internals must never call back
# up into serve locks. Leaf bookkeeping locks (metrics, faults, stats)
# come last: they are acquired everywhere and may never hold anything.
CANONICAL_LOCK_ORDER = (
    # fleet plane (outermost: the fleet's replica-set mutations hold
    # their lock across router failover and replica HTTP forwards; the
    # autoscaler's own lock guards decision counters only and is never
    # held across an action. The router owns replicas and affinity and
    # reaches replicas over HTTP only, never into their locks —
    # failover serializes above the routing map)
    "serve.fleet.ServeFleet._lock",
    "serve.autoscale.FleetAutoscaler._lock",
    "serve.fleet.FleetRouter._failover_lock",
    "serve.fleet.FleetRouter._lock",
    # serve plane (owns requests and jobs)
    "serve.daemon.ServeDaemon._first_query_lock",
    "serve.daemon.ServeDaemon._views_lock",
    "serve.scheduler.JobScheduler._lock",
    # predictive-admission bookkeeping: the scheduler updates these
    # under its own lock (submit/pick hooks), so they rank below it;
    # O(1) arithmetic only, nothing is acquired under them
    "serve.admission.QueryCostModel._lock",
    "serve.admission.PredictiveAdmission._lock",
    "serve.session.SessionManager._lock",
    # stream plane: the standing-pipeline step claim sits ABOVE the
    # session lock (a view refresh calls session.save_table) but the
    # claim flag's critical sections are O(1) — fold/IO never run under
    # it (steps coalesce through the busy flag instead)
    "stream.pipeline.StandingPipeline._lock",
    "serve.session.ServeSession._lock",
    "serve.scheduler.ServeJob._finish_lock",
    "serve.supervisor.EngineSupervisor._lock",
    "serve.supervisor.CircuitBreaker._lock",
    "serve.supervisor.HealthState._lock",
    "serve.state.ServeStateJournal._lock",
    # the ONE lock journal IO may run under (see baseline.json FLN104):
    # state locks snapshot above it, nothing is acquired below it
    "serve.state.SnapshotWriter._lock",
    # engine plane
    "execution.engine._GLOBAL_LOCK",
    "execution.engine.ExecutionEngine._ctx_lock",
    "execution.engine.ExecutionEngine._stop_lock",
    "jax.engine.JaxExecutionEngine._dispatch_lock",
    "jax.memory.MemoryGovernor._lock",
    "optimize.cache.PlanCache._lock",
    "optimize.exec_cache._WORKER_LOCK",
    "optimize.exec_cache._WARM_LOCK",
    "optimize.exec_cache._FN_HASH_LOCK",
    # leaf bookkeeping (held for O(1) mutations only; never nest)
    "jax.engine.JaxExecutionEngine._dispatch_secs_lock",
    # lake-table bookkeeping: guards the cached head/manifest memo only.
    # Commit/scan IO NEVER runs under it (snapshot-then-write, the same
    # discipline FLN104 enforces for the journal helpers) — writers on
    # different PROCESSES serialize through the manifest CAS, not locks
    "lake.table.LakeTable._lock",
    "workflow.manifest.RunManifest._lock",
    "workflow.fault.RunStats._lock",
    "testing.faults._ACTIVE_LOCK",
    "testing.faults.FaultPlan._lock",
    "obs.trace.Trace._lock",
    "obs.metrics.MetricsRegistry._lock",
    "obs.metrics.MetricFamily._lock",
)

LOCK_RANK = {name: i for i, name in enumerate(CANONICAL_LOCK_ORDER)}

# package-relative path prefixes whose file IO must go through engine.fs
ENGINE_FS_PATHS = (
    "fugue_tpu/serve/",
    "fugue_tpu/lake/",
    "fugue_tpu/jax_backend/",
    "fugue_tpu/optimize/",
    "fugue_tpu/obs/",
    "fugue_tpu/stream/",
    "fugue_tpu/workflow/",
)

# dotted-call prefixes that block (IO, sleep, network, subprocess):
# forbidden while holding any registered lock (FLN104). The engine-fs
# JSON/IO helpers (workflow/manifest.py) are listed by bare name: they
# stream through shared/remote filesystems, so calling one under a
# request-path lock stalls every thread queued on it behind a slow
# mount — exactly the journal-write shape ISSUE 13 removed from
# ServeStateJournal (snapshot under the state lock, write through the
# dedicated SnapshotWriter outside it).
BLOCKING_CALLS = (
    "time.sleep",
    "open",
    "urllib.",
    "requests.",
    "socket.",
    "subprocess.",
    "os.system",
    "http.client.",
    "atomic_json_write",
    "read_json",
    "artifact_fingerprint",
)
