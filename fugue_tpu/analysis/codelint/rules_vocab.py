"""Registry-vocabulary rules: the source-side complements of the
runtime registries.

FLN105 — engine/serve-path file IO must route through ``engine.fs``:
the fs layer owns fault sites (``fs.open``/``fs.write``), URI schemes
and atomic-write semantics; a raw ``open()``/``os.remove`` there
bypasses chaos injection and breaks object-store deployments.

FLN106 — every string-literal ``fugue.*`` conf key must be declared in
the :mod:`fugue_tpu.constants` registry (the source-side complement of
the runtime FWF201 rule: an undeclared key is silently ignored by every
engine getter AND unlintable for users).

FLN107 — ``fault_point(site, ...)`` literals must come from
``testing/faults.py KNOWN_SITES`` (a typo'd site never fires, so the
chaos test silently stops testing anything), and literal metric names
must fall under ``obs/metrics.py METRIC_NAME_PREFIXES`` (one dashboard
namespace, no silent forks).

FLN108 — no eager default-device placement on engine paths
(``fugue_tpu/jax_backend/``): a single-argument ``jax.device_put``
pins data to the process default device — which belongs to a DIFFERENT
replica's slice when engines carve up the pod via ``fugue.jax.devices``
— and a module-level ``jnp.array/zeros/...`` allocates on that device
at import time, before any mesh exists. Placement must name its
sharding (``device_put(x, sharding)``) or happen inside traced/mesh-
scoped code.
"""

import ast
import re
from typing import Any, Iterable, List

from fugue_tpu.analysis.codelint.engine import call_name
from fugue_tpu.analysis.codelint.lockspec import ENGINE_FS_PATHS
from fugue_tpu.analysis.codelint.model import (
    SourceDiagnostic,
    SourceRule,
    register_source_rule,
)

_RAW_IO_CALLS = {
    "open": "engine.fs.open_read/open_write",
    "os.remove": "engine.fs.remove",
    "os.unlink": "engine.fs.remove",
    "os.rmdir": "engine.fs.remove",
    "shutil.rmtree": "engine.fs.remove",
}

_CONF_KEY_RE = re.compile(r"fugue(\.[a-z0-9_]+)+")
_METRIC_METHODS = {"counter", "gauge", "histogram"}

#: paths where device placement must stay mesh-scoped (FLN108)
_DEVICE_PLACEMENT_PATHS = ("fugue_tpu/jax_backend/",)
_EAGER_ARRAY_CTORS = {
    "array", "asarray", "zeros", "ones", "full", "empty", "eye",
    "arange", "linspace",
}
_JNP_PREFIXES = ("jnp.", "jax.numpy.")


@register_source_rule
class RawIoOnEnginePathRule(SourceRule):
    code = "FLN105"
    description = (
        "raw open()/os.remove on an engine/serve path that must route "
        "through engine.fs"
    )

    def check(self, ctx: Any) -> Iterable[SourceDiagnostic]:
        for mod in ctx.modules:
            if not mod.rel.startswith(ENGINE_FS_PATHS):
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                replacement = _RAW_IO_CALLS.get(name or "")
                if replacement is None:
                    continue
                yield self.diag(
                    f"raw '{name}(...)' on an engine/serve path: route "
                    f"through {replacement} so fault injection "
                    "(fs.open/fs.write sites), URI schemes and atomic "
                    "writes apply",
                    path=mod.rel,
                    line=node.lineno,
                    qualname=mod.qualname(node),
                )


@register_source_rule
class UndeclaredConfKeyLiteralRule(SourceRule):
    code = "FLN106"
    description = (
        "string-literal fugue.* conf key absent from the constants.py "
        "registry (source-side complement of runtime FWF201)"
    )

    def check(self, ctx: Any) -> Iterable[SourceDiagnostic]:
        from fugue_tpu.constants import declared_conf_keys

        declared = declared_conf_keys()
        for mod in ctx.modules:
            for node in ast.walk(mod.tree):
                if (
                    not isinstance(node, ast.Constant)
                    or not isinstance(node.value, str)
                    or id(node) in mod.docstrings
                ):
                    continue
                value = node.value
                if not _CONF_KEY_RE.fullmatch(value):
                    continue
                if value in declared:
                    continue
                yield self.diag(
                    f"conf-key literal '{value}' is not declared in the "
                    "constants.py registry: undeclared fugue.* keys are "
                    "silently ignored by every engine getter and "
                    "invisible to the conf linter — register_conf_key it "
                    "(or rename to the declared key)",
                    path=mod.rel,
                    line=node.lineno,
                    qualname=mod.qualname(node),
                )


@register_source_rule
class VocabularyRule(SourceRule):
    code = "FLN107"
    description = (
        "fault_point site missing from KNOWN_SITES, or metric name "
        "outside the registered METRIC_NAME_PREFIXES"
    )

    def check(self, ctx: Any) -> Iterable[SourceDiagnostic]:
        from fugue_tpu.obs.metrics import METRIC_NAME_PREFIXES
        from fugue_tpu.testing.faults import KNOWN_SITES

        for mod in ctx.modules:
            defines_vocab = mod.rel.endswith("testing/faults.py")
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name is None:
                    continue
                if (
                    not defines_vocab
                    and (name == "fault_point" or name.endswith(".fault_point"))
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    site = node.args[0].value
                    if site not in KNOWN_SITES:
                        yield self.diag(
                            f"fault site '{site}' is not in testing/"
                            "faults.py KNOWN_SITES: a plan spec naming "
                            "it would silently never fire — add it to "
                            "the vocabulary",
                            path=mod.rel,
                            line=node.lineno,
                            qualname=mod.qualname(node),
                        )
                    continue
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_METHODS
                    and len(node.args) >= 2
                    # our registry signature: (name_literal, help_literal)
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)
                ):
                    metric = node.args[0].value
                    if not metric.startswith(METRIC_NAME_PREFIXES):
                        yield self.diag(
                            f"metric name '{metric}' falls outside the "
                            "registered METRIC_NAME_PREFIXES (obs/"
                            "metrics.py): new subsystems extend the "
                            "prefix tuple in the same PR",
                            path=mod.rel,
                            line=node.lineno,
                            qualname=mod.qualname(node),
                        )


def _import_time_nodes(tree: ast.Module) -> Iterable[ast.AST]:
    """AST nodes whose code runs at IMPORT time: module and class bodies,
    plus decorator expressions and argument defaults of function
    definitions — but not function/lambda bodies."""
    stack: List[ast.AST] = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(node.decorator_list)
            stack.extend(d for d in node.args.defaults)
            stack.extend(d for d in node.args.kw_defaults if d is not None)
            continue
        if isinstance(node, ast.Lambda):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@register_source_rule
class EagerDevicePlacementRule(SourceRule):
    code = "FLN108"
    description = (
        "eager default-device placement on an engine path: single-arg "
        "jax.device_put, or module-level jnp array construction"
    )

    def check(self, ctx: Any) -> Iterable[SourceDiagnostic]:
        for mod in ctx.modules:
            if not mod.rel.startswith(_DEVICE_PLACEMENT_PATHS):
                continue
            import_time = {id(n) for n in _import_time_nodes(mod.tree)}
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name is None:
                    continue
                if name in ("jax.device_put", "device_put"):
                    placed = len(node.args) >= 2 or any(
                        kw.arg == "device" for kw in node.keywords
                    )
                    if not placed:
                        yield self.diag(
                            "single-argument jax.device_put on an engine "
                            "path commits data to the process default "
                            "device — the WRONG device once engines "
                            "carve the pod into per-replica slices "
                            "(fugue.jax.devices): pass the owning "
                            "mesh's sharding (device_put(x, sharding))",
                            path=mod.rel,
                            line=node.lineno,
                            qualname=mod.qualname(node),
                        )
                    continue
                if (
                    id(node) in import_time
                    and name.startswith(_JNP_PREFIXES)
                    and name.rsplit(".", 1)[-1] in _EAGER_ARRAY_CTORS
                ):
                    yield self.diag(
                        f"module-level '{name}(...)' allocates on the "
                        "default device at import time, before any mesh "
                        "or device slice exists: build device arrays "
                        "inside jitted/mesh-scoped code (host-side "
                        "np.* constants are fine)",
                        path=mod.rel,
                        line=node.lineno,
                        qualname=mod.qualname(node),
                    )
