"""Lock-discipline rules.

FLN101 builds the statically-observable lock-acquisition graph: an edge
``A -> B`` whenever a ``with A``/`A.acquire()`` region lexically
contains an acquisition of ``B``, or calls (same module) a function
whose acquisition closure reaches ``B``. It then rejects (a) any edge
that runs BACKWARDS through the canonical hierarchy declared in
:mod:`fugue_tpu.analysis.codelint.lockspec` and (b) any cycle among
observed edges — the static complement of the runtime sanitizer's
per-acquisition inversion check.

FLN104 rejects blocking calls (sleep, file IO, network, subprocess)
lexically inside a held registered lock: a slow syscall under an engine
lock stalls every thread behind it (the serving daemon's workers, the
memory governor's admission path).
"""

import ast
from typing import Any, Dict, Iterable, List, Tuple

from fugue_tpu.analysis.codelint.engine import call_name
from fugue_tpu.analysis.codelint.lockspec import (
    BLOCKING_CALLS,
    LOCK_RANK,
)
from fugue_tpu.analysis.codelint.model import (
    SourceDiagnostic,
    SourceRule,
    register_source_rule,
)


def _inner_acquisitions(mod: Any, fs: Any, with_node: ast.With) -> List[Tuple[str, int, str]]:
    """Locks acquired inside ``with_node``'s body: (lock, line, via)."""
    out: List[Tuple[str, int, str]] = []
    for stmt in with_node.body:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    lock = mod.resolve_lock(item.context_expr, sub)
                    if lock is not None:
                        out.append((lock, sub.lineno, fs.qualname))
            elif isinstance(sub, ast.Call):
                name = call_name(sub)
                if name is None:
                    continue
                if name.endswith(".acquire"):
                    lock = mod.resolve_lock(sub.func.value, sub)
                    if lock is not None:
                        out.append((lock, sub.lineno, fs.qualname))
                    continue
                callee = None
                if name.startswith("self.") and name.count(".") == 1:
                    cls = fs.qualname.split(".", 1)[0]
                    callee = f"{cls}.{name.split('.', 1)[1]}"
                elif "." not in name:
                    callee = name
                target = mod.functions.get(callee) if callee else None
                if target is not None:
                    for lock, (_, via) in target.reachable.items():
                        out.append((lock, sub.lineno, via))
    return out


class _Edge:
    __slots__ = ("outer", "inner", "path", "line", "qualname", "via")

    def __init__(self, outer, inner, path, line, qualname, via):
        self.outer = outer
        self.inner = inner
        self.path = path
        self.line = line
        self.qualname = qualname
        self.via = via


def collect_edges(ctx: Any) -> List[_Edge]:
    edges: List[_Edge] = []
    for mod, fs in ctx.functions():
        for sub in ast.walk(fs.node):
            if not isinstance(sub, ast.With):
                continue
            outers = [
                mod.resolve_lock(item.context_expr, sub) for item in sub.items
            ]
            # `with A, B:` acquires item-order left to right: each earlier
            # item is an outer of every later one
            resolved = [o for o in outers if o is not None]
            for i, outer in enumerate(resolved):
                for inner in resolved[i + 1:]:
                    if inner != outer:
                        edges.append(
                            _Edge(
                                outer, inner, mod.rel, sub.lineno,
                                fs.qualname, fs.qualname,
                            )
                        )
            for outer in resolved:
                for inner, line, via in _inner_acquisitions(mod, fs, sub):
                    if inner != outer:  # reentrant nesting is legal
                        edges.append(
                            _Edge(outer, inner, mod.rel, line, fs.qualname, via)
                        )
    return edges


@register_source_rule
class LockOrderRule(SourceRule):
    code = "FLN101"
    description = (
        "lock acquired against the canonical hierarchy, or a cycle in "
        "the statically-observed lock-acquisition graph"
    )

    def check(self, ctx: Any) -> Iterable[SourceDiagnostic]:
        edges = collect_edges(ctx)
        # (a) canonical-order inversions
        for e in edges:
            ro, ri = LOCK_RANK.get(e.outer), LOCK_RANK.get(e.inner)
            if ro is not None and ri is not None and ro > ri:
                hint = f" (reached via {e.via})" if e.via != e.qualname else ""
                yield self.diag(
                    f"'{e.inner}' acquired while holding '{e.outer}', "
                    "inverting the canonical lock order declared in "
                    f"analysis/codelint/lockspec.py{hint}",
                    path=e.path,
                    line=e.line,
                    qualname=e.qualname,
                )
        # (b) cycles among observed edges (listed in the hierarchy or not)
        adjacency: Dict[str, Dict[str, _Edge]] = {}
        for e in edges:
            adjacency.setdefault(e.outer, {}).setdefault(e.inner, e)
        reported = set()
        for start in sorted(adjacency):
            path: List[str] = []
            onpath = set()
            seen = set()

            def dfs(node: str) -> Iterable[SourceDiagnostic]:
                path.append(node)
                onpath.add(node)
                seen.add(node)
                for nxt, e in sorted(adjacency.get(node, {}).items()):
                    if nxt in onpath:
                        cycle = tuple(path[path.index(nxt):] + [nxt])
                        key = frozenset(cycle)
                        if key not in reported:
                            reported.add(key)
                            yield self.diag(
                                "lock-acquisition cycle: "
                                + " -> ".join(cycle)
                                + " — two threads entering it from "
                                "different locks can deadlock",
                                path=e.path,
                                line=e.line,
                                qualname=e.qualname,
                            )
                    elif nxt not in seen:
                        yield from dfs(nxt)
                path.pop()
                onpath.discard(node)

            yield from dfs(start)


@register_source_rule
class BlockingUnderLockRule(SourceRule):
    code = "FLN104"
    description = (
        "blocking IO/sleep/network call while holding a registered lock"
    )

    def check(self, ctx: Any) -> Iterable[SourceDiagnostic]:
        for mod, fs in ctx.functions():
            for sub in ast.walk(fs.node):
                if not isinstance(sub, ast.With):
                    continue
                held = [
                    lock
                    for item in sub.items
                    if (lock := mod.resolve_lock(item.context_expr, sub))
                ]
                if not held:
                    continue
                for stmt in sub.body:
                    for call in ast.walk(stmt):
                        if not isinstance(call, ast.Call):
                            continue
                        name = call_name(call)
                        if name is None:
                            continue
                        for pat in BLOCKING_CALLS:
                            hit = (
                                name.startswith(pat)
                                if pat.endswith(".")
                                else name == pat
                            )
                            if hit:
                                yield self.diag(
                                    f"blocking call '{name}' while "
                                    f"holding '{held[0]}' — every thread "
                                    "queued on that lock stalls behind "
                                    "this IO/sleep",
                                    path=mod.rel,
                                    line=call.lineno,
                                    qualname=fs.qualname,
                                )
                                break
