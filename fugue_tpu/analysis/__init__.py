"""Pre-execution static analysis of workflow DAGs.

A pluggable linter over the built-but-unexecuted :class:`FugueWorkflow`
task graph: stable-coded rules (``FWF###``) check schemas, partition
specs, conf keys and predicted jax-engine behavior in milliseconds,
before a single byte hits a device. Wired into ``FugueWorkflow.run()``
behind the ``fugue.analysis`` conf (``off`` / ``warn`` / ``error``,
default ``warn``), exposed directly as ``workflow.analyze()``, and
runnable standalone over FugueSQL files or workflow modules via
``python -m fugue_tpu.analysis``.
"""

from fugue_tpu.analysis.diagnostics import (
    GENERIC,
    JAX,
    Diagnostic,
    Rule,
    Severity,
    all_rules,
    register_rule,
)
from fugue_tpu.analysis.schema_pass import SchemaInfo, propagate
from fugue_tpu.analysis.analyzer import (
    AnalysisContext,
    Analyzer,
    analyze_workflow,
    max_severity,
)

__all__ = [
    "AnalysisContext",
    "Analyzer",
    "Diagnostic",
    "GENERIC",
    "JAX",
    "Rule",
    "SchemaInfo",
    "Severity",
    "all_rules",
    "analyze_workflow",
    "max_severity",
    "propagate",
    "register_rule",
]
