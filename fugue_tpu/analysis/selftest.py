"""Representative-workflow self-test for the analyzer.

Builds (never runs) a set of DAGs exercising the patterns the
``fugue_tpu_test`` acceptance suites use — create/transform/select/
aggregate/join/zip-cotransform/checkpoint/save — and analyzes each at
full scope. A clean framework must produce ZERO error-level diagnostics
over them: any error here is an analyzer false positive (or a genuinely
broken exemplar), which is exactly what a pre-merge gate should catch.
Used by ``python -m fugue_tpu.analysis --self-test`` and the test suite.
"""

from typing import Any, Callable, Dict, List, Tuple

import pandas as pd

from fugue_tpu.analysis.analyzer import Analyzer
from fugue_tpu.analysis.diagnostics import Diagnostic, Severity


# schema: *,s:double
def _add_s(df: pd.DataFrame) -> pd.DataFrame:
    return df.assign(s=df["b"] * 2.0)


def _wf_transform() -> Any:
    from fugue_tpu.workflow.workflow import FugueWorkflow

    dag = FugueWorkflow()
    df = dag.df([[0, 1.0], [1, 2.0]], "a:int,b:double")
    df.partition_by("a").transform(_add_s).select("a", "s")
    return dag


def _wf_relational() -> Any:
    from fugue_tpu.column import functions as f
    from fugue_tpu.column.expressions import col
    from fugue_tpu.workflow.workflow import FugueWorkflow

    dag = FugueWorkflow()
    left = dag.df([[0, "x"], [1, "y"]], "a:int,c:str")
    right = dag.df([[0, 10], [2, 20]], "a:int,d:int")
    joined = left.inner_join(right, on=["a"])
    joined.filter(col("d") > 5).partition_by("a").aggregate(
        total=f.sum(col("d"))
    )
    left.rename({"c": "name"}).drop(["name"])
    left.union(left, distinct=True).distinct()
    return dag


def _wf_sql_and_schema_ops() -> Any:
    from fugue_tpu.workflow.workflow import FugueWorkflow

    dag = FugueWorkflow()
    df = dag.df([[1, "a", 2.5]], "x:int,y:str,z:double")
    dag.select("SELECT x, z FROM", df)
    df.alter_columns("x:long").assign(w=1)[["x", "w"]]
    df.dropna(subset=["z"]).fillna(0.0, subset=["z"]).sample(frac=0.5)
    df.take(1, presort="z desc")
    return dag


def _wf_checkpoint_yield() -> Any:
    from fugue_tpu.workflow.workflow import FugueWorkflow

    dag = FugueWorkflow()
    df = dag.df([[0]], "a:int")
    df.persist().yield_dataframe_as("res")
    return dag


# schema: a:int,n:long
def _count_group(df: pd.DataFrame) -> pd.DataFrame:
    return pd.DataFrame({"a": [int(df["a"].iloc[0])], "n": [len(df)]})


def _wf_deep_chain(n: int = 50) -> Any:
    """A 50-task DAG for the timing bound in the acceptance criteria."""
    from fugue_tpu.column.expressions import col
    from fugue_tpu.workflow.workflow import FugueWorkflow

    dag = FugueWorkflow()
    df = dag.df([[i, float(i)] for i in range(8)], "a:int,b:double")
    for i in range(n - 1):
        if i % 5 == 4:
            df = df.partition_by("a").transform(_count_group).rename({"n": "b"})
            df = df.alter_columns("b:double")
        elif i % 2 == 0:
            df = df.filter(col("a") >= 0)
        else:
            df = df.assign(b=col("b") + 1.0)
    return dag


def _wf_join_filter_narrow() -> Any:
    """Join + filter + narrow select (ISSUE 10): the optimizer's bread
    and butter — filter pushdown below the rename, chain fusion, and a
    projection requirement that narrows both join sides."""
    from fugue_tpu.column.expressions import col
    from fugue_tpu.workflow.workflow import FugueWorkflow

    dag = FugueWorkflow()
    left = dag.df(
        [[i, float(i), f"u{i}"] for i in range(8)], "k:int,v:double,name:str"
    )
    right = dag.df([[i, i * 10] for i in range(8)], "k:int,w:long")
    joined = left.inner_join(right, on=["k"])
    out = joined.rename({"w": "weight"}).filter(col("weight") > 20)
    out.select("k", "weight").yield_dataframe_as("res")
    return dag


def _wf_streaming() -> Any:
    """The standing-pipeline shape (ISSUE 15): the groupby aggregation a
    micro-batch driver re-runs incrementally, compiled with the
    ``fugue.stream.*`` conf a continuous deployment carries (source +
    resume + checkpoint path, so FWF506 and FWF403-style resume rules
    stay silent). The analyzer and EXPLAIN legs must both render it
    clean — the serve plane builds exactly this per registered view."""
    from fugue_tpu.column import functions as f
    from fugue_tpu.column.expressions import col
    from fugue_tpu.workflow.workflow import FugueWorkflow

    dag = FugueWorkflow(
        {
            "fugue.stream.source": "memory://selftest/stream_in",
            "fugue.stream.interval": 0.5,
            "fugue.stream.watermark.delay": 5.0,
            "fugue.workflow.resume": True,
            "fugue.workflow.checkpoint.path": "memory://selftest/ckpt",
        }
    )
    events = dag.df(
        [[0, 1.0, 3], [1, 2.0, 7], [0, 3.0, 12]], "k:int,v:double,ts:long"
    )
    events.partition_by("k").aggregate(
        s=f.sum(col("v")),
        c=f.count(col("v")),
        hi=f.max(col("v")),
    ).yield_dataframe_as("view")
    return dag


def _wf_lake() -> Any:
    """The versioned-table shape (ISSUE 17): a lake:// read with AS OF
    time travel feeding a filter the optimizer turns into pruning
    triples, plus a transactional append back into another lake table —
    compiled under the ``fugue.lake.*`` conf a serving deployment
    carries (the serve path anchors the keys, so FWF507 stays silent).
    Analyzer and EXPLAIN legs must both render it clean. The builder
    also seeds the memory-fs table up to version 3 (idempotent), so the
    workflow is RUNNABLE — the optimizer parity gate executes every
    corpus entry, and the AS OF pin stays stable across the appends
    each run commits on top."""
    import pyarrow as pa

    from fugue_tpu.column.expressions import col
    from fugue_tpu.lake import LakeTable
    from fugue_tpu.workflow.workflow import FugueWorkflow

    seed = LakeTable("memory://selftest/lake/events")
    while seed.current_version() < 3:
        i = seed.current_version()
        seed.append(
            pa.table(
                {
                    "k": pa.array([i, i + 1], pa.int32()),
                    "v": pa.array([float(i), i + 1.5], pa.float64()),
                }
            )
        )

    dag = FugueWorkflow(
        {
            "fugue.lake.commit.retries": 8,
            "fugue.lake.commit.backoff": 0.02,
            "fugue.lake.serve.path": "memory://selftest/lake",
        }
    )
    events = dag.load("lake://memory://selftest/lake/events", version=3)
    events.filter(col("v") > 1.0).yield_dataframe_as("asof_view")
    fresh = dag.df([[0, 1.0], [1, 2.0]], "k:int,v:double")
    fresh.save("lake://memory://selftest/lake/events", mode="append")
    return dag


WORKFLOW_BUILDERS: Dict[str, Callable[[], Any]] = {
    "transform": _wf_transform,
    "relational": _wf_relational,
    "sql_and_schema_ops": _wf_sql_and_schema_ops,
    "checkpoint_yield": _wf_checkpoint_yield,
    "deep_chain_50": _wf_deep_chain,
    "join_filter_narrow": _wf_join_filter_narrow,
    "streaming_pipeline": _wf_streaming,
    "lake_versioned": _wf_lake,
}


def run_self_test() -> List[Tuple[str, List[Diagnostic]]]:
    """Analyze every representative workflow at full scope; returns
    (name, diagnostics) pairs. Error-level diagnostics mean the self-test
    FAILS (the CLI exits nonzero)."""
    out: List[Tuple[str, List[Diagnostic]]] = []
    analyzer = Analyzer()
    for name, build in WORKFLOW_BUILDERS.items():
        dag = build()
        out.append((name, analyzer.analyze(dag, conf=dag._conf)))
    return out


def self_test_failed(results: List[Tuple[str, List[Diagnostic]]]) -> bool:
    return any(
        d.severity is Severity.ERROR for _, diags in results for d in diags
    )


class _OptimizedView:
    """Adapter handing an optimized task list to the Analyzer (which
    reads ``.tasks``) without building a workflow around it."""

    def __init__(self, tasks: Any):
        self.tasks = tasks


def run_optimize_check() -> List[Tuple[str, int, List[Diagnostic]]]:
    """``--optimize`` gate: rewrite every corpus workflow with the full
    rule set forced ON, then re-analyze the OPTIMIZED plan at full
    scope. Returns (name, applied_rewrites, diagnostics) triples; any
    error-level diagnostic means a rewrite broke schema propagation (or
    another invariant a clean plan must satisfy) — the CLI exits
    nonzero."""
    from fugue_tpu.constants import FUGUE_CONF_OPTIMIZE
    from fugue_tpu.optimize import optimize_tasks

    out: List[Tuple[str, int, List[Diagnostic]]] = []
    analyzer = Analyzer()
    for name, build in WORKFLOW_BUILDERS.items():
        dag = build()
        conf = dict(dag._conf)
        conf[FUGUE_CONF_OPTIMIZE] = "on"
        plan = optimize_tasks(dag.tasks, conf=conf)
        # exclude_lint_only: FWF501 would dry-run the optimizer AGAIN
        # over the already-optimized plan (second-order rewrite noise)
        diags = analyzer.analyze(
            _OptimizedView(plan.tasks), conf=conf, exclude_lint_only=True
        )
        out.append((name, len(plan.applied), diags))
    return out


def optimize_check_failed(
    results: List[Tuple[str, int, List[Diagnostic]]]
) -> bool:
    return any(
        d.severity is Severity.ERROR
        for _, _, diags in results
        for d in diags
    )


# a representative FugueSQL multi-statement script for the explain_sql
# leg of the gate — same shapes the serve plane compiles per request
_EXPLAIN_SQL = """
a = CREATE [[0, 1.0], [1, 2.0], [0, 3.0]] SCHEMA k:int,v:double
b = CREATE [[0, 'x'], [1, 'y']] SCHEMA k:int,name:str
SELECT a.k, name, v FROM a INNER JOIN b ON a.k = b.k WHERE v > 1.0
YIELD DATAFRAME AS res
"""


def run_explain_check() -> List[Tuple[str, str]]:
    """EXPLAIN gate: render every corpus workflow's plan report (text +
    JSON) plus an ``explain_sql`` pass over a representative FugueSQL
    script. Any exception propagates — a crashing EXPLAIN is a broken
    pre-merge gate, exactly like a crashing rule corpus. Returns
    (name, rendered text) pairs for the CLI to summarize."""
    import json

    out: List[Tuple[str, str]] = []
    for name, build in WORKFLOW_BUILDERS.items():
        dag = build()
        report = dag.explain(conf=dag._conf)
        text = report.to_text()
        json.dumps(report.to_dict())  # JSON form must serialize clean
        assert text.startswith("EXPLAIN"), text[:60]
        out.append((name, text))
    from fugue_tpu.sql_frontend.workflow_sql import explain_sql

    report = explain_sql(_EXPLAIN_SQL)
    json.dumps(report.to_dict())
    out.append(("explain_sql", report.to_text()))
    return out


# ---------------------------------------------------------------------------
# admission leg (ISSUE 18): the predictive scheduler's admit/shed/defer
# decisions replayed against a canned stats fixture
# ---------------------------------------------------------------------------
class _CannedStats:
    """The stats-store surface the cost model reads, with fixed history."""

    def __init__(self, history: Dict[str, List[Dict[str, Any]]]):
        self._h = history

    def history(self, fp: str) -> List[Dict[str, Any]]:
        return list(self._h.get(fp, []))


def _canned_obs(total_ms: float, device_bytes: int) -> Dict[str, Any]:
    return {
        "workflow": "selftest",
        "total_ms": total_ms,
        "tasks": {"t1": {"device_bytes": device_bytes}},
    }


# one long ETL query with real history, one cheap dashboard query with
# real history, and an unknown ad-hoc shape that falls to the defaults
_ADMISSION_FIXTURE: Dict[str, List[Dict[str, Any]]] = {
    "fp-etl": [_canned_obs(6000.0, 700)],
    "fp-dash": [_canned_obs(100.0, 100)],
}

# (label, fingerprint, priority) — replayed in order against ONE slot,
# a 1000-byte ledger at 0.8 memory fraction, and a 2s wait budget
_ADMISSION_SEQUENCE: List[Tuple[str, str, int]] = [
    ("etl-backfill", "fp-etl", 0),
    ("dashboard", "fp-dash", 0),
    ("dashboard-priority", "fp-dash", 5),
    ("adhoc", "fp-unknown", 0),
    ("adhoc-priority", "fp-unknown", 9),
]

# the pinned contract: admit from observed history, shed below the
# overload priority floor, priority punches through the shed gate, the
# default estimate sheds too, and a too-big default DEFERS on memory
# even at high priority — any drift in the cost model or the admission
# arithmetic moves one of these strings
_ADMISSION_EXPECTED: List[Tuple[str, str]] = [
    ("etl-backfill", "admit wall_ms=6000 device_bytes=700"),
    ("dashboard", "shed"),
    ("dashboard-priority", "admit wall_ms=100 device_bytes=100"),
    ("adhoc", "shed"),
    ("adhoc-priority", "defer"),
]


def _replay_admission() -> List[Tuple[str, str]]:
    from fugue_tpu.serve.admission import make_admission

    adm = make_admission(
        _CannedStats(_ADMISSION_FIXTURE),
        max_concurrent=1,
        memory_fraction=0.8,
        default_ms=250.0,
        default_bytes=600,
        budget_bytes_fn=lambda: 1000,
    )
    max_wait = 2.0
    running: List[str] = []
    decisions: List[Tuple[str, str]] = []
    for label, fp, priority in _ADMISSION_SEQUENCE:
        est = adm.model.estimate_fingerprint(fp)
        # the daemon's shed rule: predicted drain over the wait budget
        # sets the overload ratio, and the ratio IS the priority floor
        ratio = adm.predicted_drain_secs() / max_wait
        if ratio > 1.0 and priority < int(ratio):
            decisions.append((label, "shed"))
            continue
        if not adm.fits_memory(est, anything_running=bool(running)):
            decisions.append((label, "defer"))
            continue
        adm.job_queued(label, est)
        if not running:  # one slot: first admitted job runs, rest queue
            adm.job_started(label)
            running.append(label)
        decisions.append(
            (
                label,
                f"admit wall_ms={est.wall_ms:g} "
                f"device_bytes={est.device_bytes}",
            )
        )
    return decisions


def run_admission_check() -> List[Tuple[str, str]]:
    """``--self-test`` admission leg: replay the canned submission
    sequence through a real PredictiveAdmission TWICE — the two replays
    must agree exactly (determinism), and the decisions must match the
    pinned contract (no silent drift in cost estimation, the shed
    priority floor, or memory deferral). Returns the decision pairs for
    the CLI to count."""
    first = _replay_admission()
    second = _replay_admission()
    if first != second:
        raise AssertionError(
            "admission replay is not deterministic: "
            f"{first!r} != {second!r}"
        )
    return first


def admission_check_failed(results: List[Tuple[str, str]]) -> bool:
    return results != _ADMISSION_EXPECTED


# ---------------------------------------------------------------------------
# device-recovery leg (ISSUE 19): the fault executor's degrade-recover-
# retry decisions replayed against a scripted engine and injected
# device-loss errors
# ---------------------------------------------------------------------------
class _ReplayEngine:
    """The recovery surface ``execute_with_policy`` drives, with a
    scripted mesh: each successful recovery drops the named device from
    the survivor set; recovery refuses when disabled or when the loss
    would leave no survivors — exactly the real engine's contract."""

    def __init__(self, ndev: int):
        self.devices = list(range(ndev))
        self.enabled = True
        self.recoveries = 0

    def recover_from_device_loss(self, ex: Exception) -> bool:
        from fugue_tpu.jax_backend.distributed import parse_lost_devices

        if not self.enabled:
            return False
        lost = [d for d in parse_lost_devices(str(ex)) if d in self.devices]
        if not lost or len(lost) >= len(self.devices):
            return False
        self.devices = [d for d in self.devices if d not in lost]
        self.recoveries += 1
        return True


# (task, scripted per-attempt errors — None = the attempt succeeds).
# Builders, not instances: each replay must inject FRESH errors.
def _recovery_script() -> List[Tuple[str, List[Any]]]:
    from fugue_tpu.testing.faults import collective_hang, device_lost

    return [
        # a mid-shuffle device loss: recover 4 -> 3 and retry clean
        ("shuffle-groupby", [device_lost(2), None]),
        # a hung collective is TRANSIENT, not a loss: plain retry, the
        # mesh must NOT shrink
        ("join-allreduce", [collective_hang(1), None]),
        # a second loss on the already-degraded mesh: recover 3 -> 2
        ("agg-rescan", [device_lost(0), None]),
        # recovery disabled mid-sequence: the same error is now FATAL
        ("post-disable", [device_lost(1), None]),
    ]


# the pinned contract: classification, recovery, mesh shrinkage and
# retry accounting for the scripted sequence — any drift in the fault
# classifier's DEVICE_LOST triage, the executor's recover-then-retry
# branch, or the recovery bookkeeping moves one of these strings
_RECOVERY_EXPECTED: List[Tuple[str, str]] = [
    ("shuffle-groupby", "recovered survivors=[0,1,3] attempts=2"),
    ("join-allreduce", "retried survivors=[0,1,3] attempts=2"),
    ("agg-rescan", "recovered survivors=[1,3] attempts=2"),
    ("post-disable", "fatal XlaRuntimeError survivors=[1,3] attempts=1"),
]


def _replay_recovery() -> List[Tuple[str, str]]:
    from fugue_tpu.workflow.fault import RetryPolicy, execute_with_policy

    engine = _ReplayEngine(4)
    policy = RetryPolicy(max_attempts=3, backoff=0.0, jitter=0.0)
    decisions: List[Tuple[str, str]] = []
    for task, errors in _recovery_script():
        if task == "post-disable":
            engine.enabled = False
        attempts = [0]
        before = engine.recoveries

        def _attempt() -> str:
            err = errors[attempts[0]]
            attempts[0] += 1
            if err is not None:
                raise err
            return "ok"

        survivors = "[%s]" % ",".join(str(d) for d in engine.devices)
        try:
            execute_with_policy(
                _attempt, policy, engine=engine, task_name=task
            )
            survivors = "[%s]" % ",".join(str(d) for d in engine.devices)
            verb = "recovered" if engine.recoveries > before else "retried"
            decisions.append(
                (task, f"{verb} survivors={survivors} attempts={attempts[0]}")
            )
        except Exception as ex:
            survivors = "[%s]" % ",".join(str(d) for d in engine.devices)
            decisions.append(
                (
                    task,
                    f"fatal {type(ex).__name__} survivors={survivors} "
                    f"attempts={attempts[0]}",
                )
            )
    return decisions


def run_recovery_check() -> List[Tuple[str, str]]:
    """``--self-test`` device-recovery leg: replay the scripted
    degrade-recover-retry sequence through the REAL fault classifier and
    ``execute_with_policy`` TWICE — the replays must agree exactly
    (determinism), and the decisions must match the pinned contract.
    Returns the decision pairs for the CLI to count."""
    first = _replay_recovery()
    second = _replay_recovery()
    if first != second:
        raise AssertionError(
            "device-recovery replay is not deterministic: "
            f"{first!r} != {second!r}"
        )
    return first


def recovery_check_failed(results: List[Tuple[str, str]]) -> bool:
    return results != _RECOVERY_EXPECTED
