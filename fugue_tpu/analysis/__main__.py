"""``python -m fugue_tpu.analysis`` — lint a FugueSQL file or a workflow
module WITHOUT executing it, or (``--lint-source``) lint the fugue_tpu
SOURCE TREE itself with the FLN concurrency/invariant rules.

Targets:

- a FugueSQL script (``.fsql`` / ``.sql`` / any readable file): the DAG is
  compiled exactly as ``fugue_sql_flow`` would, then analyzed instead of
  run;
- a workflow module: ``pkg.mod`` or ``pkg.mod:attr`` where the attribute
  (or, unqualified, the first match in the module) is a FugueWorkflow
  instance or a zero-arg callable returning one;
- ``--lint-source [dir]``: run the ``FLN###`` source linter
  (:mod:`fugue_tpu.analysis.codelint`) over a package tree (default:
  the installed fugue_tpu package), applying the justification-required
  baseline (``--baseline``, default: the packaged baseline.json);
- ``--lint-jit [dir]``: run the ``FJX###`` jit-hazard linter
  (:mod:`fugue_tpu.analysis.jitlint`) over a package tree — static
  recompile/host-sync/dtype/donation/side-effect analysis of every jit
  boundary, with its own justification-required baseline;
- ``--self-test``: analyze the built-in representative workflow corpus
  AND source-lint AND jit-lint the installed tree — the one-command
  pre-merge gate covering all three planes (exits nonzero on any
  error-level diagnostic).

Exit codes: 0 clean (or only sub-error findings), 1 error-level
diagnostics, 2 the target could not be built.
"""

import argparse
import importlib
import os
import sys
from typing import Any, List, Optional

from fugue_tpu.analysis.analyzer import Analyzer
from fugue_tpu.analysis.diagnostics import Diagnostic, Severity


def _build_from_sql_file(path: str, conf: Any) -> Any:
    from fugue_tpu.sql_frontend.workflow_sql import FugueSQLWorkflow

    with open(path, "r") as fp:
        code = fp.read()
    dag = FugueSQLWorkflow(conf)
    dag._sql(code, {})
    return dag


def _build_from_module(spec: str) -> Any:
    from fugue_tpu.workflow.workflow import FugueWorkflow

    mod_name, _, attr = spec.partition(":")
    mod = importlib.import_module(mod_name)
    candidates = (
        [getattr(mod, attr)]
        if attr
        else [getattr(mod, n) for n in dir(mod) if not n.startswith("_")]
    )
    for obj in candidates:
        if isinstance(obj, FugueWorkflow):
            return obj
        if attr and callable(obj):
            wf = obj()
            if isinstance(wf, FugueWorkflow):
                return wf
            raise TypeError(f"{spec} returned {type(wf).__name__}, not a FugueWorkflow")
    if not attr:
        # second sweep: zero-arg builder functions by convention
        for name in ("build_workflow", "get_workflow", "workflow"):
            obj = getattr(mod, name, None)
            if callable(obj):
                wf = obj()
                if isinstance(wf, FugueWorkflow):
                    return wf
    raise LookupError(f"no FugueWorkflow found in {spec!r}")


def _parse_conf(pairs: List[str]) -> dict:
    conf = {}
    for p in pairs:
        k, eq, v = p.partition("=")
        if eq == "":
            raise ValueError(f"--conf expects key=value, got {p!r}")
        conf[k.strip()] = v.strip()
    return conf


def _strip_bootstrap_frames(callsite: List[str]) -> List[str]:
    """Drop interpreter-bootstrap frames (runpy; ``<frozen runpy>`` on
    py3.11+) from a callsite, each with its trailing source line(s), and
    keep any genuine user frames — a module target's build function IS a
    meaningful callsite even though runpy frames lead the stack."""
    kept: List[str] = []
    skipping = False
    for line in callsite:
        if line.lstrip().startswith("File "):
            skipping = "/runpy.py" in line or "<frozen runpy>" in line
        if not skipping:
            kept.append(line)
    return kept


def _print_diags(title: str, diags: List[Diagnostic], out: Any) -> None:
    if title:
        print(f"== {title}", file=out)
    if not diags:
        print("  clean: no diagnostics", file=out)
        return
    for d in diags:
        frames = _strip_bootstrap_frames(d.callsite or [])
        print(d.describe(with_callsite=False), file=out)
        if frames:
            print("  defined at:", file=out)
            for line in frames:
                print("  " + line, file=out)


def _run_source_lint(
    root: Optional[str], baseline_path: Optional[str], floor: Severity, out: Any
) -> int:
    """Source-lint a tree with the baseline applied; prints findings and
    returns the number of error-level diagnostics."""
    from fugue_tpu.analysis.codelint import (
        apply_baseline,
        lint_tree,
        load_baseline,
        stale_diags,
    )

    entries, problems = load_baseline(baseline_path)
    diags = lint_tree(root)
    kept, suppressed, stale = apply_baseline(diags, entries)
    final = problems + kept + stale_diags(stale, baseline_path)
    for d in final:
        if d.severity >= floor:
            print(d.describe(), file=out)
    errors = sum(1 for d in final if d.severity is Severity.ERROR)
    print(
        f"source lint: {errors} error(s), "
        f"{sum(1 for d in final if d.severity is Severity.WARN)} warning(s), "
        f"{len(suppressed)} baselined exception(s)",
        file=out,
    )
    return errors


def _run_jit_lint(
    root: Optional[str], baseline_path: Optional[str], floor: Severity, out: Any
) -> int:
    """Jit-lint a tree with the FJX baseline applied; prints findings and
    returns the number of error-level diagnostics."""
    from fugue_tpu.analysis.jitlint import lint_tree_jit
    from fugue_tpu.analysis.jitlint.baseline import (
        apply_baseline,
        load_jit_baseline,
        stale_jit_diags,
    )

    entries, problems = load_jit_baseline(baseline_path)
    diags = lint_tree_jit(root)
    kept, suppressed, stale = apply_baseline(diags, entries)
    final = problems + kept + stale_jit_diags(stale, baseline_path)
    for d in final:
        if d.severity >= floor:
            print(d.describe(), file=out)
    errors = sum(1 for d in final if d.severity is Severity.ERROR)
    print(
        f"jit lint: {errors} error(s), "
        f"{sum(1 for d in final if d.severity is Severity.WARN)} warning(s), "
        f"{len(suppressed)} baselined exception(s)",
        file=out,
    )
    return errors


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m fugue_tpu.analysis",
        description="statically lint a FugueSQL file or workflow module "
        "without executing it",
    )
    p.add_argument(
        "target",
        nargs="?",
        help="FugueSQL file path, or module[:attr] providing a FugueWorkflow",
    )
    p.add_argument(
        "--self-test",
        action="store_true",
        help="analyze the built-in representative workflows; exit nonzero "
        "on any error-level diagnostic (pre-merge gate)",
    )
    p.add_argument(
        "--optimize",
        action="store_true",
        help="with --self-test: additionally rewrite every corpus "
        "workflow with the DAG optimizer's full rule set and assert the "
        "optimized plans still pass schema propagation (exit nonzero on "
        "any rewrite that breaks it)",
    )
    p.add_argument(
        "--lint-source",
        action="store_true",
        help="run the FLN source linter over a package tree (optional "
        "target: directory; default: the installed fugue_tpu package)",
    )
    p.add_argument(
        "--lint-jit",
        action="store_true",
        help="run the FJX jit-hazard linter over a package tree (optional "
        "target: directory; default: the installed fugue_tpu package)",
    )
    p.add_argument(
        "--baseline",
        default=None,
        metavar="PATH",
        help="with --lint-source/--lint-jit: the justification-required "
        "baseline file (default: the packaged baseline.json of that plane)",
    )
    p.add_argument(
        "--conf",
        action="append",
        default=[],
        metavar="KEY=VALUE",
        help="conf overrides for the analysis (repeatable)",
    )
    p.add_argument(
        "--min-severity",
        default="info",
        choices=["info", "warn", "error"],
        help="hide diagnostics below this severity (default: info)",
    )
    args = p.parse_args(argv)
    floor = Severity.parse(args.min_severity)
    try:
        conf = _parse_conf(args.conf)
    except ValueError as ex:
        print(str(ex), file=sys.stderr)
        return 2

    if args.lint_source or args.lint_jit:
        flag = "--lint-source" if args.lint_source else "--lint-jit"
        if args.lint_source and args.lint_jit:
            print("--lint-source and --lint-jit are exclusive (run them "
                  "separately, or --self-test for every plane)",
                  file=sys.stderr)
            return 2
        if args.self_test:
            print(f"{flag} and --self-test are exclusive "
                  f"(--self-test already includes the lint planes)",
                  file=sys.stderr)
            return 2
        root = args.target
        if root is not None and not os.path.isdir(root):
            print(f"{flag} target {root!r} is not a directory",
                  file=sys.stderr)
            return 2
        if args.lint_source:
            errors = _run_source_lint(root, args.baseline, floor, sys.stdout)
        else:
            errors = _run_jit_lint(root, args.baseline, floor, sys.stdout)
        return 1 if errors else 0

    if args.self_test:
        from fugue_tpu.analysis.selftest import run_self_test, self_test_failed

        results = run_self_test()
        for name, diags in results:
            _print_diags(name, [d for d in diags if d.severity >= floor], sys.stdout)
        failed = self_test_failed(results)
        print(
            f"self-test {'FAILED' if failed else 'passed'}: "
            f"{len(results)} workflows analyzed",
            file=sys.stdout,
        )
        if args.optimize:
            from fugue_tpu.analysis.selftest import (
                optimize_check_failed,
                run_optimize_check,
            )

            opt_results = run_optimize_check()
            for name, applied, diags in opt_results:
                _print_diags(
                    f"{name} [optimized: {applied} rewrites]",
                    [d for d in diags if d.severity >= floor],
                    sys.stdout,
                )
            opt_failed = optimize_check_failed(opt_results)
            total_applied = sum(a for _, a, _ in opt_results)
            print(
                f"optimize-check {'FAILED' if opt_failed else 'passed'}: "
                f"{len(opt_results)} workflows rewritten "
                f"({total_applied} rewrites applied)",
                file=sys.stdout,
            )
            failed = failed or opt_failed
        # EXPLAIN leg (ISSUE 14): every corpus workflow plus a
        # representative FugueSQL script must render a clean plan
        # report (text + JSON) — a crashing EXPLAIN is a failed gate
        try:
            from fugue_tpu.analysis.selftest import run_explain_check

            explained = run_explain_check()
            print(
                f"explain-check passed: {len(explained)} plans rendered",
                file=sys.stdout,
            )
        except Exception as ex:
            print(
                f"explain-check FAILED: {type(ex).__name__}: {ex}",
                file=sys.stdout,
            )
            failed = True
        # admission leg (ISSUE 18): the predictive scheduler's
        # admit/shed/defer decisions replayed against a canned stats
        # fixture — two replays must agree and match the pinned contract
        try:
            from fugue_tpu.analysis.selftest import (
                _ADMISSION_EXPECTED,
                admission_check_failed,
                run_admission_check,
            )

            decisions = run_admission_check()
            adm_failed = admission_check_failed(decisions)
            if adm_failed:
                for got, want in zip(decisions, _ADMISSION_EXPECTED):
                    if got != want:
                        print(f"  {got!r} != expected {want!r}",
                              file=sys.stdout)
            print(
                f"admission-check {'FAILED' if adm_failed else 'passed'}: "
                f"{len(decisions)} decisions replayed",
                file=sys.stdout,
            )
            failed = failed or adm_failed
        except Exception as ex:
            print(
                f"admission-check FAILED: {type(ex).__name__}: {ex}",
                file=sys.stdout,
            )
            failed = True
        # device-recovery leg (ISSUE 19): the fault executor's
        # degrade-recover-retry decisions replayed against a scripted
        # engine — two replays must agree and match the pinned contract
        try:
            from fugue_tpu.analysis.selftest import (
                _RECOVERY_EXPECTED,
                recovery_check_failed,
                run_recovery_check,
            )

            rec = run_recovery_check()
            rec_failed = recovery_check_failed(rec)
            if rec_failed:
                for got, want in zip(rec, _RECOVERY_EXPECTED):
                    if got != want:
                        print(f"  {got!r} != expected {want!r}",
                              file=sys.stdout)
            print(
                f"recovery-check {'FAILED' if rec_failed else 'passed'}: "
                f"{len(rec)} decisions replayed",
                file=sys.stdout,
            )
            failed = failed or rec_failed
        except Exception as ex:
            print(
                f"recovery-check FAILED: {type(ex).__name__}: {ex}",
                file=sys.stdout,
            )
            failed = True
        # every plane, one command: the workflow-corpus gate above plus
        # the FLN source lint and the FJX jit-hazard lint of the
        # installed tree (each against its own packaged baseline)
        src_errors = _run_source_lint(None, args.baseline, floor, sys.stdout)
        failed = failed or src_errors > 0
        jit_errors = _run_jit_lint(None, None, floor, sys.stdout)
        failed = failed or jit_errors > 0
        return 1 if failed else 0
    if args.optimize:
        print("--optimize requires --self-test", file=sys.stderr)
        return 2

    if not args.target:
        p.print_usage(sys.stderr)
        print("error: a target or --self-test is required", file=sys.stderr)
        return 2
    try:
        if os.path.isfile(args.target):
            dag = _build_from_sql_file(args.target, conf)
        else:
            dag = _build_from_module(args.target)
    except Exception as ex:
        print(
            f"can't build a workflow from {args.target!r}: "
            f"{type(ex).__name__}: {ex}",
            file=sys.stderr,
        )
        return 2
    merged = dict(dag._conf)
    merged.update(conf)
    diags = Analyzer().analyze(dag, conf=merged)
    _print_diags(args.target, [d for d in diags if d.severity >= floor], sys.stdout)
    return 1 if any(d.severity is Severity.ERROR for d in diags) else 0


if __name__ == "__main__":
    sys.exit(main())
