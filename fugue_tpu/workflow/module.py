"""Reusable sub-DAG functions (reference fugue/workflow/module.py:19): a
``@module`` function takes/returns WorkflowDataFrames and can be applied in
any workflow."""

import inspect
from typing import Any, Callable, Optional

from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.workflow.workflow import FugueWorkflow, WorkflowDataFrame


def module(
    func: Optional[Callable] = None, as_method: bool = False,
    name: Optional[str] = None, on_dup: str = "overwrite",
) -> Any:
    """Mark a function as a workflow module. With ``as_method=True`` it is
    also injected as a WorkflowDataFrame method."""

    def deco(fn: Callable) -> Callable:
        sig = inspect.signature(fn)

        def wrapper(*args: Any, **kwargs: Any) -> Any:
            return fn(*args, **kwargs)

        wrapper.__name__ = fn.__name__
        wrapper.__doc__ = fn.__doc__
        if as_method:
            method_name = name or fn.__name__
            params = list(sig.parameters.values())
            assert_or_throw(
                len(params) > 0,
                ValueError("as_method module needs a WorkflowDataFrame param"),
            )

            def method(self: WorkflowDataFrame, *args: Any, **kwargs: Any) -> Any:
                return fn(self, *args, **kwargs)

            if hasattr(WorkflowDataFrame, method_name) and on_dup == "throw":
                raise KeyError(f"{method_name} already exists")
            setattr(WorkflowDataFrame, method_name, method)
        return wrapper

    if func is not None:
        return deco(func)
    return deco
