"""Checkpoints: caching and cross-run recovery (reference
fugue/workflow/_checkpoint.py:37-175).

- WeakCheckpoint  = engine persist (in-memory cache)
- StrongCheckpoint = save+reload a parquet file; ``deterministic=True`` keys
  the file by the task uuid so re-running an identical DAG SKIPS recompute
  when the artifact already exists.

All paths resolve through the engine's virtual filesystem, so
``fugue.workflow.checkpoint.path`` may be a URI (``memory://...``,
``gs://...``) and checkpoint artifacts live wherever the cluster's
data does.
"""

from typing import Any, Optional
from uuid import uuid4

from fugue_tpu.collections.yielded import PhysicalYielded
from fugue_tpu.dataframe import DataFrame
from fugue_tpu.utils.assertion import assert_or_throw


class Checkpoint:
    """Null checkpoint."""

    @property
    def is_null(self) -> bool:
        return True

    @property
    def deterministic(self) -> bool:
        """True when the checkpoint's artifact is keyed by the task uuid
        (re-running an identical DAG reuses it) — the eligibility bit
        the optimizer's result cache and observability checks read."""
        return False

    def run(self, df: DataFrame, path: "CheckpointPath") -> DataFrame:
        return df

    def try_load(self, path: "CheckpointPath") -> Optional[DataFrame]:
        """Pre-execution check: a deterministic checkpoint whose artifact
        already exists returns the cached dataframe so the task can SKIP
        recompute entirely (reference _checkpoint.py:67)."""
        return None

    def artifact_uri(self, path: "CheckpointPath") -> Optional[str]:
        """The PERMANENT artifact URI this checkpoint writes, or None when
        it leaves nothing durable behind (null/weak/temp checkpoints).
        The run manifest records it so a killed run can resume by loading
        the artifact instead of recomputing."""
        return None

    @property
    def fmt(self) -> str:
        return "parquet"


class WeakCheckpoint(Checkpoint):
    def __init__(self, lazy: bool = False, **kwargs: Any):
        self._lazy = lazy
        self._kwargs = dict(kwargs)

    @property
    def is_null(self) -> bool:
        return False

    def run(self, df: DataFrame, path: "CheckpointPath") -> DataFrame:
        return path.execution_engine.persist(df, lazy=self._lazy, **self._kwargs)


class StrongCheckpoint(Checkpoint):
    def __init__(
        self,
        obj_id: str,
        deterministic: bool = False,
        permanent: bool = False,
        lazy: bool = False,
        fmt: str = "parquet",
        partition: Any = None,
        single: bool = False,
        namespace: Any = None,
        **save_kwargs: Any,
    ):
        assert_or_throw(
            not deterministic or permanent,
            ValueError("deterministic checkpoint must be permanent"),
        )
        assert_or_throw(not lazy, NotImplementedError("lazy strong checkpoint"))
        self._obj_id = obj_id
        self._deterministic = deterministic
        self._permanent = permanent
        self._fmt = fmt
        self._partition = partition
        self._single = single
        self._namespace = namespace
        self._save_kwargs = dict(save_kwargs)
        self.yielded: Optional[PhysicalYielded] = None

    @property
    def is_null(self) -> bool:
        return False

    @property
    def deterministic(self) -> bool:
        return self._deterministic

    def _file_path(self, path: "CheckpointPath") -> str:
        from fugue_tpu.utils.hash import to_uuid

        fid = self._obj_id if self._namespace is None else to_uuid(
            self._obj_id, self._namespace
        )
        return path.get_file_path(fid, self._fmt, permanent=self._permanent)

    def artifact_uri(self, path: "CheckpointPath") -> Optional[str]:
        if not (self._deterministic and self._permanent):
            return None
        return self._file_path(path)

    @property
    def fmt(self) -> str:
        return self._fmt

    def try_load(self, path: "CheckpointPath") -> Optional[DataFrame]:
        if not self._deterministic:
            return None
        fpath = self._file_path(path)
        if not path.file_exists(fpath):
            return None
        result = path.execution_engine.load_df(fpath, format_hint=self._fmt)
        if self.yielded is not None:
            self.yielded.set_value(fpath)
        return result

    def run(self, df: DataFrame, path: "CheckpointPath") -> DataFrame:
        fpath = self._file_path(path)
        if not (self._deterministic and path.file_exists(fpath)):
            path.execution_engine.save_df(
                df,
                fpath,
                format_hint=self._fmt,
                mode="overwrite",
                force_single=self._single,
                **self._save_kwargs,
            )
        result = path.execution_engine.load_df(fpath, format_hint=self._fmt)
        if self.yielded is not None:
            self.yielded.set_value(fpath)
        return result


# last catalog table name per checkpoint obj_id: lets a rebuilt workflow
# replace (not accumulate) its previous yield table
_LAST_TABLE_BY_OBJ: dict = {}


class TableCheckpoint(Checkpoint):
    """Save+reload through the SQL engine's table catalog (the reference's
    StrongCheckpoint storage_type='table'); backs ``yield_table_as``. No
    checkpoint path needed — tables live in the engine's catalog."""

    def __init__(
        self,
        obj_id: str,
        deterministic: bool = False,
        namespace: Any = None,
        **save_kwargs: Any,
    ):
        self._obj_id = obj_id
        self._deterministic = deterministic
        self._namespace = namespace
        self._save_kwargs = dict(save_kwargs)
        self.yielded: Optional[PhysicalYielded] = None

    @property
    def is_null(self) -> bool:
        return False

    @property
    def deterministic(self) -> bool:
        return self._deterministic

    def _table_name(self, path: "CheckpointPath") -> str:
        from fugue_tpu.utils.hash import to_uuid

        fid = self._obj_id if self._namespace is None else to_uuid(
            self._obj_id, self._namespace
        )
        return path.execution_engine.sql_engine.encode_name(
            "tbl_" + fid.replace("-", "")[:24]
        )

    def try_load(self, path: "CheckpointPath") -> Optional[DataFrame]:
        if not self._deterministic:
            return None
        sql = path.execution_engine.sql_engine
        name = self._table_name(path)
        if not sql.table_exists(name):
            return None
        result = sql.load_table(name)
        if self.yielded is not None:
            self.yielded.set_value(name)
        return result

    def run(self, df: DataFrame, path: "CheckpointPath") -> DataFrame:
        sql = path.execution_engine.sql_engine
        name = self._table_name(path)
        if not (self._deterministic and sql.table_exists(name)):
            # evict the previous build's table for the same logical yield:
            # random per-build namespaces must not accumulate copies in the
            # process-wide catalog (review r3)
            prev = _LAST_TABLE_BY_OBJ.get(self._obj_id)
            if prev is not None and prev != name:
                try:
                    sql.drop_table(prev)  # engine-polymorphic eviction
                except NotImplementedError:  # pragma: no cover
                    pass
            _LAST_TABLE_BY_OBJ[self._obj_id] = name
            sql.save_table(df, name, mode="overwrite", **self._save_kwargs)
        result = sql.load_table(name)
        if self.yielded is not None:
            self.yielded.set_value(name)
        return result


class CheckpointPath:
    """Temp/permanent checkpoint dirs per workflow execution (reference
    _checkpoint.py:130-175)."""

    def __init__(self, engine: Any):
        self._engine = engine
        self._path = engine.conf.get("fugue.workflow.checkpoint.path", "").strip()
        self._temp_path = ""

    @property
    def execution_engine(self) -> Any:
        return self._engine

    def init_temp_path(self, execution_id: str) -> str:
        if self._path == "":
            self._temp_path = ""
            return ""
        fs = self._engine.fs
        self._temp_path = fs.join(self._path, execution_id)
        fs.makedirs(self._temp_path, exist_ok=True)
        return self._temp_path

    def remove_temp_path(self) -> None:
        if self._temp_path != "":
            try:
                self._engine.fs.rm(self._temp_path, recursive=True)
            except Exception:  # pragma: no cover - best effort
                pass

    def get_file_path(self, obj_id: str, fmt: str, permanent: bool) -> str:
        path = self._path if permanent else self._temp_path
        assert_or_throw(
            path != "",
            ValueError(
                "fugue.workflow.checkpoint.path is not set for checkpoints"
            ),
        )
        return self._engine.fs.join(path, f"{obj_id}.{fmt}")

    def file_exists(self, path: str) -> bool:
        return self._engine.fs.exists(path)

    def temp_file(self, fmt: str = "parquet") -> str:
        assert_or_throw(
            self._temp_path != "",
            ValueError("fugue.workflow.checkpoint.path is not set"),
        )
        return self._engine.fs.join(self._temp_path, f"{uuid4()}.{fmt}")
