"""The #1 entry points: transform / out_transform / raw_sql (reference
fugue/workflow/api.py:34,187,253)."""

from typing import Any, Callable, List, Optional

from fugue_tpu.collections.sql import StructuredRawSQL, TempTableName
from fugue_tpu.collections.yielded import Yielded
from fugue_tpu.dataframe import DataFrame
from fugue_tpu.dataframe.api import as_fugue_df, get_native_as_df
from fugue_tpu.execution.factory import make_execution_engine
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.workflow.workflow import FugueWorkflow, WorkflowDataFrame


def transform(
    df: Any,
    using: Any,
    schema: Any = None,
    params: Any = None,
    partition: Any = None,
    callback: Any = None,
    ignore_errors: Optional[List[type]] = None,
    persist: bool = False,
    as_local: bool = False,
    as_fugue: bool = False,
    engine: Any = None,
    engine_conf: Any = None,
) -> Any:
    """Transform ``df`` by ``using`` (an interfaceless function, Transformer,
    or registered alias) on any engine — the one-line entry point (call stack
    parity: SURVEY §3.1)."""
    dag = FugueWorkflow()
    src = dag.create_data(df)
    if partition is not None:
        src = src.partition(partition)
    tdf = src.transform(
        using,
        schema=schema,
        params=params,
        ignore_errors=ignore_errors,
        callback=callback,
    )
    if persist:
        tdf = tdf.persist()
    tdf.yield_dataframe_as("result", as_local=as_local)
    e = make_execution_engine(engine, engine_conf, infer_by=[df])
    dag.run(e)
    result = dag.yields["result"].result  # type: ignore
    if as_fugue or isinstance(df, (DataFrame, Yielded)):
        return result
    # local results surface as pandas — reference fugue/workflow/api.py:184
    return result.as_pandas() if result.is_local else get_native_as_df(result)


def out_transform(
    df: Any,
    using: Any,
    params: Any = None,
    partition: Any = None,
    callback: Any = None,
    ignore_errors: Optional[List[type]] = None,
    engine: Any = None,
    engine_conf: Any = None,
) -> None:
    """Transform with no output — side effects only (reference api.py:187)."""
    dag = FugueWorkflow()
    src = dag.create_data(df)
    if partition is not None:
        src = src.partition(partition)
    src.out_transform(
        using, params=params, ignore_errors=ignore_errors, callback=callback
    )
    e = make_execution_engine(engine, engine_conf, infer_by=[df])
    dag.run(e)


def raw_sql(
    *statements: Any,
    engine: Any = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
    as_local: bool = False,
) -> Any:
    """Run a SQL SELECT mixing string fragments and dataframes::

        raw_sql("SELECT a FROM", df, "WHERE a > 0")
    """
    from fugue_tpu.collections.sql import interleave_sql

    dag = FugueWorkflow()
    parts, dfs = interleave_sql(statements)
    named = {k: dag.create_data(v) for k, v in dfs.items()}
    tdf = dag.select(
        StructuredRawSQL(parts), dfs=named if len(named) > 0 else None
    )
    tdf.yield_dataframe_as("result", as_local=as_local)
    e = make_execution_engine(engine, engine_conf, infer_by=list(dfs.values()))
    dag.run(e)
    result = dag.yields["result"].result  # type: ignore
    if as_fugue or any(isinstance(x, DataFrame) for x in dfs.values()):
        return result
    return result.native if result.is_local else get_native_as_df(result)


def explain(
    df: Any = None, conf: Any = None, engine: Any = None
) -> Any:
    """EXPLAIN without executing: the static plan report
    (:class:`~fugue_tpu.analysis.explain.ExplainReport`) for a built
    :class:`FugueWorkflow`, a :class:`WorkflowDataFrame` (its whole
    workflow), or any raw dataframe (a one-task plan). Renders the
    optimizer-rewritten task tree with applied rewrites, propagated
    schemas and estimated device bytes via ``.to_text()`` /
    ``.to_dict()``; run with ``fugue.obs.profile`` and read
    ``FugueWorkflowResult.profile()`` for EXPLAIN ANALYZE."""
    if isinstance(df, FugueWorkflow):
        return df.explain(conf=conf, engine=engine)
    if isinstance(df, WorkflowDataFrame):
        return df.workflow.explain(conf=conf, engine=engine)
    dag = FugueWorkflow(conf)
    if df is not None:
        dag.create_data(df)
    return dag.explain(conf=conf, engine=engine)
