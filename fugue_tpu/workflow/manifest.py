"""Run manifest: checkpoint-backed resume for killed/failed workflows.

With ``fugue.workflow.resume`` enabled (and a checkpoint path set), every
task completion atomically rewrites a small JSON manifest under the
checkpoint dir, keyed by the workflow's deterministic uuid::

    <checkpoint.path>/manifest_<workflow_uuid>.json
    {"workflow": "...", "completed": {task_uuid: {name, artifact, fmt}}}

The manifest is crash-durable — a run killed mid-flight leaves it behind.
Re-running the IDENTICAL DAG (same workflow uuid — the task-uuid
determinism backbone guarantees identical specs hash identically)
consults it before executing each task: a completed task whose artifact
URI still exists short-circuits (the artifact is served by the task's
own deterministic-checkpoint ``try_load`` through ``engine.fs``), so
execution restarts at the frontier. Artifacts exist for
deterministically-checkpointed tasks (their files are permanent);
completed tasks without a durable artifact are recorded for
observability but re-execute. A fully successful run deletes its
manifest — resume state never outlives the failure it serves.
"""

import json
import threading
from typing import Any, Dict, Optional

from fugue_tpu.constants import (
    FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH,
    FUGUE_CONF_WORKFLOW_RESUME,
)


class RunManifest:
    """Tracks completed task uuids + artifact URIs for one workflow run."""

    def __init__(self, engine: Any, checkpoint_path: Any, workflow_uuid: str):
        self._engine = engine
        self._ckpt = checkpoint_path
        self._wf_uuid = workflow_uuid
        self._lock = threading.Lock()
        self._completed: Dict[str, Dict[str, Any]] = {}
        self._resumable: Dict[str, Dict[str, Any]] = {}

    @staticmethod
    def from_conf(
        engine: Any, checkpoint_path: Any, workflow_uuid: str
    ) -> Optional["RunManifest"]:
        """Build the manifest manager when resume is on and a durable
        checkpoint dir exists to hold it; None otherwise."""
        if not engine.conf.get(FUGUE_CONF_WORKFLOW_RESUME, False):
            return None
        base = str(
            engine.conf.get(FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH, "")
        ).strip()
        if base == "":
            return None
        m = RunManifest(engine, checkpoint_path, workflow_uuid)
        m.load()
        return m

    @property
    def uri(self) -> str:
        base = str(
            self._engine.conf.get(FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH, "")
        ).strip()
        return self._engine.fs.join(base, f"manifest_{self._wf_uuid}.json")

    @property
    def completed(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return dict(self._completed)

    def load(self) -> None:
        """Read a prior (killed/failed) run's manifest; its completed set
        becomes this run's resume candidates."""
        fs = self._engine.fs
        uri = self.uri
        try:
            if not fs.exists(uri):
                return
            data = json.loads(fs.read_bytes(uri).decode("utf-8"))
        except Exception:  # unreadable manifest: resume is best-effort
            self._engine.log.warning(
                "fugue_tpu resume: manifest %s unreadable; ignoring", uri
            )
            return
        if data.get("workflow") != self._wf_uuid:  # pragma: no cover
            return
        self._resumable = dict(data.get("completed", {}))

    def can_resume(self, task: Any, ctx: Any) -> bool:
        """True when the prior run completed this task AND its durable
        artifact still exists. The caller then runs the task's NORMAL
        execute path — validation rules still fire (they are workflow
        declarations, not data checks — see ProcessTask.execute) and the
        deterministic checkpoint's ``try_load`` serves the artifact, so
        resume adds no second load path to keep consistent."""
        rec = self._resumable.get(task.__uuid__())
        if rec is None:
            return False
        uri = rec.get("artifact")
        if not uri:
            return False
        try:
            return bool(ctx.engine.fs.exists(uri))
        except Exception:  # pragma: no cover - fs probe failure
            return False

    def mark_complete(self, task: Any) -> None:
        """Record a finished task and atomically rewrite the manifest —
        the incremental write is what makes resume survive a hard kill,
        not just a graceful failure."""
        ckpt = task.checkpoint
        rec = {
            "name": task.name,
            "artifact": ckpt.artifact_uri(self._ckpt),
            "fmt": ckpt.fmt,
        }
        with self._lock:
            # write under the lock: concurrent completions must not land
            # an older snapshot LAST and drop a finished task from the
            # manifest a resume will trust
            self._completed[task.__uuid__()] = rec
            payload = json.dumps(
                {"workflow": self._wf_uuid, "completed": self._completed},
                indent=1,
            ).encode("utf-8")
            try:
                self._engine.fs.write_file_atomic(
                    self.uri, lambda fp: fp.write(payload)
                )
            except Exception:  # pragma: no cover - manifest is best-effort
                self._engine.log.warning(
                    "fugue_tpu resume: failed writing manifest %s", self.uri
                )

    def finish(self) -> None:
        """Successful run: the manifest has served its purpose."""
        try:
            self._engine.fs.rm(self.uri)
        except Exception:  # pragma: no cover - best effort
            pass
