"""Run manifest: checkpoint-backed resume for killed/failed workflows.

With ``fugue.workflow.resume`` enabled (and a checkpoint path set), every
task completion atomically rewrites a small JSON manifest under the
checkpoint dir, keyed by the workflow's deterministic uuid::

    <checkpoint.path>/manifest_<workflow_uuid>.json
    {"workflow": "...", "completed":
        {task_uuid: {name, artifact, fmt, size, sha256}}}

The manifest is crash-durable — a run killed mid-flight leaves it behind.
Re-running the IDENTICAL DAG (same workflow uuid — the task-uuid
determinism backbone guarantees identical specs hash identically)
consults it before executing each task: a completed task whose artifact
URI still exists short-circuits (the artifact is served by the task's
own deterministic-checkpoint ``try_load`` through ``engine.fs``), so
execution restarts at the frontier. Artifacts exist for
deterministically-checkpointed tasks (their files are permanent);
completed tasks without a durable artifact are recorded for
observability but re-execute. A fully successful run deletes its
manifest — resume state never outlives the failure it serves.

**Artifact integrity**: each completion records the artifact's byte size
and sha256. ``can_resume`` recomputes the fingerprint before serving a
checkpoint hit — a truncated or corrupted artifact (a crash mid-write
outside the atomic path, bit rot on remote storage) is treated as
INCOMPLETE: the stale file is removed so the deterministic checkpoint
recomputes instead of loading garbage, and the rejection is counted in
``fault_stats["integrity_rejected"]``.
"""

import hashlib
import json
from typing import Any, Dict, Optional, Tuple

from fugue_tpu.constants import (
    FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH,
    FUGUE_CONF_WORKFLOW_RESUME,
    typed_conf_get,
)
from fugue_tpu.testing.locktrace import tracked_lock


_FINGERPRINT_CHUNK = 4 * 1024 * 1024


def atomic_json_write(fs: Any, uri: str, payload: Dict[str, Any]) -> None:
    """Atomically rewrite ``uri`` with ``payload`` as indented JSON —
    the crash-durability primitive shared by the run manifest and the
    serving daemon's state journal (serve/state.py): a hard kill leaves
    either the previous snapshot or the new one, never a torn file."""
    data = json.dumps(payload, indent=1).encode("utf-8")
    fs.write_file_atomic(uri, lambda fp: fp.write(data))


def read_json(
    fs: Any, uri: str, log: Any = None, what: str = "state file"
) -> Optional[Dict[str, Any]]:
    """Best-effort JSON read: None when the file is missing or
    unreadable (recovery consumers treat that as 'no prior state').
    Missing is silent; an EXISTING-but-unreadable file warns through
    ``log`` — an operator debugging a from-scratch restart needs the
    signal that prior state was there and got rejected."""
    try:
        if not fs.exists(uri):
            return None
        data = json.loads(fs.read_bytes(uri).decode("utf-8"))
        if isinstance(data, dict):
            return data
    except Exception:
        pass
    if log is not None:
        log.warning("fugue_tpu: %s %s unreadable; ignoring", what, uri)
    return None


def artifact_fingerprint(fs: Any, uri: str) -> Tuple[int, str]:
    """(total bytes, sha256 hexdigest) of a checkpoint artifact — a
    single file, or a part-file directory hashed as sorted
    (relative name, size, bytes) records so the digest is layout-stable.
    Dot/underscore-prefixed entries (atomic temps, markers) are skipped,
    matching what the readers consume. Files hash in streamed chunks:
    constant memory regardless of artifact size (this runs on the
    SUCCESS path of every completed task, not just on resume)."""
    h = hashlib.sha256()
    total = 0

    def _walk(path: str, rel: str) -> None:
        nonlocal total
        if fs.isdir(path):
            for name in sorted(fs.listdir(path)):
                if name.startswith(".") or name.startswith("_"):
                    continue
                _walk(fs.join(path, name), f"{rel}/{name}" if rel else name)
            return
        h.update(rel.encode("utf-8"))
        size = fs.file_size(path)
        h.update(int(size).to_bytes(8, "little"))
        total += size
        with fs.open_input_stream(path) as fp:
            while True:
                chunk = fp.read(_FINGERPRINT_CHUNK)
                if not chunk:
                    break
                h.update(chunk)

    _walk(uri, "")
    return total, h.hexdigest()


class RunManifest:
    """Tracks completed task uuids + artifact URIs for one workflow run."""

    def __init__(self, engine: Any, checkpoint_path: Any, workflow_uuid: str):
        self._engine = engine
        self._ckpt = checkpoint_path
        self._wf_uuid = workflow_uuid
        self._lock = tracked_lock("workflow.manifest.RunManifest._lock")
        self._completed: Dict[str, Dict[str, Any]] = {}
        self._resumable: Dict[str, Dict[str, Any]] = {}

    @staticmethod
    def from_conf(
        engine: Any, checkpoint_path: Any, workflow_uuid: str
    ) -> Optional["RunManifest"]:
        """Build the manifest manager when resume is on and a durable
        checkpoint dir exists to hold it; None otherwise."""
        if not typed_conf_get(engine.conf, FUGUE_CONF_WORKFLOW_RESUME):
            return None
        base = str(
            typed_conf_get(engine.conf, FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH)
        ).strip()
        if base == "":
            return None
        m = RunManifest(engine, checkpoint_path, workflow_uuid)
        m.load()
        return m

    @property
    def uri(self) -> str:
        base = str(
            typed_conf_get(self._engine.conf, FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH)
        ).strip()
        return self._engine.fs.join(base, f"manifest_{self._wf_uuid}.json")

    @property
    def completed(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return dict(self._completed)

    def load(self) -> None:
        """Read a prior (killed/failed) run's manifest; its completed set
        becomes this run's resume candidates."""
        uri = self.uri
        data = read_json(
            self._engine.fs, uri, log=self._engine.log, what="resume manifest"
        )
        if data is None:  # missing or unreadable: resume is best-effort
            return
        if data.get("workflow") != self._wf_uuid:  # pragma: no cover
            return
        self._resumable = dict(data.get("completed", {}))

    def can_resume(self, task: Any, ctx: Any, stats: Any = None) -> bool:
        """True when the prior run completed this task AND its durable
        artifact still exists and verifies against the recorded
        size/sha256. The caller then runs the task's NORMAL execute path
        — validation rules still fire (they are workflow declarations,
        not data checks — see ProcessTask.execute) and the deterministic
        checkpoint's ``try_load`` serves the artifact, so resume adds no
        second load path to keep consistent. A corrupted artifact is
        REMOVED so the checkpoint recomputes instead of loading it."""
        rec = self._resumable.get(task.__uuid__())
        if rec is None:
            return False
        uri = rec.get("artifact")
        if not uri:
            return False
        fs = ctx.engine.fs
        try:
            if not fs.exists(uri):
                return False
            want_sha = rec.get("sha256")
            if want_sha:
                size, digest = artifact_fingerprint(fs, uri)
                want_size = rec.get("size")
                if digest != want_sha or (
                    want_size is not None and size != want_size
                ):
                    self._engine.log.warning(
                        "fugue_tpu resume: artifact %s failed integrity "
                        "check (size %s vs %s); recomputing task %s",
                        uri, size, want_size, rec.get("name", "?"),
                    )
                    if stats is not None:
                        stats.note_integrity_rejected(task.name)
                    try:
                        fs.rm(uri, recursive=True)
                    except Exception:  # pragma: no cover - best effort
                        pass
                    return False
        except Exception:  # pragma: no cover - fs probe failure
            return False
        return True

    def mark_complete(self, task: Any) -> None:
        """Record a finished task and atomically rewrite the manifest —
        the incremental write is what makes resume survive a hard kill,
        not just a graceful failure."""
        ckpt = task.checkpoint
        artifact = ckpt.artifact_uri(self._ckpt)
        size: Optional[int] = None
        sha256: Optional[str] = None
        if artifact:
            # fingerprint OUTSIDE the lock (reads the whole artifact);
            # best-effort — a missing fingerprint just skips verification
            try:
                size, sha256 = artifact_fingerprint(
                    self._engine.fs, artifact
                )
            except Exception:  # pragma: no cover - storage hiccup
                self._engine.log.warning(
                    "fugue_tpu resume: could not fingerprint artifact %s",
                    artifact,
                )
        rec = {
            "name": task.name,
            "artifact": artifact,
            "fmt": ckpt.fmt,
            "size": size,
            "sha256": sha256,
        }
        with self._lock:
            # write under the lock: concurrent completions must not land
            # an older snapshot LAST and drop a finished task from the
            # manifest a resume will trust
            self._completed[task.__uuid__()] = rec
            try:
                atomic_json_write(
                    self._engine.fs,
                    self.uri,
                    {"workflow": self._wf_uuid, "completed": self._completed},
                )
            except Exception:  # pragma: no cover - manifest is best-effort
                self._engine.log.warning(
                    "fugue_tpu resume: failed writing manifest %s", self.uri
                )

    def finish(self) -> None:
        """Successful run: the manifest has served its purpose."""
        try:
            self._engine.fs.rm(self.uri)
        except Exception:  # pragma: no cover - best effort
            pass
