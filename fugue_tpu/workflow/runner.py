"""Lightweight parallel DAG runner — replaces the reference's external
`adagio` dependency (SURVEY §7 step 6: "own lightweight parallel DAG
runner"). Topological execution with bounded concurrency; independent tasks
run concurrently when ``fugue.workflow.concurrency > 1``.

Fault semantics (the production contract):

- A task failure stops LAUNCHING but the runner drains: every in-flight
  sibling is awaited (their results/side effects stay consistent) and
  every failure is collected — a single failure re-raises the original
  exception unchanged (compat with ``raises(UserError)`` call sites),
  two or more raise one structured
  :class:`~fugue_tpu.exceptions.WorkflowRuntimeError` listing every
  failed task with its name and user callsite.
- A per-task wall-clock ``timeout`` (node field, fed from
  ``fugue.workflow.timeout``/per-task policy) is enforced by the
  parallel runner and covers EXECUTION time (queue wait is free): an
  expired task is abandoned (recorded as
  :class:`~fugue_tpu.exceptions.TaskTimeoutError`), never awaited in
  the drain. Workers are bounded DAEMON threads (not a
  ThreadPoolExecutor, whose non-daemon workers would be joined at
  interpreter shutdown) so a wedged call in a TIMED task can't hang
  the workflow or process exit; a wedged task WITHOUT a timeout is
  awaited indefinitely by the drain (no budget means no abandonment),
  and the serial runner cannot preempt at all (it warns when timeouts
  are configured with concurrency <= 1).
- On any failure/timeout the shared :class:`CancelToken` is set;
  launched-but-unstarted siblings abort at their first cancellation
  point and are NOT recorded as failures (they didn't fail — they were
  cancelled).
- An EXTERNAL cancellation (a caller-owned ``cancel_token`` set from
  another thread — the serving daemon's job-cancel path) stops task
  launch at the next supervisor round, drains in-flight work, and
  raises :class:`~fugue_tpu.exceptions.TaskCancelledError` when the run
  did not complete; a token set after every task already finished is a
  completed run, not a cancelled one.
"""

import threading
import time
from concurrent.futures import FIRST_COMPLETED, Future, wait
from typing import Any, Callable, Dict, List, Optional, Set

from fugue_tpu.exceptions import (
    TaskCancelledError,
    TaskFailure,
    TaskTimeoutError,
    WorkflowRuntimeError,
)
from fugue_tpu.obs.trace import activate, current_span
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.workflow.fault import CancelToken


class TaskNode:
    def __init__(
        self,
        task_id: str,
        func: Callable[[List[Any]], Any],
        dependencies: List[str],
        name: Optional[str] = None,
        callsite: Optional[List[str]] = None,
        timeout: float = 0.0,
    ):
        self.task_id = task_id
        self.func = func
        self.dependencies = dependencies
        self.name = name or task_id
        self.callsite = list(callsite or [])
        self.timeout = max(0.0, float(timeout))
        # stamped by the worker thread when execution actually BEGINS:
        # the wall-clock budget covers run time, not launch-queue wait
        self.started_at: Optional[float] = None


class DAGRunner:
    """Run tasks respecting dependencies; results keyed by task id."""

    def __init__(self, concurrency: int = 1):
        self._concurrency = max(1, concurrency)

    def run(
        self,
        nodes: List[TaskNode],
        on_complete: Optional[Callable[[TaskNode], None]] = None,
        cancel_token: Optional[CancelToken] = None,
    ) -> Dict[str, Any]:
        by_id = {n.task_id: n for n in nodes}
        for n in nodes:
            for d in n.dependencies:
                assert_or_throw(d in by_id, ValueError(f"unknown dependency {d}"))
            n.started_at = None  # nodes may be reused across runs
        results: Dict[str, Any] = {}
        token = cancel_token if cancel_token is not None else CancelToken()
        if self._concurrency <= 1:
            if any(n.timeout > 0 for n in nodes):
                import logging

                logging.getLogger("fugue_tpu").warning(
                    "task timeouts are configured but "
                    "fugue.workflow.concurrency <= 1: the serial runner "
                    "cannot preempt a task — timeouts will NOT be enforced"
                )
            for n in self._topological(nodes):
                token.raise_if_cancelled()
                try:
                    results[n.task_id] = n.func(
                        [results[d] for d in n.dependencies]
                    )
                except BaseException:
                    token.cancel()
                    raise
                self._notify(on_complete, n)
            return results
        return self._run_parallel(nodes, results, on_complete, token)

    def _notify(
        self, on_complete: Optional[Callable[[TaskNode], None]], node: TaskNode
    ) -> None:
        if on_complete is not None:
            try:
                on_complete(node)
            except Exception:  # manifest write is best-effort observability
                pass

    def _topological(self, nodes: List[TaskNode]) -> List[TaskNode]:
        done: Set[str] = set()
        ordered: List[TaskNode] = []
        remaining = list(nodes)
        while remaining:
            progress = False
            still: List[TaskNode] = []
            for n in remaining:
                if all(d in done for d in n.dependencies):
                    ordered.append(n)
                    done.add(n.task_id)
                    progress = True
                else:
                    still.append(n)
            assert_or_throw(progress, ValueError("cycle detected in workflow DAG"))
            remaining = still
        return ordered

    def _run_parallel(
        self,
        nodes: List[TaskNode],
        results: Dict[str, Any],
        on_complete: Optional[Callable[[TaskNode], None]],
        token: CancelToken,
    ) -> Dict[str, Any]:
        pending = {n.task_id: n for n in nodes}
        running: Dict[Future, TaskNode] = {}
        failures: List[TaskFailure] = []
        while running or (
            pending and not failures and not token.cancelled
        ):
            if not failures and not token.cancelled:
                # bounded concurrency: launch ready tasks into free slots
                # only (each task gets its own daemon worker thread)
                free = self._concurrency - len(running)
                if free > 0:
                    ready = [
                        n for n in pending.values()
                        if all(d in results for d in n.dependencies)
                    ][:free]
                    for n in ready:
                        del pending[n.task_id]
                        deps = [results[d] for d in n.dependencies]
                        running[self._spawn(n, deps, token, on_complete)] = n
                if not running:
                    assert_or_throw(
                        not pending,
                        ValueError("cycle detected in workflow DAG"),
                    )
                    break
            if not running:
                break
            finished, _ = wait(
                list(running.keys()),
                timeout=self._next_wait(running.values()),
                return_when=FIRST_COMPLETED,
            )
            for f in finished:
                n = running.pop(f)
                err = f.exception()
                if err is None:
                    results[n.task_id] = f.result()
                elif isinstance(err, TaskCancelledError):
                    pass  # cancelled, not failed
                else:
                    failures.append(
                        TaskFailure(n.task_id, n.name, err, n.callsite)
                    )
                    token.cancel()
            # expire tasks whose EXECUTION exceeded their budget: record
            # the timeout, abandon the future (its daemon thread can't be
            # killed, but it can't wedge the drain or interpreter exit
            # either), cancel siblings. A future that completed while the
            # supervisor was busy is NOT expired — it's harvested on the
            # next wait round.
            now = time.monotonic()
            for f, n in [
                (f, n)
                for f, n in running.items()
                if n.timeout > 0
                and not f.done()
                and n.started_at is not None
                and now - n.started_at >= n.timeout
            ]:
                del running[f]
                failures.append(
                    TaskFailure(
                        n.task_id,
                        n.name,
                        TaskTimeoutError(n.name, n.timeout),
                        n.callsite,
                    )
                )
                token.cancel()
        if failures:
            if len(failures) == 1:
                raise failures[0].error
            raise WorkflowRuntimeError(failures)
        if len(results) < len(nodes):
            # nothing failed but not every task completed: an externally
            # cancelled run surfaces as cancellation, not as a silent
            # partial result dict
            token.raise_if_cancelled()
        return results

    def _spawn(
        self,
        node: TaskNode,
        deps: List[Any],
        token: CancelToken,
        on_complete: Optional[Callable[[TaskNode], None]],
    ) -> Future:
        """One bounded worker: a DAEMON thread resolving a Future. The
        completion callback (manifest write — possibly remote fs I/O)
        runs HERE, not on the supervisor thread, so it can't stall task
        launch or timeout enforcement; it finishes before the future
        resolves, so downstream tasks only launch after the manifest
        already records their dependency."""
        f: Future = Future()

        # tracing context crosses the thread boundary explicitly: the
        # caller's span (captured at run()) is re-attached inside the
        # worker so task/attempt/engine spans land in the right tree
        ambient = current_span()

        def work() -> None:
            if not f.set_running_or_notify_cancel():  # pragma: no cover
                return
            with activate(ambient):
                try:
                    # first cancellation point: a task launched just
                    # before a sibling failed aborts here instead of
                    # doing work the run will discard
                    token.raise_if_cancelled()
                    node.started_at = time.monotonic()
                    result = node.func(deps)
                except BaseException as ex:
                    f.set_exception(ex)
                    return
                # stop the wall clock BEFORE the completion callback: a
                # slow manifest write (remote fs) must not expire a task
                # whose work already succeeded
                node.started_at = None
                self._notify(on_complete, node)
            f.set_result(result)

        threading.Thread(
            target=work, daemon=True, name=f"fugue-task-{node.task_id}"
        ).start()
        return f

    @staticmethod
    def _next_wait(running: Any) -> Optional[float]:
        """How long the supervisor may block: until the nearest deadline
        of a STARTED timed task, or a short poll while a timed task has
        not stamped its start yet (its clock begins at execution)."""
        now = time.monotonic()
        wait_for: Optional[float] = None
        for n in running:
            if n.timeout <= 0:
                continue
            remaining = (
                0.05 if n.started_at is None
                else max(0.0, n.started_at + n.timeout - now)
            )
            if wait_for is None or remaining < wait_for:
                wait_for = remaining
        return wait_for
