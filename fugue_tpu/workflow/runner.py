"""Lightweight parallel DAG runner — replaces the reference's external
`adagio` dependency (SURVEY §7 step 6: "own lightweight parallel DAG
runner"). Topological execution with bounded concurrency; independent tasks
run concurrently when ``fugue.workflow.concurrency > 1``."""

from concurrent.futures import FIRST_COMPLETED, Future, ThreadPoolExecutor, wait
from typing import Any, Callable, Dict, List, Optional, Set

from fugue_tpu.utils.assertion import assert_or_throw


class TaskNode:
    def __init__(self, task_id: str, func: Callable[[List[Any]], Any],
                 dependencies: List[str]):
        self.task_id = task_id
        self.func = func
        self.dependencies = dependencies


class DAGRunner:
    """Run tasks respecting dependencies; results keyed by task id."""

    def __init__(self, concurrency: int = 1):
        self._concurrency = max(1, concurrency)

    def run(self, nodes: List[TaskNode]) -> Dict[str, Any]:
        by_id = {n.task_id: n for n in nodes}
        for n in nodes:
            for d in n.dependencies:
                assert_or_throw(d in by_id, ValueError(f"unknown dependency {d}"))
        results: Dict[str, Any] = {}
        if self._concurrency <= 1:
            for n in self._topological(nodes):
                results[n.task_id] = n.func([results[d] for d in n.dependencies])
            return results
        return self._run_parallel(nodes, results)

    def _topological(self, nodes: List[TaskNode]) -> List[TaskNode]:
        done: Set[str] = set()
        ordered: List[TaskNode] = []
        remaining = list(nodes)
        while remaining:
            progress = False
            still: List[TaskNode] = []
            for n in remaining:
                if all(d in done for d in n.dependencies):
                    ordered.append(n)
                    done.add(n.task_id)
                    progress = True
                else:
                    still.append(n)
            assert_or_throw(progress, ValueError("cycle detected in workflow DAG"))
            remaining = still
        return ordered

    def _run_parallel(
        self, nodes: List[TaskNode], results: Dict[str, Any]
    ) -> Dict[str, Any]:
        pending = {n.task_id: n for n in nodes}
        running: Dict[Future, str] = {}
        first_error: List[BaseException] = []
        with ThreadPoolExecutor(max_workers=self._concurrency) as pool:
            while (pending or running) and not first_error:
                # launch all ready tasks
                ready = [
                    n for n in pending.values()
                    if all(d in results for d in n.dependencies)
                ]
                for n in ready:
                    del pending[n.task_id]
                    deps = [results[d] for d in n.dependencies]
                    running[pool.submit(n.func, deps)] = n.task_id
                if not running:
                    assert_or_throw(
                        not pending, ValueError("cycle detected in workflow DAG")
                    )
                    break
                finished, _ = wait(list(running.keys()), return_when=FIRST_COMPLETED)
                for f in finished:
                    tid = running.pop(f)
                    err = f.exception()
                    if err is not None:
                        first_error.append(err)
                    else:
                        results[tid] = f.result()
            # drain remaining futures on error
            for f in list(running.keys()):
                f.cancel()
        if first_error:
            raise first_error[0]
        return results
