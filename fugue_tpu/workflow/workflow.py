"""FugueWorkflow: the lazy DAG programming interface (reference
fugue/workflow/workflow.py:88-2302 re-built on our own runner/tasks).

``FugueWorkflow()`` collects operations as deterministic tasks;
``run(engine)`` executes them (nothing is compiled before that)."""

from contextlib import nullcontext
from typing import Any, Callable, Dict, Iterable, List, Optional, Union
from uuid import uuid4

from fugue_tpu.collections.partition import PartitionSpec
from fugue_tpu.collections.sql import StructuredRawSQL
from fugue_tpu.collections.yielded import PhysicalYielded, Yielded
from fugue_tpu.column.expressions import ColumnExpr
from fugue_tpu.column.sql import SelectColumns
from fugue_tpu.constants import (
    FUGUE_CONF_ANALYSIS,
    FUGUE_CONF_WORKFLOW_CONCURRENCY,
    FUGUE_CONF_WORKFLOW_EXCEPTION_HIDE,
    FUGUE_CONF_WORKFLOW_EXCEPTION_INJECT,
    FUGUE_GLOBAL_CONF,
)
from fugue_tpu.dataframe import DataFrame
from fugue_tpu.dataframe.dataframe import YieldedDataFrame
from fugue_tpu.execution.factory import make_execution_engine
from fugue_tpu.extensions.builtins import (
    Aggregate,
    AlterColumns,
    Assign,
    AssertEqFunc,
    AssertNotEqFunc,
    CreateData,
    Distinct,
    DropColumns,
    Dropna,
    Fillna,
    Filter,
    Load,
    Rename,
    RunJoin,
    RunOutputTransformer,
    RunSetOperation,
    RunSQLSelect,
    RunTransformer,
    Sample,
    Save,
    SaveAndUse,
    Select,
    SelectColumnsP,
    Show,
    Take,
    Zip,
)
from fugue_tpu.obs import (
    activate,
    current_span,
    finalize_trace,
    obs_options,
    open_trace,
    start_span,
    tracing_suppressed,
)
from fugue_tpu.obs.profile import (
    Profiler,
    profiling_forced,
    profiling_requested,
    task_scope,
)
from fugue_tpu.obs.trace import NULL_CM
from fugue_tpu.rpc import make_rpc_server, to_rpc_handler
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.utils.exception import (
    add_error_note,
    extract_user_callsite,
    prune_traceback,
)
from fugue_tpu.utils.hash import to_uuid
from fugue_tpu.utils.params import ParamDict
from fugue_tpu.workflow.checkpoint import (
    Checkpoint,
    CheckpointPath,
    StrongCheckpoint,
    TableCheckpoint,
    WeakCheckpoint,
)
from fugue_tpu.workflow.fault import (
    CancelToken,
    RetryPolicy,
    RunStats,
    execute_with_policy,
)
from fugue_tpu.workflow.manifest import RunManifest
from fugue_tpu.workflow.runner import DAGRunner, TaskNode
from fugue_tpu.workflow.tasks import (
    CreateTask,
    FugueTask,
    OutputTask,
    ProcessTask,
    TaskContext,
)


class WorkflowDataFrame:
    """Lazy handle to a dataframe inside a workflow DAG (reference
    workflow.py:88). All methods add tasks; nothing executes until
    ``workflow.run``."""

    def __init__(self, workflow: "FugueWorkflow", task: FugueTask):
        self._workflow = workflow
        self._task = task
        self._pending_partition: Optional[PartitionSpec] = None

    @property
    def workflow(self) -> "FugueWorkflow":
        return self._workflow

    @property
    def task(self) -> FugueTask:
        return self._task

    @property
    def partition_spec(self) -> PartitionSpec:
        return self._pending_partition or PartitionSpec()

    def __uuid__(self) -> str:
        return self._task.__uuid__()

    # ---- partition hints -------------------------------------------------
    def partition(self, *args: Any, **kwargs: Any) -> "WorkflowDataFrame":
        res = WorkflowDataFrame(self._workflow, self._task)
        res._pending_partition = PartitionSpec(*args, **kwargs)
        return res

    def partition_by(self, *keys: str, **kwargs: Any) -> "WorkflowDataFrame":
        return self.partition(by=list(keys), **kwargs)

    def per_partition_by(self, *keys: str) -> "WorkflowDataFrame":
        return self.partition(by=list(keys), algo="coarse")

    def per_row(self) -> "WorkflowDataFrame":
        return self.partition("per_row")

    # ---- transform -------------------------------------------------------
    def transform(
        self,
        using: Any,
        schema: Any = None,
        params: Any = None,
        pre_partition: Any = None,
        ignore_errors: Optional[List[type]] = None,
        callback: Any = None,
    ) -> "WorkflowDataFrame":
        if pre_partition is None and self._pending_partition is not None:
            pre_partition = self._pending_partition
        task = ProcessTask(
            RunTransformer,
            params=dict(
                transformer=using,
                schema=schema,
                params=ParamDict(params),
                ignore_errors=ignore_errors or [],
                rpc_handler=None if callback is None else to_rpc_handler(callback),
            ),
            partition_spec=PartitionSpec(pre_partition),
            input_tasks=[self._task],
        )
        return self._workflow.add(task)

    def out_transform(
        self,
        using: Any,
        params: Any = None,
        pre_partition: Any = None,
        ignore_errors: Optional[List[type]] = None,
        callback: Any = None,
    ) -> None:
        if pre_partition is None and self._pending_partition is not None:
            pre_partition = self._pending_partition
        task = OutputTask(
            RunOutputTransformer,
            params=dict(
                transformer=using,
                params=ParamDict(params),
                ignore_errors=ignore_errors or [],
                rpc_handler=None if callback is None else to_rpc_handler(callback),
            ),
            partition_spec=PartitionSpec(pre_partition),
            input_tasks=[self._task],
        )
        self._workflow.add(task)

    def process(
        self,
        using: Any,
        schema: Any = None,
        params: Any = None,
        pre_partition: Any = None,
    ) -> "WorkflowDataFrame":
        return self._workflow.process(
            self, using=using, schema=schema, params=params,
            pre_partition=pre_partition or self._pending_partition,
        )

    def output(self, using: Any, params: Any = None, pre_partition: Any = None) -> None:
        self._workflow.output(
            self, using=using, params=params,
            pre_partition=pre_partition or self._pending_partition,
        )

    # ---- relational ------------------------------------------------------
    def join(
        self, *dfs: "WorkflowDataFrame", how: str, on: Optional[List[str]] = None
    ) -> "WorkflowDataFrame":
        return self._workflow.join(self, *dfs, how=how, on=on)

    def inner_join(self, *dfs: Any, on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self.join(*dfs, how="inner", on=on)

    def semi_join(self, *dfs: Any, on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self.join(*dfs, how="semi", on=on)

    def anti_join(self, *dfs: Any, on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self.join(*dfs, how="anti", on=on)

    def left_outer_join(self, *dfs: Any, on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self.join(*dfs, how="left_outer", on=on)

    def right_outer_join(self, *dfs: Any, on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self.join(*dfs, how="right_outer", on=on)

    def full_outer_join(self, *dfs: Any, on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self.join(*dfs, how="full_outer", on=on)

    def cross_join(self, *dfs: Any) -> "WorkflowDataFrame":
        return self.join(*dfs, how="cross")

    def union(self, *dfs: Any, distinct: bool = True) -> "WorkflowDataFrame":
        return self._workflow.set_op("union", self, *dfs, distinct=distinct)

    def subtract(self, *dfs: Any, distinct: bool = True) -> "WorkflowDataFrame":
        return self._workflow.set_op("subtract", self, *dfs, distinct=distinct)

    def intersect(self, *dfs: Any, distinct: bool = True) -> "WorkflowDataFrame":
        return self._workflow.set_op("intersect", self, *dfs, distinct=distinct)

    def distinct(self) -> "WorkflowDataFrame":
        return self._add_process(Distinct)

    def dropna(
        self, how: str = "any", thresh: Optional[int] = None,
        subset: Optional[List[str]] = None,
    ) -> "WorkflowDataFrame":
        params: Dict[str, Any] = dict(how=how, subset=subset)
        if thresh is not None:
            params["thresh"] = thresh
        return self._add_process(Dropna, params=params)

    def fillna(self, value: Any, subset: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self._add_process(Fillna, params=dict(value=value, subset=subset))

    def sample(
        self, n: Optional[int] = None, frac: Optional[float] = None,
        replace: bool = False, seed: Optional[int] = None,
    ) -> "WorkflowDataFrame":
        params: Dict[str, Any] = dict(replace=replace)
        if n is not None:
            params["n"] = n
        if frac is not None:
            params["frac"] = frac
        if seed is not None:
            params["seed"] = seed
        return self._add_process(Sample, params=params)

    def take(
        self, n: int, presort: str = "", na_position: str = "last"
    ) -> "WorkflowDataFrame":
        return self._add_process(
            Take,
            params=dict(n=n, presort=presort, na_position=na_position),
            partition_spec=self._pending_partition,
        )

    def select(
        self,
        *columns: Union[str, ColumnExpr],
        where: Optional[ColumnExpr] = None,
        having: Optional[ColumnExpr] = None,
        distinct: bool = False,
    ) -> "WorkflowDataFrame":
        from fugue_tpu.column.expressions import col as _col

        cols = SelectColumns(
            *[_col(c) if isinstance(c, str) else c for c in columns],
            arg_distinct=distinct,
        )
        return self._add_process(
            Select, params=dict(columns=cols, where=where, having=having)
        )

    def filter(self, condition: ColumnExpr) -> "WorkflowDataFrame":
        return self._add_process(Filter, params=dict(condition=condition))

    def assign(self, **columns: Any) -> "WorkflowDataFrame":
        from fugue_tpu.column.expressions import lit

        cols = [
            (v if isinstance(v, ColumnExpr) else lit(v)).alias(k)
            for k, v in columns.items()
        ]
        return self._add_process(Assign, params=dict(columns=cols))

    def aggregate(self, **agg_kwcols: ColumnExpr) -> "WorkflowDataFrame":
        cols = [v.alias(k) for k, v in agg_kwcols.items()]
        return self._add_process(
            Aggregate,
            params=dict(columns=cols),
            partition_spec=self._pending_partition,
        )

    # ---- schema ops ------------------------------------------------------
    def rename(self, *args: Dict[str, str], **kwargs: str) -> "WorkflowDataFrame":
        columns: Dict[str, str] = {}
        for a in args:
            columns.update(a)
        columns.update(kwargs)
        return self._add_process(Rename, params=dict(columns=columns))

    def alter_columns(self, columns: Any) -> "WorkflowDataFrame":
        return self._add_process(AlterColumns, params=dict(columns=str(columns)))

    def drop(self, columns: List[str], if_exists: bool = False) -> "WorkflowDataFrame":
        return self._add_process(
            DropColumns, params=dict(columns=columns, if_exists=if_exists)
        )

    def __getitem__(self, columns: List[Any]) -> "WorkflowDataFrame":
        return self._add_process(SelectColumnsP, params=dict(columns=columns))

    # ---- zip -------------------------------------------------------------
    def zip(
        self,
        *dfs: "WorkflowDataFrame",
        how: str = "inner",
        partition: Any = None,
        temp_path: Optional[str] = None,
        to_file_threshold: int = -1,
    ) -> "WorkflowDataFrame":
        return self._workflow.zip(
            self, *dfs, how=how,
            partition=partition or self._pending_partition,
            temp_path=temp_path, to_file_threshold=to_file_threshold,
        )

    # ---- checkpoints / persist / broadcast ------------------------------
    def persist(self) -> "WorkflowDataFrame":
        self._task.checkpoint = WeakCheckpoint(lazy=False)
        return self

    def weak_checkpoint(self, lazy: bool = False, **kwargs: Any) -> "WorkflowDataFrame":
        self._task.checkpoint = WeakCheckpoint(lazy=lazy, **kwargs)
        return self

    def checkpoint(self, **kwargs: Any) -> "WorkflowDataFrame":
        # non-deterministic strong checkpoint lives in the per-run TEMP dir
        # (cleaned up after run); only deterministic ones are permanent
        self._task.checkpoint = StrongCheckpoint(
            obj_id=str(uuid4()), deterministic=False, permanent=False, **kwargs
        )
        return self

    def strong_checkpoint(self, **kwargs: Any) -> "WorkflowDataFrame":
        return self.checkpoint(**kwargs)

    def deterministic_checkpoint(
        self, namespace: Any = None, **kwargs: Any
    ) -> "WorkflowDataFrame":
        self._task.checkpoint = StrongCheckpoint(
            obj_id=self._task.__uuid__(),
            deterministic=True,
            permanent=True,
            namespace=namespace,
            **kwargs,
        )
        return self

    def broadcast(self) -> "WorkflowDataFrame":
        self._task.broadcast_result = True
        return self

    # ---- fault tolerance -------------------------------------------------
    def fault_tolerant(
        self,
        max_attempts: Optional[int] = None,
        backoff: Optional[float] = None,
        jitter: Optional[float] = None,
        timeout: Optional[float] = None,
        retry_on: Any = None,
    ) -> "WorkflowDataFrame":
        """Per-task override of the workflow fault policy
        (``fugue.workflow.retry.*`` / ``fugue.workflow.timeout``):
        retry the task producing THIS dataframe up to ``max_attempts``
        times on transient errors with exponential ``backoff`` (+
        ``jitter``), abandon it after ``timeout`` seconds of wall clock
        (parallel runner), and additionally treat the ``retry_on``
        exception types as transient."""
        if isinstance(retry_on, type):  # a bare exception class is fine
            retry_on = (retry_on,)
        ov = dict(self._task.fault_override or {})
        for k, v in (
            ("max_attempts", max_attempts),
            ("backoff", backoff),
            ("jitter", jitter),
            ("timeout", timeout),
            ("retry_on", None if retry_on is None else tuple(retry_on)),
        ):
            if v is not None:
                ov[k] = v
        self._task.fault_override = ov
        return self

    # ---- yields ----------------------------------------------------------
    def yield_dataframe_as(self, name: str, as_local: bool = False) -> None:
        y = YieldedDataFrame(self._task.__uuid__())
        self._task.yields.append(y)
        self._task.yield_as_local = as_local
        self._workflow.register_yield(name, y)

    def yield_file_as(self, name: str, **kwargs: Any) -> None:
        if not isinstance(self._task.checkpoint, StrongCheckpoint):
            # reference workflow.py:1006: a RANDOM namespace per DAG build =
            # permanent but effectively non-deterministic checkpoint, so a
            # rebuilt workflow with different data never serves stale yields
            # (task uuids hash dataframes weakly); an EXPLICIT deterministic
            # checkpoint before the yield opts back into skip-on-rerun
            kwargs.setdefault("namespace", str(uuid4()))
            self._task.checkpoint = StrongCheckpoint(
                obj_id=self._task.__uuid__(), deterministic=True, permanent=True,
                **kwargs,
            )
        y = PhysicalYielded(self._task.__uuid__(), "file")
        self._task.checkpoint.yielded = y  # type: ignore
        self._workflow.register_yield(name, y)

    def yield_table_as(self, name: str, **kwargs: Any) -> None:
        if not isinstance(self._task.checkpoint, TableCheckpoint):
            # same random-namespace guard as yield_file_as
            kwargs.setdefault("namespace", str(uuid4()))
            self._task.checkpoint = TableCheckpoint(
                obj_id=self._task.__uuid__(), deterministic=True, **kwargs
            )
        y = PhysicalYielded(self._task.__uuid__(), "table")
        self._task.checkpoint.yielded = y  # type: ignore
        self._workflow.register_yield(name, y)

    # ---- io / output sugar ----------------------------------------------
    def save(
        self,
        path: str,
        fmt: str = "",
        mode: str = "overwrite",
        partition: Any = None,
        single: bool = False,
        **kwargs: Any,
    ) -> None:
        task = OutputTask(
            Save,
            params=dict(path=path, fmt=fmt, mode=mode, single=single, params=kwargs),
            partition_spec=PartitionSpec(partition or self._pending_partition),
            input_tasks=[self._task],
        )
        self._workflow.add(task)

    def save_and_use(
        self,
        path: str,
        fmt: str = "",
        mode: str = "overwrite",
        partition: Any = None,
        single: bool = False,
        **kwargs: Any,
    ) -> "WorkflowDataFrame":
        return self._add_process(
            SaveAndUse,
            params=dict(
                path=path, fmt=fmt, mode=mode, single=single, params=kwargs
            ),
            partition_spec=PartitionSpec(partition or self._pending_partition),
        )

    def show(
        self, n: int = 10, with_count: bool = False, title: Optional[str] = None
    ) -> None:
        task = OutputTask(
            Show,
            params=dict(n=n, with_count=with_count, title=title or ""),
            input_tasks=[self._task],
        )
        self._workflow.add(task)

    def assert_eq(self, *dfs: "WorkflowDataFrame", **params: Any) -> None:
        self._workflow.assert_eq(self, *dfs, **params)

    def assert_not_eq(self, *dfs: "WorkflowDataFrame", **params: Any) -> None:
        self._workflow.assert_not_eq(self, *dfs, **params)

    # ---- internals -------------------------------------------------------
    def _add_process(
        self,
        ext: Any,
        params: Any = None,
        partition_spec: Optional[PartitionSpec] = None,
    ) -> "WorkflowDataFrame":
        task = ProcessTask(
            ext,
            params=params,
            partition_spec=partition_spec or PartitionSpec(),
            input_tasks=[self._task],
        )
        return self._workflow.add(task)


class FugueWorkflow:
    """Build and run a workflow DAG (reference workflow.py:1499)."""

    def __init__(self, compile_conf: Any = None):
        self._tasks: List[FugueTask] = []
        self._yields: Dict[str, Yielded] = {}
        self._conf = ParamDict(FUGUE_GLOBAL_CONF)
        self._conf.update(ParamDict(compile_conf))
        self._computed = False
        self._last_df: Optional[WorkflowDataFrame] = None
        # the most recent profiled run's RunProfile (None otherwise) —
        # run()'s finalize reads it for the slow-query top-tasks block
        self._last_run_profile: Any = None

    @property
    def yields(self) -> Dict[str, Yielded]:
        return self._yields

    @property
    def tasks(self) -> List[FugueTask]:
        """The DAG's tasks in build (= dependency) order."""
        return list(self._tasks)

    @property
    def last_df(self) -> Optional[WorkflowDataFrame]:
        return self._last_df

    def register_yield(self, name: str, y: Yielded) -> None:
        assert_or_throw(
            name not in self._yields, ValueError(f"duplicated yield {name}")
        )
        self._yields[name] = y

    def add(self, task: FugueTask) -> WorkflowDataFrame:
        task.callsite = extract_user_callsite(
            self._conf.get(FUGUE_CONF_WORKFLOW_EXCEPTION_INJECT, 3),
            [self._conf.get(FUGUE_CONF_WORKFLOW_EXCEPTION_HIDE, "fugue_tpu.")],
        )
        self._tasks.append(task)
        res = WorkflowDataFrame(self, task)
        if not isinstance(task, OutputTask):
            self._last_df = res
        return res

    # ---- creation --------------------------------------------------------
    def create(
        self, using: Any, schema: Any = None, params: Any = None
    ) -> WorkflowDataFrame:
        import pandas as pd

        if isinstance(using, (DataFrame, pd.DataFrame)):
            # a dataframe input IS the data: identical spec (and uuid) to
            # dag.df(data) — reference builtin_suite.py:106 equivalence
            assert_or_throw(
                params is None or len(ParamDict(params)) == 0,
                ValueError("params not allowed when creating from a dataframe"),
            )
            return self.create_data(using, schema)
        task = CreateTask(using, params=ParamDict(params), schema=schema)
        return self.add(task)

    def df(self, data: Any, schema: Any = None) -> WorkflowDataFrame:
        return self.create_data(data, schema)

    def create_data(self, data: Any, schema: Any = None) -> WorkflowDataFrame:
        if isinstance(data, WorkflowDataFrame):
            assert_or_throw(
                data.workflow is self, ValueError("dataframe from another workflow")
            )
            return data
        task = CreateTask(
            CreateData,
            params=dict(
                data=data, schema=None if schema is None else str(Schema(schema))
            ),
        )
        return self.add(task)

    def load(
        self, path: str, fmt: str = "", columns: Any = None, **kwargs: Any
    ) -> WorkflowDataFrame:
        task = CreateTask(
            Load,
            params=dict(path=path, fmt=fmt, columns=columns, params=kwargs),
        )
        return self.add(task)

    # ---- generic ---------------------------------------------------------
    def process(
        self,
        *dfs: Any,
        using: Any,
        schema: Any = None,
        params: Any = None,
        pre_partition: Any = None,
    ) -> WorkflowDataFrame:
        inputs, names = self._resolve_dfs(*dfs)
        task = ProcessTask(
            using,
            params=ParamDict(params),
            schema=schema,
            partition_spec=PartitionSpec(pre_partition),
            input_tasks=inputs,
            input_names=names,
        )
        return self.add(task)

    def output(
        self, *dfs: Any, using: Any, params: Any = None, pre_partition: Any = None
    ) -> None:
        inputs, names = self._resolve_dfs(*dfs)
        task = OutputTask(
            using,
            params=ParamDict(params),
            partition_spec=PartitionSpec(pre_partition),
            input_tasks=inputs,
            input_names=names,
        )
        self.add(task)

    def transform(self, *dfs: Any, using: Any, **kwargs: Any) -> WorkflowDataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("transform takes 1 df"))
        return self.create_data(dfs[0]).transform(using, **kwargs)

    def out_transform(self, *dfs: Any, using: Any, **kwargs: Any) -> None:
        assert_or_throw(len(dfs) == 1, ValueError("out_transform takes 1 df"))
        self.create_data(dfs[0]).out_transform(using, **kwargs)

    # ---- multi-df ops ----------------------------------------------------
    def join(
        self, *dfs: Any, how: str, on: Optional[List[str]] = None
    ) -> WorkflowDataFrame:
        inputs, names = self._resolve_dfs(*dfs)
        task = ProcessTask(
            RunJoin,
            params=dict(how=how, on=on or []),
            input_tasks=inputs,
            input_names=names,
        )
        return self.add(task)

    def set_op(self, how: str, *dfs: Any, distinct: bool = True) -> WorkflowDataFrame:
        inputs, names = self._resolve_dfs(*dfs)
        task = ProcessTask(
            RunSetOperation,
            params=dict(how=how, distinct=distinct),
            input_tasks=inputs,
            input_names=names,
        )
        return self.add(task)

    def union(self, *dfs: Any, distinct: bool = True) -> WorkflowDataFrame:
        return self.set_op("union", *dfs, distinct=distinct)

    def subtract(self, *dfs: Any, distinct: bool = True) -> WorkflowDataFrame:
        return self.set_op("subtract", *dfs, distinct=distinct)

    def intersect(self, *dfs: Any, distinct: bool = True) -> WorkflowDataFrame:
        return self.set_op("intersect", *dfs, distinct=distinct)

    def zip(
        self,
        *dfs: Any,
        how: str = "inner",
        partition: Any = None,
        temp_path: Optional[str] = None,
        to_file_threshold: int = -1,
    ) -> WorkflowDataFrame:
        inputs, names = self._resolve_dfs(*dfs)
        task = ProcessTask(
            Zip,
            params=dict(
                how=how, temp_path=temp_path, to_file_threshold=to_file_threshold
            ),
            partition_spec=PartitionSpec(partition),
            input_tasks=inputs,
            input_names=names,
        )
        return self.add(task)

    def select(
        self,
        *statements: Any,
        statement: Any = None,
        dfs: Optional[Dict[str, Any]] = None,
        dialect: Optional[str] = None,
    ) -> WorkflowDataFrame:
        """Raw SQL SELECT via the engine's SQLEngine. Accepts either one
        statement (positional or ``statement=``) plus ``dfs={name: df}``,
        or the reference's interleaved form mixing fragments and
        dataframes::

            dag.select("SELECT k, SUM(x) AS s FROM", df, "GROUP BY k")
        """
        if statement is not None:
            assert_or_throw(
                len(statements) == 0,
                ValueError("pass the statement positionally OR by keyword"),
            )
            statements = (statement,)
        if len(statements) == 1 and isinstance(
            statements[0], (str, StructuredRawSQL)
        ):
            statement = statements[0]
        else:
            from fugue_tpu.collections.sql import interleave_sql

            parts, inline = interleave_sql(statements)
            statement = StructuredRawSQL(parts, dialect=dialect)
            dfs = {**(dfs or {}), **inline}
        named = {k: self.create_data(v) for k, v in (dfs or {}).items()}
        inputs = [v.task for v in named.values()]
        names = list(named.keys())
        if isinstance(statement, str):
            statement = StructuredRawSQL([(False, statement)], dialect=dialect)
        task = ProcessTask(
            RunSQLSelect,
            params=dict(statement=statement),
            input_tasks=inputs,
            input_names=names if len(names) > 0 else None,
        )
        return self.add(task)

    def assert_eq(self, *dfs: Any, **params: Any) -> None:
        self.output(*dfs, using=AssertEqFunc, params=params)

    def assert_not_eq(self, *dfs: Any, **params: Any) -> None:
        self.output(*dfs, using=AssertNotEqFunc, params=params)

    def show(
        self, *dfs: Any, n: int = 10, with_count: bool = False,
        title: Optional[str] = None,
    ) -> None:
        self.output(
            *dfs, using=Show, params=dict(n=n, with_count=with_count,
                                          title=title or ""),
        )

    # ---- static analysis -------------------------------------------------
    def analyze(
        self,
        conf: Any = None,
        engine: Any = None,
        exclude_lint_only: bool = False,
    ) -> List[Any]:
        """Statically analyze the built (unexecuted) DAG and return the
        list of :class:`~fugue_tpu.analysis.Diagnostic` findings, most
        severe first — stable-coded rules over schemas, partition specs,
        conf keys and predicted engine behavior. Nothing executes.

        With no ``engine``, every rule scope runs (lint mode); pass the
        target engine — a live instance or the same name/spec ``run()``
        accepts (e.g. ``"jax"``) — to narrow engine-specific rules to the
        actual backend."""
        from fugue_tpu.analysis import Analyzer

        if engine is not None and not hasattr(engine, "conf"):
            # an engine NAME/spec, as run() accepts: resolve it the same
            # way — analyze(engine="jax") must not silently degrade to a
            # generic-only (false-clean) report
            engine = make_execution_engine(engine, conf)
        merged = ParamDict(self._conf)
        # a live engine brings its own conf (row_bucket, memory budget, …);
        # engine-dependent rules must read it, not the global defaults
        engine_conf = getattr(engine, "conf", None)
        if engine_conf is not None:
            merged.update(ParamDict(engine_conf))
        merged.update(ParamDict(conf))
        return Analyzer().analyze(
            self, conf=merged, engine=engine,
            exclude_lint_only=exclude_lint_only,
        )

    def explain(self, conf: Any = None, engine: Any = None) -> Any:
        """EXPLAIN: the static plan report for this DAG — the
        optimizer-rewritten task tree (clone-and-pin dry run; this
        workflow is untouched) with applied rewrites, propagated
        schemas and estimated device bytes, as a text tree
        (``.to_text()``) and JSON (``.to_dict()``). Nothing executes.
        ``engine`` accepts a live instance or the same name/spec
        ``run()`` accepts."""
        from fugue_tpu.analysis.explain import explain_workflow

        if engine is not None and not hasattr(engine, "conf"):
            engine = make_execution_engine(engine, conf)
        return explain_workflow(self, conf=conf, engine=engine)

    def _pre_run_analysis(self, e: Any, run_conf: Any = None) -> None:
        """The ``fugue.analysis`` gate at the top of ``run()``: ``off``
        skips, ``warn`` (default) logs findings and proceeds, ``error``
        raises :class:`WorkflowAnalysisError` before any task executes
        when error-level diagnostics exist. The analyzer itself is
        sandboxed — an internal analyzer failure never blocks a run."""
        # precedence: run/engine conf > workflow compile conf > default.
        # run() hands us its RAW conf argument, so an explicitly passed
        # run-level value always wins — even one equal to the default
        # (e.g. run-level "warn" relaxing a compile-level "error"). Only
        # the merged engine conf inherits the global default, so there an
        # inherited-default value is "not set" and yields to an explicit
        # compile-conf override.
        from fugue_tpu.constants import conf_default

        default = str(conf_default(FUGUE_CONF_ANALYSIS))
        raw_run = ParamDict(run_conf)
        e_val = str(e.conf.get(FUGUE_CONF_ANALYSIS, default))
        c_val = str(self._conf.get(FUGUE_CONF_ANALYSIS, default))
        if FUGUE_CONF_ANALYSIS in raw_run:
            mode = str(raw_run[FUGUE_CONF_ANALYSIS]).strip().lower()
        else:
            mode = (
                c_val if e_val == default and c_val != default else e_val
            ).strip().lower()
        if mode in ("off", "false", "0", "none", ""):
            return
        if mode not in ("warn", "error", "true", "on", "1"):
            # an unrecognized mode must NOT silently degrade to warn: the
            # user asked for a gate that doesn't exist
            raise ValueError(
                f"invalid {FUGUE_CONF_ANALYSIS} mode {mode!r}: "
                "expected off | warn | error"
            )
        from fugue_tpu.analysis import Severity
        from fugue_tpu.exceptions import WorkflowAnalysisError

        try:
            # lint_only rules (FWF501's optimizer dry-run) are skipped:
            # run() performs the rewrite for real right after this gate
            diags = self.analyze(
                conf=e.conf, engine=e, exclude_lint_only=True
            )
        except WorkflowAnalysisError:  # pragma: no cover - defensive
            raise
        except Exception as ex:  # analyzer bug: log VISIBLY (the user asked
            # for a gate that silently didn't run), never block the run
            e.log.warning(
                "fugue_tpu workflow analysis crashed and was skipped "
                "(the %s gate did not run): %s: %s",
                FUGUE_CONF_ANALYSIS,
                type(ex).__name__,
                ex,
            )
            return
        if mode == "error" and any(
            d.severity is Severity.ERROR for d in diags
        ):
            raise WorkflowAnalysisError(diags)
        for d in diags:
            if d.severity is Severity.ERROR or d.severity is Severity.WARN:
                e.log.warning("fugue_tpu analysis: %s", d.describe())
            else:
                e.log.info("fugue_tpu analysis: %s", d.describe(False))

    # ---- run -------------------------------------------------------------
    def run(
        self,
        engine: Any = None,
        conf: Any = None,
        cancel_token: Any = None,
    ) -> "FugueWorkflowResult":
        """Execute the DAG. ``cancel_token`` (optional): a caller-owned
        :class:`~fugue_tpu.workflow.fault.CancelToken` shared with the
        runner — setting it from another thread cancels the run at the
        next task boundary (how the serving daemon cancels a job
        mid-workflow). The token is a ONE-RUN object: the runner also
        sets it internally when any task fails (that is the sibling
        abort signal), so never reuse a token across runs — a re-run
        with a fired token cancels immediately."""
        e = make_execution_engine(engine, conf)
        # observability: under an AMBIENT trace (a serving daemon's job)
        # this run is one child span; embedded with fugue.obs.enabled it
        # OWNS a per-run trace — exported to fugue.obs.trace_path and
        # slow-query-checked at the end
        opts = obs_options(e.conf)
        owned_trace = None
        if not opts.enabled or tracing_suppressed():
            # suppressed: a serving daemon's job whose request lost the
            # sampling draw — re-drawing here would export uncorrelated
            # traces at ~double the configured rate
            run_scope: Any = nullcontext()
        elif current_span() is not None:
            run_scope = start_span("workflow.run", tasks=len(self._tasks))
        else:
            owned_trace, obs_root = open_trace(
                opts,
                "workflow.run",
                workflow=self.__uuid__()[:12],
                tasks=len(self._tasks),
            )
            run_scope = activate(obs_root)
        try:
            with run_scope:
                return self._run_inner(e, conf, cancel_token)
        finally:
            finalize_trace(
                owned_trace,
                opts,
                fs=e.fs,
                log=e.log,
                registry=e.metrics,
                profile=self._last_run_profile,
                what="workflow.run",
                workflow=self.__uuid__()[:12],
            )

    def _overlay_optimize_conf(self, base_conf: Any) -> ParamDict:
        """The ``fugue.optimize*`` precedence shared by run()'s rewrite
        phase, ``explain()`` and the EXPLAIN ANALYZE tree (they must
        all describe the SAME plan): a base/engine conf value that
        still equals the registered default is "not set", so an
        explicit workflow compile-conf value (``fugue.optimize`` and
        its per-rule keys) wins over the inherited default — the same
        dance as the ``fugue.analysis`` gate."""
        from fugue_tpu.constants import declared_conf_keys

        declared = declared_conf_keys()
        conf = ParamDict(base_conf)
        for k, v in self._conf.items():
            if not isinstance(k, str) or not k.startswith("fugue.optimize"):
                continue
            info = declared.get(k)
            if info is not None and str(conf.get(k, info.default)) == str(
                info.default
            ):
                conf[k] = v
        return conf

    def _optimized_tasks(self, e: Any) -> List[FugueTask]:
        """The task list execution runs: the optimizer's rewrite phase
        (``fugue.optimize``; ``auto`` = jax engines only) over a CLONED
        graph whose uuids are pinned to the original tasks — rewrites
        never change the identities deterministic checkpoints and
        manifest resume key on. The phase is sandboxed: an optimizer
        crash logs a warning and the pristine DAG runs instead."""
        from fugue_tpu.optimize import optimize_enabled, optimize_tasks

        conf = self._overlay_optimize_conf(e.conf)
        # an invalid fugue.optimize mode must raise (the user asked for
        # a gate that doesn't exist), so it is checked OUTSIDE the
        # sandbox below
        if not optimize_enabled(conf, e):
            return list(self._tasks)
        try:
            plan = optimize_tasks(self._tasks, conf=conf, engine=e)
            for note in plan.applied:
                e.log.info("fugue_tpu optimize: %s", note.describe())
            return plan.tasks
        except Exception as ex:
            e.log.warning(
                "fugue_tpu optimize crashed and was skipped (the DAG "
                "runs unoptimized): %s: %s",
                type(ex).__name__,
                ex,
            )
            return list(self._tasks)

    def _run_inner(
        self,
        e: Any,
        conf: Any = None,
        cancel_token: Any = None,
    ) -> "FugueWorkflowResult":
        self._pre_run_analysis(e, run_conf=conf)
        run_tasks = self._optimized_tasks(e)
        execution_id = str(uuid4())
        rpc_server = make_rpc_server(e.conf)
        checkpoint_path = CheckpointPath(e)
        token = cancel_token if cancel_token is not None else CancelToken()
        stats = RunStats(registry=e.metrics)
        ctx = TaskContext(e, rpc_server, checkpoint_path, cancel_token=token)
        base_policy = RetryPolicy.from_conf(e.conf)
        concurrency = e.conf.get(FUGUE_CONF_WORKFLOW_CONCURRENCY, 1)
        # per-task profiler (EXPLAIN ANALYZE): only constructed when
        # fugue.obs.profile is requested (conf gate needs fugue.obs.
        # enabled for the span-derived phase split; the serving daemon's
        # per-request flag forces it) — off means the task wrapper takes
        # the pre-existing path and nothing here allocates
        profiler = None
        if profiling_forced() or profiling_requested(e.conf):
            profiler = Profiler(
                self.__uuid__(), e, concurrency=int(concurrency)
            )
        # checkpoint-backed resume: None unless fugue.workflow.resume is on
        # AND a durable checkpoint dir exists to hold the run manifest
        manifest = RunManifest.from_conf(e, checkpoint_path, self.__uuid__())
        started_rpc = in_ctx = False
        try:
            rpc_server.start()
            started_rpc = True
            e.as_context()
            in_ctx = True
            checkpoint_path.init_temp_path(execution_id)
            index_of = {id(t): i for i, t in enumerate(run_tasks)}
            nodes = [
                TaskNode(
                    t.__uuid__() + f"_{i}",
                    self._make_task_func(
                        t, ctx, base_policy, token, manifest, stats,
                        profiler=profiler,
                    ),
                    [
                        inp.__uuid__() + f"_{index_of[id(inp)]}"
                        for inp in t.inputs
                    ],
                    name=t.name,
                    callsite=t.callsite,
                    timeout=self._task_policy(t, base_policy).timeout,
                )
                for i, t in enumerate(run_tasks)
            ]
            on_complete = None
            if manifest is not None:
                by_node_id = {
                    t.__uuid__() + f"_{i}": t
                    for i, t in enumerate(run_tasks)
                }
                on_complete = lambda node: manifest.mark_complete(  # noqa: E731
                    by_node_id[node.task_id]
                )
            try:
                DAGRunner(concurrency).run(
                    nodes, on_complete=on_complete, cancel_token=token
                )
            except Exception as ex:
                # prune at the outermost point: frames added during
                # propagation through the runner are framework noise too
                if self._conf.get("fugue.workflow.exception.optimize", True):
                    hide = [
                        self._conf.get(
                            FUGUE_CONF_WORKFLOW_EXCEPTION_HIDE, "fugue_tpu."
                        ),
                        "concurrent.futures.",
                        "threading",
                    ]
                    # ``from ex.__cause__`` (not ``from None``): both
                    # suppress the re-raise context, but this one keeps
                    # the cause an aggregated WorkflowRuntimeError chains
                    # to its first failure
                    raise ex.with_traceback(
                        prune_traceback(ex.__traceback__, hide)
                    ) from ex.__cause__
                raise
            self._computed = True
            if manifest is not None:
                manifest.finish()
            # governed jax engines: the memory ledger snapshot rides the
            # run's fault stats (same surface as retries/degradations)
            mem = getattr(e, "memory_stats", None)
            if isinstance(mem, dict) and mem.get("enabled"):
                stats.set_memory(mem)
        finally:
            if in_ctx:
                e.stop_context()
            checkpoint_path.remove_temp_path()
            if started_rpc:
                rpc_server.stop()
        run_profile = None
        if profiler is not None:
            run_profile = self._settle_profile(e, profiler, stats)
        return FugueWorkflowResult(
            self._yields, stats=stats, profile=run_profile
        )

    def _settle_profile(self, e: Any, profiler: Any, stats: Any) -> Any:
        """Finalize a profiled run: merge the span-derived phase split,
        attach the EXPLAIN tree (same deterministic rewrite dry run the
        plan executed, so uuids line up), persist the observation into
        the runtime-statistics store when ``fugue.stats.path`` is set,
        and stash the profile for the slow-query enrichment in
        ``run()``'s finalize. Every step is best-effort — profiling
        must never fail the run it measured."""
        cur = current_span()
        run_profile = profiler.finalize(
            trace=cur.trace if cur is not None else None, stats=stats
        )
        try:
            from fugue_tpu.analysis.explain import explain_tasks

            # the SAME conf overlay _optimized_tasks used: the attached
            # tree must describe the plan this run actually executed
            run_profile.report = explain_tasks(
                self._tasks,
                conf=self._overlay_optimize_conf(e.conf),
                engine=e,
            )
        except Exception as ex:  # plan report is additive
            e.log.warning(
                "fugue_tpu profile: EXPLAIN tree build failed (%s: %s); "
                "the runtime profile stands alone",
                type(ex).__name__, ex,
            )
        try:
            from fugue_tpu.constants import (
                FUGUE_CONF_STATS_HISTORY,
                FUGUE_CONF_STATS_PATH,
                typed_conf_get,
            )

            stats_path = typed_conf_get(e.conf, FUGUE_CONF_STATS_PATH)
            if str(stats_path or "").strip():
                from fugue_tpu.obs.stats_store import get_stats_store

                get_stats_store(
                    e,
                    stats_path,
                    history=typed_conf_get(e.conf, FUGUE_CONF_STATS_HISTORY),
                ).record(self.__uuid__(), run_profile.observation())
        except Exception as ex:  # pragma: no cover - store is best-effort
            e.log.warning(
                "fugue_tpu profile: statistics-store record failed "
                "(%s: %s); the run is unaffected",
                type(ex).__name__, ex,
            )
        self._last_run_profile = run_profile
        return run_profile

    def _task_policy(self, task: FugueTask, base: RetryPolicy) -> RetryPolicy:
        if not task.fault_override:
            return base
        return base.override(**task.fault_override)

    def _make_task_func(
        self,
        task: FugueTask,
        ctx: TaskContext,
        base_policy: RetryPolicy,
        token: CancelToken,
        manifest: Optional[RunManifest],
        stats: RunStats,
        profiler: Any = None,
    ) -> Callable:
        policy = self._task_policy(task, base_policy)

        def attempt(inputs: List[Any]) -> Any:
            # fault-injection site INSIDE the attempt loop: "task" faults
            # fire per attempt, so nth-invocation plans exercise retries
            from fugue_tpu.testing.faults import fault_point

            fault_point("task", task.name)
            return task.execute(ctx, inputs)

        def run_task(inputs: List[Any]) -> Any:
            # one span per TaskNode execution (the runner worker thread
            # inherits the run's context via DAGRunner._spawn); attempt
            # spans nest under it from execute_with_policy. With the
            # profiler off (None), this is the pre-existing path plus
            # one is-None check — nothing is allocated.
            with start_span(
                "task", task=task.name, type=task.task_type
            ) as sp:
                rec = None if profiler is None else profiler.begin(task, sp)
                # NULL_CM when off: the shared no-op, nothing allocated
                with NULL_CM if rec is None else task_scope(rec):
                    return _execute(inputs, rec)

        def _execute(inputs: List[Any], rec: Any) -> Any:
            try:
                # manifest resume is OBSERVED here but served by the
                # task's own checkpoint short-circuit inside
                # execute(): validations still fire and there is
                # only one load path
                if manifest is not None and manifest.can_resume(
                    task, ctx, stats=stats
                ):
                    stats.note_resumed(task.name)
                # each attempt inside holds the engine's dispatch
                # guard (task_execution_lock): shared-engine device
                # programs serialize per attempt, host phases overlap
                result = execute_with_policy(
                    lambda: attempt(inputs),
                    policy,
                    engine=ctx.engine,
                    token=token,
                    task_name=task.name,
                    stats=stats,
                    log=ctx.engine.log,
                )
            except Exception as ex:
                if rec is not None:
                    profiler.finish(rec, inputs, None, error=ex)
                self._reraise_with_callsite(task, ex)
            if rec is not None:
                profiler.finish(rec, inputs, result)
            return result

        return run_task

    def _reraise_with_callsite(self, task: FugueTask, ex: Exception) -> None:
        """Attach the failing task's name and the USER's workflow callsite
        to the error, so a failing transform points at the line that
        defined it rather than runner internals (notes survive retry
        wrapping, pruning and aggregation)."""
        note = f"in task {task.name}"
        if task.callsite:
            note += ", defined at:\n" + "\n".join(task.callsite)
        add_error_note(ex, note)
        raise ex

    def __enter__(self) -> "FugueWorkflow":
        return self

    def __exit__(self, exc_type: Any, *args: Any) -> None:
        if exc_type is None:
            self.run()

    def __uuid__(self) -> str:
        return to_uuid([t.__uuid__() for t in self._tasks])

    def _resolve_dfs(self, *dfs: Any) -> Any:
        if len(dfs) == 1 and isinstance(dfs[0], dict):
            named = {k: self.create_data(v) for k, v in dfs[0].items()}
            return [v.task for v in named.values()], list(named.keys())
        inputs = [self.create_data(d).task for d in dfs]
        return inputs, None


class FugueWorkflowResult:
    """Run result: access yielded dataframes (reference workflow.py:1609)
    plus the run's fault-tolerance stats (retries/recoveries/degradations
    per task and manifest-resumed tasks) and — for profiled runs — the
    per-task runtime profile (EXPLAIN ANALYZE)."""

    def __init__(
        self,
        yields: Dict[str, Yielded],
        stats: Any = None,
        profile: Any = None,
    ):
        self._yields = yields
        self._stats = stats
        self._profile = profile

    @property
    def yields(self) -> Dict[str, Yielded]:
        return self._yields

    @property
    def fault_stats(self) -> Dict[str, Any]:
        return self._stats.as_dict() if self._stats is not None else {}

    def profile(self) -> Any:
        """The run's :class:`~fugue_tpu.obs.profile.RunProfile` — per
        task rows in/out, device bytes, wall/compile/execute/transfer
        split, queue wait, retries and cache events, with the EXPLAIN
        plan tree attached (``.to_text()`` renders EXPLAIN ANALYZE).
        None unless the run was profiled (``fugue.obs.profile`` with
        ``fugue.obs.enabled``, or the serve ``profile`` flag)."""
        return self._profile

    def __getitem__(self, name: str) -> Any:
        y = self._yields[name]
        if isinstance(y, YieldedDataFrame):
            return y.result
        return y
