"""Fault-tolerance primitives for workflow execution.

Three pieces:

- :func:`classify_error` — the transient/oom/fatal triage that decides
  whether a task failure is worth retrying. Spark retries every task
  failure and relies on lineage; we are single-controller, so the
  classifier is the line between "the storage/transport hiccuped, run it
  again" and "the workflow is wrong, fail NOW with the original error".
- :class:`RetryPolicy` — per-task retry/backoff/timeout knobs, built
  from conf (``fugue.workflow.retry.*`` / ``fugue.workflow.timeout``)
  and overridable per task via ``WorkflowDataFrame.fault_tolerant``.
- :func:`execute_with_policy` — the attempt loop the workflow wraps
  around every task: classify, degrade device-OOM onto the host tier
  (jax engine) without consuming a retry, back off with jitter, honor
  cooperative cancellation, and report retries/recoveries/degradations
  to the active fault plan's counters.
"""

import random
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Optional, Tuple

from fugue_tpu.constants import (
    FUGUE_CONF_WORKFLOW_RETRY_BACKOFF,
    FUGUE_CONF_WORKFLOW_RETRY_JITTER,
    FUGUE_CONF_WORKFLOW_RETRY_MAX_ATTEMPTS,
    FUGUE_CONF_WORKFLOW_TIMEOUT,
)
from fugue_tpu.exceptions import (
    FugueError,
    FugueWorkflowError,
    TaskCancelledError,
)
from fugue_tpu.obs.trace import start_span
from fugue_tpu.testing.faults import active_plan
from fugue_tpu.testing.locktrace import tracked_lock

TRANSIENT = "transient"
OOM = "oom"
FATAL = "fatal"
DEVICE_LOST = "device_lost"

# exception class NAMES treated as transient: transport/storage errors
# raised by backends we don't import (fsspec, gcsfs, requests, grpc) —
# matching by name keeps the classifier dependency-free.
_TRANSIENT_NAMES = (
    "TimeoutError",
    "ConnectTimeoutError",
    "ReadTimeoutError",
    "ServiceUnavailableError",
    "TemporaryError",
    "RemoteDisconnected",
    "IncompleteRead",
    "RetriableError",
    "TransientError",
    # a lost optimistic lake commit (fugue_tpu/lake): the conflict is
    # resolved by re-reading the new head and retrying — the textbook
    # transient — and it must NOT fall into the FileExistsError->FATAL
    # branch its underlying CAS loses with
    "LakeCommitConflict",
)
# status tokens in error text that mark a transient RPC/XLA transport
# failure (grpc/absl status vocabulary)
_TRANSIENT_TOKENS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED")
_OOM_TOKENS = ("RESOURCE_EXHAUSTED", "out of memory", "Out of memory")
# status tokens marking a DEAD device (vs a slow/unreachable one): the
# runtime proved data on that device is gone, so a blind retry on the
# same mesh replays the same failure — the engine must rebuild a
# degraded mesh first (DEVICE_LOST triage)
_DEVICE_LOST_TOKENS = (
    "DATA_LOSS",
    "device lost",
    "DEVICE_LOST",
    "is in an error state",
)


def _is_status_typed(ex: BaseException) -> bool:
    """Only transport/runtime error TYPES may speak the absl status
    vocabulary: a plain RuntimeError("... ABORTED ...") from user code is
    deterministic and must not replay side effects. The same discipline
    covers grpc transports and jaxlib's XlaRuntimeError (device errors
    surface there with status-prefixed text)."""
    name = type(ex).__name__
    mod = type(ex).__module__
    return (
        name.endswith(("RpcError", "StatusError"))
        or name == "XlaRuntimeError"
        or "grpc" in mod
        or "jaxlib" in mod
    )


def classify_error(ex: BaseException, retry_on: Tuple[type, ...] = ()) -> str:
    """Triage an execution error.

    - ``oom``: a device allocation failure (jax ``RESOURCE_EXHAUSTED``) —
      eligible for host-tier degradation, then retry.
    - ``transient``: fs/IO errors and RPC transport errors — retry with
      backoff.
    - ``device_lost``: an XLA DATA_LOSS / device-dead error — a blind
      retry replays the failure; the executor must first rebuild a
      degraded mesh (``engine.recover_from_device_loss``), then retry.
    - ``fatal``: everything else — deterministic failures (schema &
      validation errors, user code bugs) re-raise immediately; retrying
      them only hides the first, best traceback.
    """
    if isinstance(ex, retry_on):
        return TRANSIENT
    # an error carrying a server backoff hint IS the server saying
    # "transient, come back later" — the serving daemon's 503/429
    # backpressure answers (ServeAPIError, AdmissionError) land here
    if getattr(ex, "retry_after", None) is not None:
        return TRANSIENT
    name = type(ex).__name__
    text = str(ex)
    if isinstance(ex, MemoryError):
        return OOM
    if name == "XlaRuntimeError" or "jaxlib" in type(ex).__module__:
        if any(t in text for t in _OOM_TOKENS):
            return OOM
    # DEVICE_LOST outranks the transient tokens: a DATA_LOSS message can
    # also mention the aborted collective, and the dead-device verdict
    # must win or the retry loop spins against a broken mesh
    if any(t in text for t in _DEVICE_LOST_TOKENS) and _is_status_typed(ex):
        return DEVICE_LOST
    # framework errors are deliberate: never retry (validation, schema,
    # compile problems are deterministic by construction)
    if isinstance(ex, (FugueError, FugueWorkflowError)):
        return FATAL
    if isinstance(ex, (ConnectionError, BrokenPipeError, TimeoutError)):
        return TRANSIENT
    if isinstance(ex, OSError):
        # a missing/denied path is deterministic; other OS errors (EIO,
        # network filesystems, stale handles) are the storage hiccups
        # this layer exists for
        if isinstance(
            ex,
            (
                FileNotFoundError,
                FileExistsError,
                IsADirectoryError,
                NotADirectoryError,
                PermissionError,
            ),
        ):
            return FATAL
        return TRANSIENT
    if name in _TRANSIENT_NAMES:
        return TRANSIENT
    if any(t in text for t in _TRANSIENT_TOKENS) and _is_status_typed(ex):
        # a transient status (UNAVAILABLE / DEADLINE_EXCEEDED / ABORTED)
        # on a real transport or XLA runtime type: a slow or unreachable
        # peer, e.g. a hung collective — retry with backoff
        return TRANSIENT
    return FATAL


class RetryPolicy:
    """Immutable per-task fault policy. ``max_attempts`` counts the first
    run (1 = no retry); ``backoff`` is the base exponential delay in
    seconds, ``jitter`` a multiplicative random fraction on top;
    ``timeout`` the per-task wall clock (0 = unlimited) enforced by the
    parallel runner; ``retry_on`` extra exception types to treat as
    transient for this task."""

    __slots__ = ("max_attempts", "backoff", "jitter", "timeout", "retry_on")

    def __init__(
        self,
        max_attempts: int = 1,
        backoff: float = 0.1,
        jitter: float = 0.1,
        timeout: float = 0.0,
        retry_on: Any = (),
    ):
        self.max_attempts = max(1, int(max_attempts))
        self.backoff = max(0.0, float(backoff))
        self.jitter = max(0.0, float(jitter))
        self.timeout = max(0.0, float(timeout))
        # accept a bare exception class as well as an iterable of them
        self.retry_on = (
            (retry_on,) if isinstance(retry_on, type) else tuple(retry_on)
        )

    @staticmethod
    def from_conf(conf: Any) -> "RetryPolicy":
        return RetryPolicy(
            max_attempts=conf.get(FUGUE_CONF_WORKFLOW_RETRY_MAX_ATTEMPTS, 1),
            backoff=conf.get(FUGUE_CONF_WORKFLOW_RETRY_BACKOFF, 0.1),
            jitter=conf.get(FUGUE_CONF_WORKFLOW_RETRY_JITTER, 0.1),
            timeout=conf.get(FUGUE_CONF_WORKFLOW_TIMEOUT, 0.0),
        )

    def override(
        self,
        max_attempts: Optional[int] = None,
        backoff: Optional[float] = None,
        jitter: Optional[float] = None,
        timeout: Optional[float] = None,
        retry_on: Any = None,
    ) -> "RetryPolicy":
        return RetryPolicy(
            max_attempts=(
                self.max_attempts if max_attempts is None else max_attempts
            ),
            backoff=self.backoff if backoff is None else backoff,
            jitter=self.jitter if jitter is None else jitter,
            timeout=self.timeout if timeout is None else timeout,
            retry_on=self.retry_on if retry_on is None else retry_on,
        )

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        base = self.backoff * (2 ** (attempt - 1))
        if self.jitter > 0:
            base *= 1.0 + rng.random() * self.jitter
        return base

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"RetryPolicy(max_attempts={self.max_attempts}, "
            f"backoff={self.backoff}, jitter={self.jitter}, "
            f"timeout={self.timeout})"
        )


class CancelToken:
    """Cooperative cancellation: the runner sets it when a sibling fails
    or times out; cancellation points (task launch, backoff sleeps, user
    extensions via ``TaskContext``) observe it and abort early.

    ``on_poll`` (optional) fires on every cancellation check: each poll
    proves the holder is alive between device dispatches, so liveness
    watchers (the serving daemon's heartbeat supervisor) ride on the
    checks the fault layer already makes at task boundaries instead of
    instrumenting every execution path."""

    def __init__(self) -> None:
        self._event = threading.Event()
        self.on_poll: Optional[Callable[[], None]] = None

    def cancel(self) -> None:
        self._event.set()

    def _polled(self) -> None:
        cb = self.on_poll
        if cb is not None:
            try:
                cb()
            except Exception:  # pragma: no cover - observer must not break
                pass

    @property
    def cancelled(self) -> bool:
        self._polled()
        return self._event.is_set()

    def raise_if_cancelled(self) -> None:
        self._polled()
        if self._event.is_set():
            raise TaskCancelledError("cancelled by a failing sibling task")

    def wait(self, seconds: float) -> bool:
        """Sleep up to ``seconds``; True if cancelled meanwhile."""
        self._polled()
        return self._event.wait(seconds)


class RunStats:
    """Per-run fault-tolerance observability, exposed on the workflow
    result: retries/recoveries/degradations per task plus the tasks the
    run manifest marked resumable (completed by a prior run with a
    durable artifact still present at check time — the actual load is
    served by the task's checkpoint short-circuit).

    With a ``registry`` (the run engine's metrics registry) every event
    is ALSO mirrored — unlabeled by task, to bound cardinality — onto
    ``fugue_workflow_fault_events_total{event=...}``, so a long-lived
    process's Prometheus scrape aggregates what the per-run dicts show
    one run at a time. The dict read shapes are unchanged."""

    def __init__(self, registry: Any = None) -> None:
        self._lock = tracked_lock("workflow.fault.RunStats._lock")
        self.retries: dict = {}
        self.recoveries: dict = {}
        self.degradations: dict = {}
        # degraded-mesh rebuilds after a lost device (per task)
        self.device_recoveries: dict = {}
        self.resumed: list = []
        # manifest artifacts that failed size/sha256 verification on
        # resume and were recomputed instead of loaded
        self.integrity_rejected: dict = {}
        # snapshot of the jax engine's memory-governance ledger at run
        # end (empty for ungoverned engines)
        self.memory: dict = {}
        self._m_events = (
            None
            if registry is None
            else registry.counter(
                "fugue_workflow_fault_events_total",
                "workflow fault-tolerance events across runs "
                "(per-run per-task detail lives on RunStats)",
                ["event"],
            )
        )

    def _bump(self, d: dict, key: str, event: str) -> None:
        with self._lock:
            d[key] = d.get(key, 0) + 1
        if self._m_events is not None:
            self._m_events.labels(event=event).inc()

    def note_retry(self, name: str) -> None:
        self._bump(self.retries, name, "retry")

    def note_recovery(self, name: str) -> None:
        self._bump(self.recoveries, name, "recovery")

    def note_degradation(self, name: str) -> None:
        self._bump(self.degradations, name, "degradation")

    def note_device_recovery(self, name: str) -> None:
        self._bump(self.device_recoveries, name, "device_lost_recovery")

    def note_integrity_rejected(self, name: str) -> None:
        self._bump(self.integrity_rejected, name, "integrity_rejected")

    def note_resumed(self, name: str) -> None:
        with self._lock:
            self.resumed.append(name)
        if self._m_events is not None:
            self._m_events.labels(event="resumed").inc()

    def set_memory(self, snapshot: dict) -> None:
        with self._lock:
            self.memory = dict(snapshot)

    def as_dict(self) -> dict:
        with self._lock:
            return {
                "retries": dict(self.retries),
                "recoveries": dict(self.recoveries),
                "degradations": dict(self.degradations),
                "device_recoveries": dict(self.device_recoveries),
                "resumed": list(self.resumed),
                "integrity_rejected": dict(self.integrity_rejected),
                "memory": dict(self.memory),
            }


def _degrade_ctx(engine: Any) -> Optional[Any]:
    """The engine's host-tier degradation context, or None when the
    engine has no cheaper tier to fall back to."""
    if engine is None or not getattr(engine, "supports_host_degrade", False):
        return None
    return engine.degraded_to_host()


@contextmanager
def engine_dispatch_guard(
    engine: Any, token: Optional[CancelToken]
) -> Any:
    """Hold the engine's ``task_execution_lock`` (device-dispatch
    serialization for engines shared by concurrent workflows — the
    serving daemon) around ONE task attempt; no-op for engines that
    allow concurrent dispatch (lock is None). Scoped to the attempt so
    backoff sleeps and queue time never serialize other tenants, and
    acquisition is CANCELLATION-AWARE: a task cancelled (or expired at
    the job layer) while queued behind a wedged sibling aborts with
    ``TaskCancelledError`` instead of blocking on the lock forever."""
    lock = getattr(engine, "task_execution_lock", None)
    if lock is None:
        yield
        return
    while not lock.acquire(timeout=0.1):
        if token is not None:
            token.raise_if_cancelled()
    try:
        yield
    finally:
        lock.release()


def execute_with_policy(
    fn: Callable[[], Any],
    policy: RetryPolicy,
    *,
    engine: Any = None,
    token: Optional[CancelToken] = None,
    task_name: str = "",
    stats: Optional[RunStats] = None,
    log: Any = None,
) -> Any:
    """Run ``fn`` under ``policy``: transient errors retry with
    exponential backoff + jitter; a device-OOM first re-runs on the
    engine's host tier WITHOUT consuming a retry (capacity degradation is
    not a transient fault — the same attempt deserves a cheaper venue);
    fatal errors and exhausted budgets re-raise the original error.
    Each attempt runs under :func:`engine_dispatch_guard`."""
    rng = random.Random()
    attempt = 0
    while True:
        attempt += 1
        if token is not None:
            token.raise_if_cancelled()
        try:
            # the attempt span covers dispatch-guard queueing AND the
            # attempt body, so a trace shows time queued behind a shared
            # engine separately from the engine's own compile/execute/
            # transfer child spans
            with start_span("task.attempt", attempt=attempt):
                with engine_dispatch_guard(engine, token):
                    result = fn()
            if attempt > 1:
                plan = active_plan()
                if plan is not None:
                    plan.note_recovery("task", task_name)
                if stats is not None:
                    stats.note_recovery(task_name)
            return result
        except TaskCancelledError:
            raise
        except Exception as ex:
            cls = classify_error(ex, policy.retry_on)
            if cls == OOM:
                # feed the measured allocation size back into the memory
                # governor's ledger FIRST: the budget clamps to observed
                # capacity and pressure is relieved, so the degraded
                # re-run (and later admissions) see the truth
                noter = getattr(engine, "note_device_oom", None)
                if noter is not None:
                    try:
                        noter(ex)
                    except Exception:  # pragma: no cover - best effort
                        pass
                degraded = _try_degrade(
                    fn, engine, token, task_name, stats, log, ex
                )
                if degraded is not None:
                    return degraded[0]
                # degradation unsupported or failed: treat as transient
                cls = TRANSIENT
            elif cls == DEVICE_LOST:
                # rebuild a degraded mesh from the survivors and re-place
                # recoverable frames BEFORE retrying: the retry then runs
                # on healthy hardware and consumes an ordinary attempt
                # under the existing backoff budget. Unrecoverable = the
                # engine can't rebuild (no survivors, recovery disabled,
                # no recovery hook) -> fatal, the owning query fails with
                # the original device error — never the process.
                recoverer = getattr(engine, "recover_from_device_loss", None)
                recovered = False
                if recoverer is not None:
                    try:
                        recovered = bool(recoverer(ex))
                    except Exception as rex:
                        if log is not None:
                            log.warning(
                                "fugue_tpu degraded-mesh recovery for task "
                                "%s failed with %s: %s (original device "
                                "error: %s)",
                                task_name, type(rex).__name__, rex, ex,
                            )
                if recovered:
                    plan = active_plan()
                    if plan is not None:
                        plan.note_device_recovery("task", task_name)
                    if stats is not None:
                        stats.note_device_recovery(task_name)
                    if log is not None:
                        log.warning(
                            "fugue_tpu task %s lost a device (%s); mesh "
                            "rebuilt on survivors, retrying",
                            task_name, ex,
                        )
                    cls = TRANSIENT
                else:
                    cls = FATAL
            if cls == FATAL or attempt >= policy.max_attempts:
                raise
            plan = active_plan()
            if plan is not None:
                plan.note_retry("task", task_name)
            if stats is not None:
                stats.note_retry(task_name)
            if log is not None:
                log.info(
                    "fugue_tpu retry %d/%d of task %s after %s: %s",
                    attempt,
                    policy.max_attempts,
                    task_name,
                    type(ex).__name__,
                    ex,
                )
            delay = policy.delay(attempt, rng)
            if token is not None:
                if token.wait(delay):
                    token.raise_if_cancelled()
            elif delay > 0:
                time.sleep(delay)


def _try_degrade(
    fn: Callable[[], Any],
    engine: Any,
    token: Optional[CancelToken],
    task_name: str,
    stats: Optional[RunStats],
    log: Any,
    cause: BaseException,
) -> Optional[Tuple[Any]]:
    """One host-tier re-run after a device OOM. Returns a 1-tuple with
    the result on success (so a None result is distinguishable), or None
    when the engine can't degrade or the degraded run failed too."""
    ctx = _degrade_ctx(engine)
    if ctx is None:
        return None
    if token is not None:
        token.raise_if_cancelled()
    if log is not None:
        log.warning(
            "fugue_tpu task %s hit device OOM (%s); degrading to host tier",
            task_name,
            cause,
        )
    try:
        with start_span("task.attempt", tier="host", degraded=True):
            with ctx, engine_dispatch_guard(engine, token):
                result = fn()
    except TaskCancelledError:
        raise
    except Exception as degraded_ex:
        # the host-tier run failed DIFFERENTLY: surface it — the caller
        # re-raises the original OOM and this may be the real bug
        if log is not None:
            log.warning(
                "fugue_tpu host-tier degraded run of task %s failed with "
                "%s: %s (original device error: %s)",
                task_name,
                type(degraded_ex).__name__,
                degraded_ex,
                cause,
            )
        from fugue_tpu.utils.exception import add_error_note

        add_error_note(
            cause,
            "host-tier degraded re-run also failed: "
            f"{type(degraded_ex).__name__}: {degraded_ex}",
        )
        return None
    plan = active_plan()
    if plan is not None:
        plan.note_degradation("task", task_name)
    if stats is not None:
        stats.note_degradation(task_name)
    if hasattr(engine, "_count_fallback"):
        engine._count_fallback("oom_degrade", task_name)
    return (result,)
