from fugue_tpu.workflow.workflow import (
    FugueWorkflow,
    FugueWorkflowResult,
    WorkflowDataFrame,
)
from fugue_tpu.workflow.module import module
from fugue_tpu.workflow.checkpoint import (
    Checkpoint,
    CheckpointPath,
    StrongCheckpoint,
    WeakCheckpoint,
)
