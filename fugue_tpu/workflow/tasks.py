"""Workflow tasks: uuid-deterministic units executed by the DAG runner
(reference fugue/workflow/_tasks.py:85-347 behavior on our own runner)."""

from typing import Any, Callable, Dict, List, Optional

from fugue_tpu.extensions.validation import (
    validate_input_schema,
    validate_partition_spec,
)
from fugue_tpu.collections.partition import PartitionSpec
from fugue_tpu.collections.yielded import PhysicalYielded, Yielded
from fugue_tpu.dataframe import DataFrame, DataFrames
from fugue_tpu.dataframe.dataframe import YieldedDataFrame
from fugue_tpu.extensions.convert import (
    _to_creator,
    _to_outputter,
    _to_processor,
)
from fugue_tpu.extensions.interfaces import Creator, Outputter, Processor
from fugue_tpu.obs.profile import note_cache_event
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.utils.hash import to_uuid
from fugue_tpu.utils.params import ParamDict
from fugue_tpu.workflow.checkpoint import Checkpoint


def _ext_uuid(ext: Any) -> str:
    if hasattr(ext, "__uuid__"):
        return ext.__uuid__()
    if isinstance(ext, type):
        return to_uuid(f"{ext.__module__}.{ext.__qualname__}")
    return to_uuid(ext)


class FugueTask:
    """A node in the workflow DAG; identity is deterministic from the spec so
    identical DAGs produce identical task uuids across runs/processes (the
    determinism backbone used by deterministic checkpoints)."""

    def __init__(
        self,
        extension: Any,
        params: Any = None,
        schema: Any = None,
        partition_spec: Optional[PartitionSpec] = None,
        input_tasks: Optional[List["FugueTask"]] = None,
        input_names: Optional[List[str]] = None,
    ):
        self.extension = extension
        self.params = ParamDict(params)  # passed to the extension verbatim
        self.schema = schema  # for interfaceless conversion only
        self.partition_spec = partition_spec or PartitionSpec()
        self.inputs = input_tasks or []
        self.input_names = input_names
        self.checkpoint: Checkpoint = Checkpoint()
        self.broadcast_result = False
        self.yields: List[Yielded] = []
        self.yield_as_local = False
        self.callsite: List[str] = []
        # per-task fault-policy override kwargs (max_attempts/backoff/
        # jitter/timeout/retry_on), resolved against the conf-level
        # RetryPolicy at run time. Execution-only: NOT part of the task
        # uuid (retry settings must not invalidate deterministic
        # checkpoints, same as checkpoint config itself).
        self.fault_override: Optional[Dict[str, Any]] = None
        self._uuid: Optional[str] = None

    def __uuid__(self) -> str:
        if self._uuid is None:
            self._uuid = to_uuid(
                type(self).__name__,
                _ext_uuid(self.extension),
                self._params_uuid(),
                str(self.schema),
                self.partition_spec.__uuid__(),
                [t.__uuid__() for t in self.inputs],
                self.input_names,
            )
        return self._uuid

    def _params_uuid(self) -> Any:
        res: Dict[str, Any] = {}
        for k, v in self.params.items():
            if hasattr(v, "__uuid__"):
                res[k] = v.__uuid__()
            elif isinstance(v, (list, dict, str, int, float, bool, type(None))):
                res[k] = v
            else:
                res[k] = to_uuid(v)
        return res

    @property
    def task_type(self) -> str:
        """``"create"`` / ``"process"`` / ``"output"`` — the task's role in
        the DAG, used by static analysis and display tooling without
        isinstance-ing against concrete task classes."""
        if isinstance(self, CreateTask):
            return "create"
        if isinstance(self, OutputTask):
            return "output"
        return "process"

    @property
    def name(self) -> str:
        # the extension is usually a CLASS (builtins) — use its own name,
        # not "type"; instances/functions fall back to their type/name.
        # This display name keys error reports and fault-injection task
        # sites ("task", "RunTransformer*"), so it must be meaningful.
        ext = self.extension
        if isinstance(ext, type):
            base = ext.__name__
        elif callable(ext) and hasattr(ext, "__name__"):
            base = ext.__name__
        else:
            base = type(ext).__name__
        return f"{base}_{self.__uuid__()[:8]}"

    def execute(self, ctx: "TaskContext", inputs: List[DataFrame]) -> Any:
        raise NotImplementedError  # pragma: no cover

    # ---- shared result handling -----------------------------------------
    def _result_cache(self, ctx: "TaskContext") -> Any:
        """The optimizer's in-memory result tier over deterministic
        checkpoints (``fugue.optimize.result_cache``, opt-in), or None."""
        from fugue_tpu.optimize import cache as _plan_cache

        if not _plan_cache.task_result_cache_enabled(ctx.engine):
            return None
        return _plan_cache

    def _try_skip(self, ctx: "TaskContext") -> Optional[DataFrame]:
        """Deterministic-checkpoint short circuit: reuse the artifact and
        skip compute when an identical DAG already produced it. With
        ``fugue.optimize.result_cache`` on, a process-wide memory tier
        sits in front of the artifact: the previously loaded dataframe
        is served (artifact existence re-verified) without paying the
        parquet decode again."""
        cache = self._result_cache(ctx)
        if cache is not None:
            hit = cache.get_task_result(self, ctx)
            if hit is not None:
                # profiler attribution (thread-local; no-op when off)
                note_cache_event("result", "hit")
                return self._finalize(ctx, hit, run_checkpoint=False)
        cached = self.checkpoint.try_load(ctx.checkpoint_path)
        if cached is None:
            return None
        note_cache_event("checkpoint", "hit")
        if cache is not None:
            cache.put_task_result(self, ctx, cached)
        return self._finalize(ctx, cached, run_checkpoint=False)

    def _finalize(
        self, ctx: "TaskContext", df: DataFrame, run_checkpoint: bool = True
    ) -> DataFrame:
        if run_checkpoint:
            df = self.checkpoint.run(df, ctx.checkpoint_path)
            cache = self._result_cache(ctx)
            if cache is not None:
                cache.put_task_result(self, ctx, df)
        if self.broadcast_result:
            df = ctx.engine.broadcast(df)
        for y in self.yields:
            if isinstance(y, YieldedDataFrame):
                y.set_value(
                    ctx.engine.convert_yield_dataframe(df, self.yield_as_local)
                )
        return df

    def _setup_extension(self, ext: Any, ctx: "TaskContext") -> None:
        ext._params = self.params
        ext._workflow_conf = ctx.engine.conf
        ext._execution_engine = ctx.engine
        ext._partition_spec = self.partition_spec
        ext._rpc_server = ctx.rpc_server


class TaskContext:
    def __init__(
        self,
        engine: Any,
        rpc_server: Any,
        checkpoint_path: Any,
        cancel_token: Any = None,
    ):
        self.engine = engine
        self.rpc_server = rpc_server
        self.checkpoint_path = checkpoint_path
        # cooperative cancellation: long-running extensions may poll
        # ctx.cancel_token.cancelled / raise_if_cancelled() to stop early
        # when a sibling task failed or timed out
        self.cancel_token = cancel_token


class CreateTask(FugueTask):
    """Wrap a Creator (reference _tasks.py:214)."""

    def execute(self, ctx: TaskContext, inputs: List[DataFrame]) -> DataFrame:
        cached = self._try_skip(ctx)
        if cached is not None:
            return cached
        creator = _to_creator(self.extension, self.schema)
        self._setup_extension(creator, ctx)
        df = creator.create()
        return self._finalize(ctx, ctx.engine.to_df(df))


class ProcessTask(FugueTask):
    """Wrap a Processor (reference _tasks.py:243)."""

    def execute(self, ctx: TaskContext, inputs: List[DataFrame]) -> DataFrame:
        # validations are declarations about the WORKFLOW, not the data:
        # they must fire even when the task result is checkpoint-cached.
        # Schemas validate DIRECTLY on the inputs (no conversion) and
        # _make_dfs runs only past the checkpoint check, so a
        # deterministic-cache hit never pays input conversion — EXCEPT a
        # raw (non-DataFrame) input under declared input-schema rules,
        # which has no schema to validate until converted (ADVICE r5 #5)
        processor = _to_processor(self.extension, self.schema)
        self._setup_extension(processor, ctx)
        rules = processor.validation_rules
        validate_partition_spec(rules, self.partition_spec)
        if "input_has" in rules or "input_is" in rules:
            inputs = [
                i if isinstance(i, DataFrame) else ctx.engine.to_df(i)
                for i in inputs
            ]
            for i in inputs:
                validate_input_schema(rules, i.schema)
        cached = self._try_skip(ctx)
        if cached is not None:
            return cached
        df = processor.process(self._make_dfs(ctx, inputs))
        return self._finalize(ctx, ctx.engine.to_df(df))

    def _make_dfs(self, ctx: TaskContext, inputs: List[DataFrame]) -> DataFrames:
        engine_inputs = [ctx.engine.to_df(i) if not isinstance(i, DataFrame) else i
                         for i in inputs]
        if self.input_names is not None:
            return DataFrames(dict(zip(self.input_names, engine_inputs)))
        return DataFrames(engine_inputs)


class OutputTask(FugueTask):
    """Wrap an Outputter (reference _tasks.py:297)."""

    def execute(self, ctx: TaskContext, inputs: List[DataFrame]) -> Optional[DataFrame]:
        outputter = _to_outputter(self.extension)
        self._setup_extension(outputter, ctx)
        validate_partition_spec(outputter.validation_rules, self.partition_spec)
        if self.input_names is not None:
            dfs = DataFrames(dict(zip(self.input_names, inputs)))
        else:
            dfs = DataFrames(inputs)
        for in_df in dfs.values():
            validate_input_schema(outputter.validation_rules, in_df.schema)
        outputter.process(dfs)
        # pass through the first input so dependents can still reference it
        return inputs[0] if len(inputs) > 0 else None
