"""PartitionSpec: the partitioning DSL + partition cursors.

Parity target: reference ``fugue/collections/partition.py:79`` — algorithms
``default|hash|rand|even|coarse``, a ``num`` expression supporting the
``ROWCOUNT``/``CONCURRENCY`` keywords, ``by`` keys and ``presort``, plus the
``"per_row"`` sugar. On the JAX backend these translate to device-placement
reshards over the mesh rather than shuffles (SURVEY §2.10 TPU mapping).
"""

import json
import re
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Tuple

from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.utils.hash import to_uuid

KEYWORD_ROWCOUNT = "ROWCOUNT"
KEYWORD_CONCURRENCY = "CONCURRENCY"

_ALGOS = {"", "default", "hash", "rand", "even", "coarse"}
_NUM_EXPR_RE = re.compile(r"^[0-9+\-*/() %]*$")


def parse_presort_exp(presort: Any) -> Dict[str, bool]:
    """Parse ``"a asc, b desc"`` / dict / list-of-tuples into an ordered
    ``{col: ascending}`` mapping."""
    if presort is None:
        return {}
    if isinstance(presort, dict):
        for v in presort.values():
            assert_or_throw(isinstance(v, bool), ValueError("presort value must be bool"))
        return dict(presort)
    if isinstance(presort, str):
        res: Dict[str, bool] = {}
        for part in presort.split(","):
            part = part.strip()
            if part == "":
                continue
            m = re.match(r"^([^\s]+|`[^`]+`)(\s+(asc|desc))?$", part, re.IGNORECASE)
            assert_or_throw(m is not None, SyntaxError(f"invalid presort {part!r}"))
            name = m.group(1).strip("`")
            asc = m.group(3) is None or m.group(3).lower() == "asc"
            assert_or_throw(name not in res, SyntaxError(f"duplicated presort key {name}"))
            res[name] = asc
        return res
    if isinstance(presort, Iterable):
        res = {}
        for item in presort:
            if isinstance(item, str):
                res[item] = True
            else:
                res[item[0]] = bool(item[1])
        return res
    raise SyntaxError(f"invalid presort {presort!r}")


class PartitionSpec:
    """Partition specification; immutable once constructed.

    Examples::

        PartitionSpec(num=4)
        PartitionSpec(by=["a"], presort="b desc")
        PartitionSpec("per_row")
        PartitionSpec(algo="even", num="ROWCOUNT/4")
    """

    def __init__(self, *args: Any, **kwargs: Any):
        self._algo = ""
        self._num_partitions = "0"
        self._partition_by: List[str] = []
        self._presort: Dict[str, bool] = {}
        for a in args:
            self._update(a)
        if kwargs:
            self._update(kwargs)

    def _update(self, obj: Any) -> None:
        if obj is None:
            return
        if isinstance(obj, PartitionSpec):
            self._algo = obj._algo or self._algo
            if obj._num_partitions != "0":
                self._num_partitions = obj._num_partitions
            if obj._partition_by:
                self._partition_by = list(obj._partition_by)
            if obj._presort:
                self._presort = dict(obj._presort)
            return
        if isinstance(obj, int):
            self._num_partitions = str(obj)
            return
        if isinstance(obj, str):
            s = obj.strip()
            if s == "":
                return
            if s == "per_row":
                self._update(dict(algo="even", num=KEYWORD_ROWCOUNT))
                return
            if s.lower() in _ALGOS:
                self._algo = s.lower()
                return
            if s.startswith("{"):
                self._update(json.loads(s))
                return
            # a number or a num expression
            if _NUM_EXPR_RE.match(s) or KEYWORD_ROWCOUNT in s or KEYWORD_CONCURRENCY in s:
                self._num_partitions = s
                return
            raise SyntaxError(f"can't interpret partition spec {obj!r}")
        if isinstance(obj, dict):
            for k, v in obj.items():
                if k == "algo":
                    v = str(v).lower()
                    assert_or_throw(v in _ALGOS, ValueError(f"invalid algo {v}"))
                    self._algo = "" if v == "default" else v
                elif k in ("num", "num_partitions"):
                    self._num_partitions = str(v)
                elif k in ("by", "partition_by"):
                    if isinstance(v, str):
                        v = [v]
                    v = list(v)
                    assert_or_throw(
                        len(set(v)) == len(v), SyntaxError(f"duplicated keys in {v}")
                    )
                    self._partition_by = v
                elif k == "presort":
                    self._presort = parse_presort_exp(v)
                else:
                    raise SyntaxError(f"unknown partition spec key {k}")
            return
        if isinstance(obj, (list, tuple)):
            self._update(dict(by=list(obj)))
            return
        raise SyntaxError(f"can't interpret partition spec {obj!r}")

    # ---- properties ------------------------------------------------------
    @property
    def empty(self) -> bool:
        return (
            self._algo == ""
            and self._num_partitions == "0"
            and len(self._partition_by) == 0
            and len(self._presort) == 0
        )

    @property
    def algo(self) -> str:
        return self._algo

    @property
    def num_partitions(self) -> str:
        return self._num_partitions

    @property
    def partition_by(self) -> List[str]:
        return list(self._partition_by)

    @property
    def presort(self) -> Dict[str, bool]:
        return dict(self._presort)

    @property
    def presort_expr(self) -> str:
        return ",".join(
            f"{k} {'ASC' if v else 'DESC'}" for k, v in self._presort.items()
        )

    def get_num_partitions(self, **expr_map_funcs: Callable[[], Any]) -> int:
        """Evaluate the ``num`` expression; keyword callables (ROWCOUNT,
        CONCURRENCY) are invoked only when referenced."""
        expr = self._num_partitions
        env: Dict[str, Any] = {"__builtins__": {}, "min": min, "max": max}
        for k, f in expr_map_funcs.items():
            if k in expr:
                env[k] = int(f())
        stripped = expr
        for k in env:
            stripped = stripped.replace(k, "")
        assert_or_throw(
            _NUM_EXPR_RE.match(stripped.replace(",", "")) is not None,
            ValueError(f"invalid num expression {expr!r}"),
        )
        try:
            return int(eval(expr, env))  # noqa: S307 - validated charset
        except Exception as e:
            raise ValueError(f"can't evaluate num expression {expr!r}") from e

    def get_sorts(
        self, schema: Schema, with_partition_keys: bool = True
    ) -> Dict[str, bool]:
        """Full sort spec for a physical partition: partition keys first (asc),
        then presort keys."""
        res: Dict[str, bool] = {}
        if with_partition_keys:
            for k in self._partition_by:
                assert_or_throw(k in schema, KeyError(f"{k} not in {schema}"))
                res[k] = True
        for k, v in self._presort.items():
            assert_or_throw(k in schema, KeyError(f"{k} not in {schema}"))
            res[k] = v
        return res

    def get_key_schema(self, schema: Schema) -> Schema:
        return schema.extract(self._partition_by)

    def get_cursor(
        self, schema: Schema, physical_partition_no: int
    ) -> "PartitionCursor":
        return PartitionCursor(schema, self, physical_partition_no)

    # ---- identity --------------------------------------------------------
    @property
    def jsondict(self) -> Dict[str, Any]:
        return dict(
            algo=self._algo,
            num_partitions=self._num_partitions,
            partition_by=list(self._partition_by),
            presort=self.presort_expr,
        )

    def __uuid__(self) -> str:
        return to_uuid(self.jsondict)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, PartitionSpec) and self.jsondict == other.jsondict

    def __hash__(self) -> int:
        return hash(self.__uuid__())

    def __repr__(self) -> str:
        return f"PartitionSpec({json.dumps(self.jsondict)})"


class DatasetPartitionCursor:
    """Tracks position while scanning physical partitions of any dataset
    (reference partition.py:336)."""

    def __init__(self, physical_no: int):
        self._physical_no = physical_no
        self._item: Any = None
        self._partition_no = 0
        self._slice_no = 0

    def set(self, item: Any, partition_no: int, slice_no: int) -> None:
        self._item = item
        self._partition_no = partition_no
        self._slice_no = slice_no

    @property
    def item(self) -> Any:
        if callable(self._item):
            self._item = self._item()
        return self._item

    @property
    def partition_no(self) -> int:
        return self._partition_no

    @property
    def physical_partition_no(self) -> int:
        return self._physical_no

    @property
    def slice_no(self) -> int:
        return self._slice_no


class PartitionCursor(DatasetPartitionCursor):
    """Row-aware cursor: inside a logical partition it exposes the key values
    of the current partition (reference partition.py:404)."""

    def __init__(self, schema: Schema, spec: PartitionSpec, physical_no: int):
        super().__init__(physical_no)
        self._schema = schema
        self._spec = spec
        self._key_index = [
            schema.index_of_key(k) for k in spec.partition_by
        ]

    def set(self, row: Any, partition_no: int, slice_no: int) -> None:
        super().set(row, partition_no, slice_no)

    @property
    def row(self) -> List[Any]:
        return self.item

    @property
    def row_schema(self) -> Schema:
        return self._schema

    @property
    def key_schema(self) -> Schema:
        return self._spec.get_key_schema(self._schema)

    @property
    def key_value_array(self) -> List[Any]:
        row = self.row
        return [row[i] for i in self._key_index]

    @property
    def key_value_dict(self) -> Dict[str, Any]:
        return dict(zip(self._spec.partition_by, self.key_value_array))
