"""Handles for results crossing workflow-run boundaries (reference
fugue/collections/yielded.py:7,37)."""

from typing import Any

from fugue_tpu.utils.assertion import assert_or_throw


class Yielded:
    """A uuid-identified handle whose value is filled in when the producing
    workflow runs."""

    def __init__(self, yid: str):
        self._yid = yid

    def __uuid__(self) -> str:
        return self._yid

    @property
    def is_set(self) -> bool:  # pragma: no cover - interface
        raise NotImplementedError

    def __copy__(self) -> "Yielded":
        return self

    def __deepcopy__(self, memo: Any) -> "Yielded":
        return self


class PhysicalYielded(Yielded):
    """Yielded result backed by permanent storage: a file path or a table name."""

    def __init__(self, yid: str, storage_type: str):
        super().__init__(yid)
        assert_or_throw(
            storage_type in ("file", "table"),
            ValueError(f"invalid storage type {storage_type}"),
        )
        self._storage_type = storage_type
        self._name = ""

    @property
    def is_set(self) -> bool:
        return self._name != ""

    @property
    def storage_type(self) -> str:
        return self._storage_type

    def set_value(self, name: str) -> None:
        self._name = name

    @property
    def name(self) -> str:
        assert_or_throw(self.is_set, ValueError("value is not set"))
        return self._name
