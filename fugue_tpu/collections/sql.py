"""Dialect-tagged SQL fragments with dataframe-name placeholders
(reference fugue/collections/sql.py:14,48)."""

import re
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple
from uuid import uuid4

from fugue_tpu.plugins import fugue_plugin
from fugue_tpu.utils.assertion import assert_or_throw


def _is_dataframe_like(obj: Any) -> bool:
    """DataFrame / WorkflowDataFrame / Yielded / pandas / pyarrow inputs."""
    from fugue_tpu.collections.yielded import Yielded
    from fugue_tpu.dataframe import DataFrame

    if isinstance(obj, (DataFrame, Yielded)):
        return True
    if hasattr(obj, "workflow") and hasattr(obj, "task"):
        return True  # WorkflowDataFrame (no import: avoids a cycle)
    mod = type(obj).__module__ or ""
    return mod.startswith("pandas") or mod.startswith("pyarrow")


def interleave_sql(statements: Any) -> "Tuple[List[Any], Dict[str, Any]]":
    """Mix string fragments and dataframes into StructuredRawSQL parts +
    a {temp_name: df} map (the ``raw_sql("SELECT ... FROM", df)`` form)."""
    parts: List[Any] = []
    dfs: Dict[str, Any] = {}
    for s in statements:
        if isinstance(s, str):
            parts.append((False, s))
        else:
            # only dataframe-like objects may interleave — anything else
            # (a misplaced dfs= dict, a scalar) fails loudly at call time,
            # not deep inside task execution
            if not _is_dataframe_like(s):
                raise ValueError(
                    f"cannot interleave {type(s).__name__} into SQL; "
                    "only SQL fragments (str) and dataframes are accepted"
                )
            t = TempTableName()
            dfs[t.key] = s
            parts.append((True, t.key))
        parts.append((False, " "))
    return parts, dfs


class TempTableName:
    """A unique placeholder name for a dataframe inside a raw SQL string."""

    _PREFIX = "_fugue_tpu_tmp_"

    def __init__(self):
        self.key = self._PREFIX + str(uuid4())[:8]

    def __repr__(self) -> str:
        return "<tmpdf:" + self.key + ">"

    @staticmethod
    def pattern() -> "re.Pattern":
        return re.compile(r"<tmpdf:(" + TempTableName._PREFIX + r"[0-9a-f]{8})>")


@fugue_plugin
def transpile_sql(raw: str, from_dialect: Optional[str], to_dialect: Optional[str]) -> str:
    """Transpile a SQL statement between dialects. Default: identity (no
    sqlglot in this environment); engines may register real transpilers."""
    return raw


class StructuredRawSQL:
    """A sequence of ``(is_dataframe, text)`` parts; dataframe parts refer to
    dataframes by name and are re-encoded per engine at construct time."""

    def __init__(
        self, statements: Iterable[Tuple[bool, str]], dialect: Optional[str] = None
    ):
        self._statements = list(statements)
        self._dialect = dialect

    @property
    def dialect(self) -> Optional[str]:
        return self._dialect

    def __uuid__(self) -> str:
        """Deterministic identity from the statement parts + dialect.
        Without this, a task holding a raw SQL statement hashed by the
        OBJECT's repr (memory address), so two compilations of the same
        query produced different task uuids — breaking the serving
        daemon's query fingerprint (breaker + result cache) and
        deterministic checkpoints over raw-SQL tasks."""
        from fugue_tpu.utils.hash import to_uuid

        return to_uuid(
            "StructuredRawSQL",
            [[bool(d), str(t)] for d, t in self._statements],
            self._dialect,
        )

    def construct(
        self,
        name_map: Any = None,
        dialect: Optional[str] = None,
        log: Any = None,
    ) -> str:
        """Render the SQL string, mapping dataframe names through ``name_map``
        (a dict or callable), transpiling when dialects differ."""
        if name_map is None:
            _map: Callable[[str], str] = lambda x: x
        elif isinstance(name_map, dict):
            _map = lambda x: name_map.get(x, x)  # noqa: E731
        else:
            _map = name_map
        sql = "".join(
            _map(text) if is_df else text for is_df, text in self._statements
        )
        if dialect is not None and self._dialect is not None and dialect != self._dialect:
            transpiled = transpile_sql(sql, self._dialect, dialect)
            if log is not None and transpiled != sql:
                log.debug("transpiled %s to %s", sql, transpiled)
            return transpiled
        return sql

    @staticmethod
    def from_expr(
        sql: str, prefix: str = "<tmpdf:", suffix: str = ">", dialect: Optional[str] = None
    ) -> "StructuredRawSQL":
        """Parse a raw string where dataframe references appear as
        ``<tmpdf:name>`` markers."""
        statements: List[Tuple[bool, str]] = []
        pos = 0
        while True:
            start = sql.find(prefix, pos)
            if start < 0:
                if pos < len(sql):
                    statements.append((False, sql[pos:]))
                break
            end = sql.find(suffix, start)
            assert_or_throw(end > 0, ValueError(f"unclosed placeholder in {sql}"))
            if start > pos:
                statements.append((False, sql[pos:start]))
            statements.append((True, sql[start + len(prefix) : end]))
            pos = end + len(suffix)
        return StructuredRawSQL(statements, dialect)
