"""The daemon's job scheduler: N workflow submissions run concurrently
against the ONE shared engine, each wrapped in the workflow runner's
existing timeout/cancellation machinery.

Every job executes as a single :class:`~fugue_tpu.workflow.runner.TaskNode`
driven by a :class:`~fugue_tpu.workflow.runner.DAGRunner` in parallel
mode, which is what provides the guarantees the daemon needs without new
mechanism:

- the node ``timeout`` gives per-job wall-clock abandonment (a wedged
  query is abandoned on its daemon worker thread, never pinning a
  scheduler slot past its budget);
- the job's :class:`~fugue_tpu.workflow.fault.CancelToken` is shared
  between the outer node AND the inner ``FugueWorkflow.run`` (via its
  ``cancel_token`` parameter), so a cancel request aborts a queued job
  before it starts and stops a running workflow at its next task
  boundary.

Concurrency is bounded by ``fugue.serve.max_concurrent`` worker threads
pulling from one FIFO queue; completed jobs stay queryable until the
retention cap evicts the oldest finished ones.
"""

import queue
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from fugue_tpu.exceptions import TaskCancelledError
from fugue_tpu.workflow.fault import CancelToken
from fugue_tpu.workflow.runner import DAGRunner, TaskNode

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
ERROR = "error"
CANCELLED = "cancelled"

# finished jobs kept for polling before the oldest are evicted
_RETAIN_FINISHED = 1000
# ... of which only the newest keep their FULL result payload (collected
# rows can run to limit x row_width bytes per job — a long-lived daemon
# must not pin hundreds of MB of host memory for jobs nobody will poll
# again); older finished jobs keep status/error/timings only
_RETAIN_RESULTS = 64


class ServeJob:
    """One submission: its request, lifecycle state, and outcome."""

    def __init__(
        self,
        session_id: str,
        sql: str,
        save_as: Optional[str] = None,
        timeout: float = 0.0,
        collect: bool = True,
        limit: int = 10_000,
    ):
        self.job_id = "job-" + uuid.uuid4().hex[:12]
        self.session_id = session_id
        self.sql = sql
        self.save_as = save_as
        self.timeout = max(0.0, float(timeout))
        self.collect = bool(collect)
        self.limit = int(limit)
        self.token = CancelToken()
        self.status = QUEUED
        self.result: Optional[Dict[str, Any]] = None
        self.error: Optional[Dict[str, str]] = None
        self.submitted_at = time.time()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.done_event = threading.Event()

    @property
    def finished(self) -> bool:
        return self.status in (DONE, ERROR, CANCELLED)

    def finish(self, status: str) -> None:
        self.status = status
        self.finished_at = time.time()
        self.done_event.set()

    def snapshot(self, include_result: bool = True) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "job_id": self.job_id,
            "session_id": self.session_id,
            "status": self.status,
            "submitted_at": self.submitted_at,
        }
        if self.started_at is not None and self.finished_at is not None:
            out["seconds"] = round(self.finished_at - self.started_at, 6)
        if self.error is not None:
            out["error"] = dict(self.error)
        if include_result and isinstance(self.result, dict):
            # the execution payload ("yields"/"saved_as"/"result") merges
            # into the snapshot top level; job fields win on collision
            for k, v in self.result.items():
                out.setdefault(k, v)
        return out


class JobScheduler:
    """Bounded-concurrency executor: ``execute(job)`` produces the job's
    result payload; failures become structured errors on the job."""

    def __init__(self, execute: Callable[[ServeJob], Any], max_concurrent: int):
        self._execute = execute
        self._max_concurrent = max(1, int(max_concurrent))
        self._queue: "queue.Queue[Optional[ServeJob]]" = queue.Queue()
        self._jobs: Dict[str, ServeJob] = {}
        self._order: List[str] = []  # submission order, for retention
        self._lock = threading.RLock()
        self._workers: List[threading.Thread] = []
        self._started = False

    @property
    def max_concurrent(self) -> int:
        return self._max_concurrent

    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
            self._workers = [
                threading.Thread(
                    target=self._work, daemon=True,
                    name=f"fugue-serve-worker-{i}",
                )
                for i in range(self._max_concurrent)
            ]
        for w in self._workers:
            w.start()

    def stop(self) -> None:
        """Cancel queued jobs and stop the workers. Running jobs get
        their token set; their worker threads are daemons, so a wedged
        query cannot block shutdown."""
        with self._lock:
            if not self._started:
                return
            self._started = False
            jobs = list(self._jobs.values())
        for job in jobs:
            if not job.finished:
                job.token.cancel()
        for _ in self._workers:
            self._queue.put(None)
        for w in self._workers:
            w.join(timeout=5)
        self._workers = []

    def submit(self, job: ServeJob) -> ServeJob:
        with self._lock:
            if not self._started:
                raise ValueError("scheduler is not running")
            self._jobs[job.job_id] = job
            self._order.append(job.job_id)
            self._evict_locked()
            # enqueue UNDER the lock: stop() flips _started and snapshots
            # the job table under the same lock, so a job can never land
            # in the queue behind the shutdown sentinels un-cancelled
            # (which would leave a sync waiter blocked forever)
            self._queue.put(job)
        return job

    def get(self, job_id: str) -> ServeJob:
        with self._lock:
            job = self._jobs.get(job_id)
        if job is None:
            raise KeyError(f"unknown job {job_id}")
        return job

    def cancel(self, job_id: str) -> ServeJob:
        """Set the job's cancel token: a queued job is skipped by its
        worker, a running one aborts at its next cancellation point (or
        its timeout). Finished jobs are left untouched."""
        job = self.get(job_id)
        if not job.finished:
            job.token.cancel()
        return job

    def counts(self) -> Dict[str, int]:
        with self._lock:
            jobs = list(self._jobs.values())
        out = {QUEUED: 0, RUNNING: 0, DONE: 0, ERROR: 0, CANCELLED: 0}
        for j in jobs:
            out[j.status] = out.get(j.status, 0) + 1
        return out

    def _evict_locked(self) -> None:
        while len(self._order) > _RETAIN_FINISHED:
            for i, jid in enumerate(self._order):
                if self._jobs[jid].finished:
                    del self._jobs[jid]
                    del self._order[i]
                    break
            else:
                return  # everything retained is still live
        # payload stripping beyond the fresh window (see _RETAIN_RESULTS)
        finished = [j for j in self._order if self._jobs[j].finished]
        for jid in finished[:-_RETAIN_RESULTS]:
            self._jobs[jid].result = None

    # ---- worker loop -----------------------------------------------------
    def _work(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            if job.token.cancelled:
                job.finish(CANCELLED)
                continue
            job.status = RUNNING
            job.started_at = time.time()
            node = TaskNode(
                job.job_id,
                lambda deps, j=job: self._execute(j),
                [],
                name=f"serve:{job.job_id}",
                timeout=job.timeout,
            )
            try:
                # parallel mode (even for one node) is what enforces the
                # wall-clock timeout; the shared token lets cancel() stop
                # the inner workflow too
                res = DAGRunner(concurrency=2).run(
                    [node], cancel_token=job.token
                )
                job.result = res.get(job.job_id)
                job.finish(DONE)
            except TaskCancelledError:
                job.finish(CANCELLED)
            except Exception as ex:
                from fugue_tpu.rpc.http import structured_error

                job.error = structured_error(ex)
                job.finish(ERROR)
