"""The daemon's job scheduler: N workflow submissions run concurrently
against the ONE shared engine, each wrapped in the workflow runner's
existing timeout/cancellation machinery.

Every job executes as a single :class:`~fugue_tpu.workflow.runner.TaskNode`
driven by a :class:`~fugue_tpu.workflow.runner.DAGRunner` in parallel
mode, which is what provides the guarantees the daemon needs without new
mechanism:

- the node ``timeout`` gives per-job wall-clock abandonment (a wedged
  query is abandoned on its daemon worker thread, never pinning a
  scheduler slot past its budget);
- the job's :class:`~fugue_tpu.workflow.fault.CancelToken` is shared
  between the outer node AND the inner ``FugueWorkflow.run`` (via its
  ``cancel_token`` parameter), so a cancel request aborts a queued job
  before it starts and stops a running workflow at its next task
  boundary.

Concurrency is bounded by ``fugue.serve.max_concurrent`` worker threads
pulling from one pending set. **Pickup order is a policy**
(``fugue.serve.scheduler``, ISSUE 18):

- ``fifo`` (default): strict submission order — PR 6 behavior;
- ``predictive``: shortest-*predicted*-job-first within per-tenant
  fairness. Each job carries a :class:`~fugue_tpu.serve.admission.
  CostEstimate` from its query fingerprint's stats-store history;
  pickup prefers higher ``priority``, then tenants with fewer running
  jobs, then the smallest predicted wall, then the nearest ``deadline``.
  A job whose ``deadline`` lapses while queued settles with a
  structured error instead of executing; a job whose predicted device
  bytes would overflow the planned fraction of the governed memory
  budget waits for headroom instead of starting (livelock-free: an
  idle scheduler always admits one job).

Resilience plumbing on top (ISSUE 7):

- :meth:`backlog` / :meth:`active_count` feed the daemon's admission
  control (queue-depth backpressure, per-session caps);
- :meth:`drain` stops intake, lets in-flight jobs finish until a
  deadline, then cancels and abandons the rest;
- finished jobs keep their **status** until the record cap evicts them,
  but their result **payload** is dropped by TTL
  (``fugue.serve.job_ttl``) — a long-lived daemon must not pin hundreds
  of MB of collected rows for jobs nobody will poll again;
- worker pickup passes the chaos site ``serve.dispatch``; an injected
  dispatch fault lands on the job as a structured error;
- jobs carry heartbeats (:meth:`ServeJob.beat`) the engine supervisor
  watches to cancel wedged runs.
"""

import queue
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from fugue_tpu.exceptions import TaskCancelledError
from fugue_tpu.testing.faults import fault_point
from fugue_tpu.testing.locktrace import tracked_lock
from fugue_tpu.workflow.fault import CancelToken
from fugue_tpu.workflow.runner import DAGRunner, TaskNode

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
ERROR = "error"
CANCELLED = "cancelled"

# finished job RECORDS (status/error/timings) kept for polling before
# the oldest are evicted; payloads go earlier, by TTL
_RETAIN_FINISHED = 1000

# seconds a worker whose every pending job is memory-deferred waits
# before re-checking predicted headroom (a finishing job frees it)
_DEFER_POLL = 0.02


class ServeJob:
    """One submission: its request, lifecycle state, and outcome.
    ``job_id`` is normally minted fresh; daemon restart recovery passes
    the journaled id so clients polling across the restart still
    resolve their job."""

    def __init__(
        self,
        session_id: str,
        sql: str,
        save_as: Optional[str] = None,
        timeout: float = 0.0,
        collect: bool = True,
        limit: int = 10_000,
        job_id: Optional[str] = None,
        request_id: Optional[str] = None,
        profile: bool = False,
        priority: int = 0,
        deadline: float = 0.0,
    ):
        self.job_id = job_id or ("job-" + uuid.uuid4().hex[:12])
        self.session_id = session_id
        self.sql = sql
        self.save_as = save_as
        self.timeout = max(0.0, float(timeout))
        self.collect = bool(collect)
        self.limit = int(limit)
        # scheduling fields (ISSUE 18): higher priority runs first and
        # survives load shedding longer; deadline is the ABSOLUTE epoch
        # second after which a still-queued job is settled with a
        # structured error instead of executing (0 = none) — the HTTP
        # layer converts the submission's relative seconds budget
        self.priority = int(priority)
        self.deadline = max(0.0, float(deadline))
        # predicted cost (a fugue_tpu.serve.admission.CostEstimate) the
        # daemon attaches at submit under the predictive policy; None
        # under fifo
        self.cost: Any = None
        # per-request profiling (ISSUE 14): the executor forces the
        # workflow profiler for this job regardless of daemon conf; the
        # RunProfile lands on ``self.profile`` for GET /v1/jobs/<id>/
        # profile (conf-level fugue.obs.profile fills it too)
        self.profile_requested = bool(profile)
        self.profile: Any = None
        # correlation id of the HTTP request that submitted this job
        # (X-Request-Id, generated when absent); journaled with async
        # jobs so a restarted daemon's resubmissions keep their ids
        self.request_id = request_id
        # observability carry: the submitting request's trace and this
        # job's serve.job span (None with obs off) — the worker thread
        # re-attaches them so the job's spans land in the request tree
        self.obs_trace: Any = None
        self.obs_span: Any = None
        self.token = CancelToken()
        # every cooperative cancellation check the inner workflow makes
        # (task launch, retry attempts, dispatch-guard acquisition) is a
        # liveness proof: heartbeats ride on the polls, so a long multi-
        # task query keeps beating between device dispatches and the
        # watchdog only sees a stale beat when ONE dispatch truly wedges
        self.token.on_poll = self.beat
        self.status = QUEUED
        self.result: Optional[Dict[str, Any]] = None
        self.error: Optional[Dict[str, str]] = None
        self.submitted_at = time.time()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.done_event = threading.Event()
        self._finish_lock = tracked_lock("serve.scheduler.ServeJob._finish_lock")
        # deterministic workflow uuid of the compiled DAG, set by the
        # executor once the DAG exists — the breaker's query fingerprint
        self.fingerprint: Optional[str] = None
        # True when restart recovery resubmitted this job from the journal
        self.recovered = False
        self._heartbeat: Optional[float] = None  # monotonic
        self._seq = 0  # submission sequence, assigned by the scheduler

    @property
    def finished(self) -> bool:
        return self.status in (DONE, ERROR, CANCELLED)

    def beat(self) -> None:
        """Record liveness; the executor calls this at milestones and
        the supervisor cancels running jobs whose beat goes stale."""
        self._heartbeat = time.monotonic()

    @property
    def heartbeat_age(self) -> Optional[float]:
        """Seconds since the last beat (None before the first)."""
        if self._heartbeat is None:
            return None
        return time.monotonic() - self._heartbeat

    def finish(self, status: str) -> None:
        self.status = status
        self.finished_at = time.time()
        self.done_event.set()

    def try_finish(self, status: str) -> bool:
        """Finish exactly once: False when another path (the watchdog's
        abandon vs the worker's own completion) already finished it."""
        with self._finish_lock:
            if self.finished:
                return False
            self.finish(status)
            return True

    def try_start(self) -> bool:
        """Atomically claim execution at worker pickup: False when the
        job was cancelled or already terminalized. Under the finish lock
        so a drain/watchdog ``abandon`` racing the pickup can never be
        overwritten back to RUNNING (a resurrected finished job would
        double-fire the finish observers)."""
        with self._finish_lock:
            if self.finished or self.token.cancelled:
                return False
            self.status = RUNNING
            self.started_at = time.time()
            return True

    def snapshot(self, include_result: bool = True) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "job_id": self.job_id,
            "session_id": self.session_id,
            "status": self.status,
            "submitted_at": self.submitted_at,
        }
        if self.priority != 0:
            out["priority"] = self.priority
        if self.deadline > 0:
            out["deadline"] = self.deadline
        if self.request_id is not None:
            out["request_id"] = self.request_id
        if self.recovered:
            out["recovered"] = True
        if self.started_at is not None and self.finished_at is not None:
            out["seconds"] = round(self.finished_at - self.started_at, 6)
        if self.error is not None:
            out["error"] = dict(self.error)
        if include_result and isinstance(self.result, dict):
            # the execution payload ("yields"/"saved_as"/"result") merges
            # into the snapshot top level; job fields win on collision
            for k, v in self.result.items():
                out.setdefault(k, v)
        return out


class JobScheduler:
    """Bounded-concurrency executor: ``execute(job)`` produces the job's
    result payload; failures become structured errors on the job.
    ``on_finish`` (optional) fires after every job reaches a terminal
    state — the daemon uses it for breaker accounting and job-journal
    cleanup. ``policy`` selects pickup order (``fifo`` | ``predictive``);
    ``admission`` (a :class:`~fugue_tpu.serve.admission.
    PredictiveAdmission`) carries the predictive policy's cost ledger."""

    def __init__(
        self,
        execute: Callable[[ServeJob], Any],
        max_concurrent: int,
        job_ttl: float = 0.0,
        on_finish: Optional[Callable[[ServeJob], None]] = None,
        policy: str = "fifo",
        admission: Any = None,
    ):
        self._execute = execute
        self._max_concurrent = max(1, int(max_concurrent))
        self._job_ttl = max(0.0, float(job_ttl))
        self._on_finish = on_finish
        self._policy = str(policy or "fifo").lower()
        if self._policy not in ("fifo", "predictive"):
            raise ValueError(
                f"fugue.serve.scheduler must be fifo|predictive, "
                f"got {self._policy!r}"
            )
        self._admission = admission
        # wake-up channel only: one token per submitted job, None as the
        # shutdown sentinel. The jobs themselves wait in _pending, where
        # the policy (not arrival order) decides pickup.
        self._queue: "queue.Queue[Optional[bool]]" = queue.Queue()
        self._pending: List[ServeJob] = []
        self._jobs: Dict[str, ServeJob] = {}
        self._order: List[str] = []  # submission order, for retention
        self._seq = 0
        self._lock = tracked_lock(
            "serve.scheduler.JobScheduler._lock", reentrant=True
        )
        self._workers: List[threading.Thread] = []
        self._started = False
        self._draining = False

    @property
    def max_concurrent(self) -> int:
        return self._max_concurrent

    @property
    def policy(self) -> str:
        return self._policy

    @property
    def admission(self) -> Any:
        return self._admission

    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
            self._draining = False
            self._workers = [
                threading.Thread(
                    target=self._work, daemon=True,
                    name=f"fugue-serve-worker-{i}",
                )
                for i in range(self._max_concurrent)
            ]
        for w in self._workers:
            w.start()

    def stop(self) -> None:
        """Cancel queued jobs and stop the workers. Running jobs get
        their token set; their worker threads are daemons, so a wedged
        query cannot block shutdown."""
        self._shutdown(cancel=True)

    def kill(self) -> None:
        """Hard-kill approximation for chaos tests: stop the workers via
        their sentinels and cancel running tokens (the closest an
        in-process harness gets to threads vanishing mid-flight), with
        no drain, no waiting, no journaling — and no finish observers:
        a killed process never runs its callbacks, so the job journal
        keeps the interrupted entries a restart must resume."""
        self._on_finish = None
        self._shutdown(cancel=True, join=0.5)

    def _shutdown(self, cancel: bool, join: float = 5.0) -> None:
        with self._lock:
            if not self._started:
                return
            self._started = False
            jobs = list(self._jobs.values())
        if cancel:
            for job in jobs:
                if not job.finished:
                    job.token.cancel()
        for _ in self._workers:
            self._queue.put(None)
        for w in self._workers:
            w.join(timeout=join)
        self._workers = []

    # ---- drain -----------------------------------------------------------
    def drain(self, timeout: float) -> Dict[str, int]:
        """Graceful drain: stop accepting, give queued+running jobs up
        to ``timeout`` seconds to finish, then cancel and abandon the
        rest. Returns ``{"completed": n, "abandoned": m}`` counted over
        the jobs that were in flight when the drain began."""
        with self._lock:
            self._draining = True
            inflight = [j for j in self._jobs.values() if not j.finished]
        deadline = time.monotonic() + max(0.0, timeout)
        for job in inflight:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            job.done_event.wait(timeout=remaining)
        # deadline passed: stragglers are abandoned — terminal CANCELLED
        # immediately, so the final journal snapshot and any pollers see
        # a settled state, not a phantom running job
        abandoned = sum(
            1 for job in inflight if not job.finished and self.abandon(job)
        )
        return {
            "completed": len(inflight) - abandoned,
            "abandoned": abandoned,
        }

    @property
    def draining(self) -> bool:
        return self._draining

    def submit(self, job: ServeJob) -> ServeJob:
        with self._lock:
            if not self._started or self._draining:
                # a 503 BackpressureError, not a 400: a submission can
                # legitimately race the start of a drain past the
                # daemon's health check, and the client's retry budget
                # must carry it to the next attempt (or, in a fleet,
                # to the replica adopting this one's sessions)
                from fugue_tpu.serve.supervisor import BackpressureError

                raise BackpressureError(
                    "scheduler is draining/stopped; not accepting jobs",
                    retry_after=1.0,
                )
            self._seq += 1
            job._seq = self._seq
            self._jobs[job.job_id] = job
            self._order.append(job.job_id)
            self._pending.append(job)
            if self._admission is not None and job.cost is not None:
                self._admission.job_queued(job.job_id, job.cost)
            self._evict_locked()
            # enqueue UNDER the lock: stop() flips _started and snapshots
            # the job table under the same lock, so a job can never land
            # in the queue behind the shutdown sentinels un-cancelled
            # (which would leave a sync waiter blocked forever)
            self._queue.put(True)
        return job

    def abandon(self, job: ServeJob) -> bool:
        """Cancel + immediately terminalize a job the daemon has given
        up on (drain deadline, stale heartbeat): the record flips to
        CANCELLED right away so pollers unblock, while the worker thread
        — possibly still wedged inside the dispatch — can no longer
        overwrite the outcome (``try_finish``). Returns False when the
        job won the race and finished on its own."""
        job.token.cancel()
        if job.try_finish(CANCELLED):
            self._settle_cost(job)
            self._notify_finish(job)
            return True
        return False

    def adopt(self, job: ServeJob) -> ServeJob:
        """Register a job record WITHOUT queueing it — restart recovery
        uses this for journaled jobs whose session did not survive, so a
        client polling the old job id gets the structured failover error
        instead of a 404."""
        with self._lock:
            self._jobs[job.job_id] = job
            self._order.append(job.job_id)
            self._evict_locked()
        return job

    def get(self, job_id: str) -> ServeJob:
        with self._lock:
            job = self._jobs.get(job_id)
        if job is None:
            raise KeyError(f"unknown job {job_id}")
        return job

    def cancel(self, job_id: str) -> ServeJob:
        """Set the job's cancel token: a queued job is skipped by its
        worker, a running one aborts at its next cancellation point (or
        its timeout). Finished jobs are left untouched."""
        job = self.get(job_id)
        if not job.finished:
            job.token.cancel()
        return job

    def counts(self) -> Dict[str, int]:
        with self._lock:
            jobs = list(self._jobs.values())
        out = {QUEUED: 0, RUNNING: 0, DONE: 0, ERROR: 0, CANCELLED: 0}
        for j in jobs:
            out[j.status] = out.get(j.status, 0) + 1
        return out

    def backlog(self) -> int:
        """Queued (not yet running) jobs — the admission controller's
        queue-depth signal."""
        with self._lock:
            return sum(1 for j in self._jobs.values() if j.status == QUEUED)

    def active_count(self, session_id: str) -> int:
        """Queued+running jobs of one session (per-session cap)."""
        with self._lock:
            return sum(
                1
                for j in self._jobs.values()
                if j.session_id == session_id and not j.finished
            )

    def running_jobs(self) -> List[ServeJob]:
        with self._lock:
            return [j for j in self._jobs.values() if j.status == RUNNING]

    def predicted_drain_secs(self) -> float:
        """Predicted seconds until the backlog drains (0.0 under fifo /
        without an admission ledger) — what the daemon's shed decision
        and its 503 ``Retry-After`` are sized from."""
        if self._admission is None:
            return 0.0
        return self._admission.predicted_drain_secs()

    # ---- retention -------------------------------------------------------
    def _evict_locked(self) -> None:
        while len(self._order) > _RETAIN_FINISHED:
            for i, jid in enumerate(self._order):
                if self._jobs[jid].finished:
                    del self._jobs[jid]
                    del self._order[i]
                    break
            else:
                return  # everything retained is still live

    def gc_payloads(self, now: Optional[float] = None) -> int:
        """TTL eviction of finished-job payloads (``fugue.serve.job_ttl``):
        a job finished more than the TTL ago keeps its status/error/
        timings but drops the collected-rows payload. 0 = keep payloads
        until the record cap evicts the whole job. Returns how many
        payloads were dropped."""
        if self._job_ttl <= 0:
            return 0
        cutoff = (now if now is not None else time.time()) - self._job_ttl
        dropped = 0
        with self._lock:
            for job in self._jobs.values():
                if (
                    job.finished
                    and job.result is not None
                    and job.finished_at is not None
                    and job.finished_at < cutoff
                ):
                    job.result = None
                    dropped += 1
        return dropped

    # ---- pickup policy ---------------------------------------------------
    def _pick_locked(self) -> Any:
        """Choose the next job from the pending set (MUST hold _lock).
        Returns ``(job, settled)`` where ``settled`` lists jobs removed
        from pending that must be terminalized OUTSIDE the lock
        (deadline expiries); ``job`` is None when nothing is eligible —
        either pending is empty (token raced a cancel/expiry sweep) or
        every candidate is memory-deferred (the worker polls for
        headroom)."""
        now = time.time()
        settled: List[ServeJob] = []
        candidates: List[ServeJob] = []
        for job in self._pending:
            if job.deadline > 0 and now >= job.deadline and (
                not job.token.cancelled
            ):
                settled.append(job)
            else:
                candidates.append(job)
        if settled:
            self._pending = list(candidates)
        if not candidates:
            return None, settled
        if self._policy == "fifo" or self._admission is None:
            job = candidates[0]
            self._pending.remove(job)
            return job, settled
        # predictive: priority first, then tenants with fewer RUNNING
        # jobs (fairness), then shortest predicted wall, then nearest
        # deadline, then submission order (stable tie-break)
        running_by_tenant: Dict[str, int] = {}
        anything_running = False
        for j in self._jobs.values():
            if j.status == RUNNING:
                anything_running = True
                running_by_tenant[j.session_id] = (
                    running_by_tenant.get(j.session_id, 0) + 1
                )

        def _key(j: ServeJob) -> Any:
            est = j.cost
            wall = est.wall_ms if est is not None else 0.0
            return (
                -j.priority,
                running_by_tenant.get(j.session_id, 0),
                wall,
                j.deadline if j.deadline > 0 else float("inf"),
                j._seq,
            )

        for j in sorted(candidates, key=_key):
            est = j.cost
            if est is None or self._admission.fits_memory(
                est, anything_running
            ):
                self._pending.remove(j)
                return j, settled
        return None, settled  # all memory-deferred: wait for headroom

    def _settle_cost(self, job: ServeJob) -> None:
        """Drop the job from the admission ledger wherever it sits."""
        if self._admission is None:
            return
        self._admission.job_dequeued(job.job_id)
        self._admission.job_finished(job.job_id)

    def _expire(self, job: ServeJob) -> None:
        """A queued job whose deadline lapsed: structured error, never
        executed — the submitter asked for an answer by a time that has
        passed, and running it anyway would burn capacity the live
        queue needs."""
        job.error = {
            "error": "DeadlineExceededError",
            "message": (
                f"job {job.job_id} missed its deadline while queued "
                f"(deadline={job.deadline:.3f}, now={time.time():.3f})"
            ),
        }
        if job.try_finish(ERROR):
            self._settle_cost(job)
            self._notify_finish(job)

    # ---- worker loop -----------------------------------------------------
    def _work(self) -> None:
        while True:
            token = self._queue.get()
            if token is None:
                return
            job: Optional[ServeJob] = None
            while True:
                with self._lock:
                    job, settled = self._pick_locked()
                    pending = len(self._pending)
                    started = self._started
                for s in settled:
                    self._expire(s)
                if job is not None or pending == 0 or not started:
                    break
                # every candidate is memory-deferred: poll for the
                # headroom a finishing job frees (bounded, shutdown-
                # aware — the sentinel ends the worker either way)
                time.sleep(_DEFER_POLL)
            if job is None:
                continue
            if not job.try_start():
                self._settle_cost(job)
                if job.try_finish(CANCELLED):
                    self._notify_finish(job)
                continue
            if self._admission is not None and job.cost is not None:
                self._admission.job_started(job.job_id)
            job.beat()
            node = TaskNode(
                job.job_id,
                lambda deps, j=job: self._dispatch(j),
                [],
                name=f"serve:{job.job_id}",
                timeout=job.timeout,
            )
            try:
                # parallel mode (even for one node) is what enforces the
                # wall-clock timeout; the shared token lets cancel() stop
                # the inner workflow too
                res = DAGRunner(concurrency=2).run(
                    [node], cancel_token=job.token
                )
                job.result = res.get(job.job_id)
                if not job.try_finish(DONE):
                    # lost the race to an abandon (drain deadline, stale
                    # heartbeat): the outcome stays CANCELLED
                    job.result = None
                    self._settle_cost(job)
                    continue
            except TaskCancelledError:
                if not job.try_finish(CANCELLED):
                    self._settle_cost(job)
                    continue
            except Exception as ex:
                from fugue_tpu.rpc.http import structured_error

                if job.finished:  # abandoned mid-flight: outcome settled
                    self._settle_cost(job)
                    continue
                job.error = structured_error(ex)
                if not job.try_finish(ERROR):
                    self._settle_cost(job)
                    continue
            self._settle_cost(job)
            self._notify_finish(job)

    def _dispatch(self, job: ServeJob) -> Any:
        # chaos site: an injected dispatch fault surfaces on the job as
        # a structured error, never as a dead worker thread
        fault_point("serve.dispatch", job.job_id)
        return self._execute(job)

    def _notify_finish(self, job: ServeJob) -> None:
        if self._on_finish is None:
            return
        try:
            self._on_finish(job)
        except Exception:  # pragma: no cover - observer must not kill worker
            pass
