"""Durable daemon state: the crash journal behind serving resilience.

With ``fugue.serve.state_path`` set, the daemon journals three things
through ``engine.fs`` into ``<state_path>/serve_state.json``, atomically
rewritten on every mutation (the same atomic-write primitive as the run
manifest — :func:`fugue_tpu.workflow.manifest.atomic_json_write` — so a
hard kill leaves the previous snapshot or the new one, never a torn
file):

- the **session registry**: id, ttl, creation time, last-use time;
- each session's **saved-table catalog**: every ``save_table`` also
  writes the frame as a parquet artifact under
  ``<state_path>/tables/<session>/<name>.parquet`` and records its byte
  size + sha256 (:func:`~fugue_tpu.workflow.manifest.artifact_fingerprint`);
  a restarted daemon reloads a hot table LAZILY on first access, after
  re-verifying the fingerprint — an integrity-rejected artifact is
  removed and the table forgotten, exactly how manifest resume rejects
  corrupted checkpoints;
- the **async job journal**: queued/running async submissions with their
  full request, so a restarted daemon resubmits them under their
  original job ids (re-running a FugueSQL job is idempotent — saves are
  overwrite-mode — so failover never duplicates rows).

Journal writes are best-effort: a failing write (chaos site
``serve.journal``) degrades durability, never availability — the error
is logged and counted, and serving continues.

**Write path (ISSUE 13)**: the state lock is held only to SNAPSHOT the
payload; the filesystem write itself runs outside it through a
:class:`SnapshotWriter` — a dedicated writer mutex that serializes
writes and drops superseded snapshots (sequence-numbered tickets), so a
slow or hung shared-fs write can no longer block every
``touch_session``/``record_*`` on the serving hot path behind it.

The journal is also the **handoff unit** of fleet failover
(:mod:`fugue_tpu.serve.fleet`): a surviving replica adopts a dead
replica's sessions/jobs by reading its journal (:meth:`read_state`),
importing the records into its own (:meth:`import_session` +
``record_job``), and clearing the source (:meth:`clear_state`) so a
later restart of the origin replica cannot double-own the sessions.
"""

import copy
import json
import time
from typing import Any, Dict, Optional
from uuid import uuid4

from fugue_tpu.testing.faults import fault_point
from fugue_tpu.testing.locktrace import tracked_lock
from fugue_tpu.workflow.manifest import atomic_json_write, read_json

_STATE_FILE = "serve_state.json"
_FENCE_FILE = "_adopt_fence.json"


class AdoptionFencedError(RuntimeError):
    """Another adopter already holds this journal's fence: backing off.
    Carries the winning token so the loser can log WHO won; the race is
    settled — retrying after the winner clears the journal adopts an
    empty state, never a double-owned session."""

    def __init__(self, base_uri: str, holder: Dict[str, Any]):
        super().__init__(
            f"journal {base_uri} is being adopted by "
            f"{holder.get('owner', '<unknown>')!r}"
        )
        self.base_uri = base_uri
        self.holder = dict(holder)


class SnapshotWriter:
    """Ordered best-effort snapshot writes OUTSIDE the state lock.

    Contract: the caller allocates a :meth:`ticket` while holding ITS
    OWN state lock together with the snapshot (so ticket order equals
    snapshot order), then calls :meth:`write` holding NO state lock.
    The writer mutex serializes the filesystem writes; a snapshot whose
    ticket is older than the last landed one is simply dropped — its
    state is a strict subset of what is already on disk, so skipping it
    preserves write ordering without ever writing stale state."""

    def __init__(self, fs: Any, uri: str, log: Any = None):
        self._fs = fs
        self._uri = uri
        self._log = log
        # the ONLY lock in the serve plane a filesystem write may run
        # under — nothing else is ever acquired while holding it, and
        # no request-path lock waits on it (see baseline.json FLN104)
        self._lock = tracked_lock("serve.state.SnapshotWriter._lock")
        self._next = 1      # mutated under the CALLER's state lock only
        self._written = 0   # mutated under self._lock only
        self.failures = 0

    def ticket(self) -> int:
        """Allocate the next snapshot sequence number. MUST be called
        under the caller's state lock, in the same critical section
        that takes the snapshot."""
        t = self._next
        self._next += 1
        return t

    def write(self, ticket: int, payload: Dict[str, Any]) -> None:
        """Atomically persist ``payload`` unless a newer ticket already
        landed (chaos site ``serve.journal``). Best-effort: failures
        degrade durability, never availability."""
        with self._lock:
            if ticket <= self._written:
                return  # superseded: a newer snapshot is already durable
            try:
                fault_point("serve.journal", self._uri)
                atomic_json_write(self._fs, self._uri, payload)
                self._written = ticket
            except Exception as ex:
                self.failures += 1
                if self._log is not None:
                    self._log.warning(
                        "fugue_tpu serve: journal write to %s failed "
                        "(%s: %s); durability degraded, serving continues",
                        self._uri, type(ex).__name__, ex,
                    )


class ServeStateJournal:
    """The daemon's durable state file. Mutators update the in-memory
    snapshot under one lock, then hand a deep-copied payload to the
    :class:`SnapshotWriter` — the filesystem write never runs under the
    state lock; readers get plain dicts."""

    def __init__(self, engine: Any, base_uri: str):
        self._engine = engine
        self._base = str(base_uri).rstrip("/")
        self._lock = tracked_lock(
            "serve.state.ServeStateJournal._lock", reentrant=True
        )
        self._sessions: Dict[str, Dict[str, Any]] = {}
        self._jobs: Dict[str, Dict[str, Any]] = {}
        self._writer = SnapshotWriter(
            engine.fs, self.uri, log=engine.log
        )
        # touch_session marks the snapshot dirty WITHOUT writing; the
        # supervisor tick flushes at a bounded cadence so a read-only
        # workload's last_used still reaches disk (else its sessions
        # would look idle-since-creation to a restarted daemon and be
        # expired, artifacts and all)
        self._dirty = False
        self._last_write = 0.0  # monotonic

    @property
    def uri(self) -> str:
        return self._engine.fs.join(self._base, _STATE_FILE)

    @property
    def base_uri(self) -> str:
        """The journal's state dir — what a fleet router hands to a
        surviving replica's adopt hook on failover."""
        return self._base

    @property
    def write_failures(self) -> int:
        return self._writer.failures

    def table_artifact_uri(self, session_id: str, name: str) -> str:
        fs = self._engine.fs
        return fs.join(self._base, "tables", session_id, f"{name}.parquet")

    # ---- load / persist --------------------------------------------------
    def load(self) -> Dict[str, Any]:
        """Read a prior daemon's journal (empty dicts when none). The
        snapshot becomes this journal's live state so the first mutation
        after a restart does not drop recovered-but-untouched entries."""
        data = read_json(
            self._engine.fs, self.uri,
            log=self._engine.log, what="serve state journal",
        ) or {}
        with self._lock:
            self._sessions = dict(data.get("sessions") or {})
            self._jobs = dict(data.get("jobs") or {})
            return {
                "sessions": dict(self._sessions),
                "jobs": dict(self._jobs),
            }

    def write(self) -> None:
        """Persist the current snapshot. The state lock covers only the
        deep-copy + ticket; the write itself runs through the ordered
        :class:`SnapshotWriter` so a hung shared-fs write cannot stall
        the serving hot path behind this lock."""
        with self._lock:
            payload = {
                "saved_at": time.time(),
                "sessions": copy.deepcopy(self._sessions),
                "jobs": copy.deepcopy(self._jobs),
            }
            self._dirty = False
            self._last_write = time.monotonic()
            ticket = self._writer.ticket()
        self._writer.write(ticket, payload)

    # ---- fleet adoption (static: reads a FOREIGN replica's journal) ------
    @staticmethod
    def read_state(fs: Any, base_uri: str, log: Any = None) -> Dict[str, Any]:
        """A replica's journal snapshot as plain dicts (empty when
        missing/unreadable) — what the adopt hook consumes."""
        base = str(base_uri).rstrip("/")
        data = read_json(
            fs, fs.join(base, _STATE_FILE),
            log=log, what="adopted serve journal",
        ) or {}
        return {
            "sessions": dict(data.get("sessions") or {}),
            "jobs": dict(data.get("jobs") or {}),
        }

    @staticmethod
    def clear_state(fs: Any, base_uri: str) -> None:
        """Atomically empty a replica's journal after its sessions were
        adopted elsewhere: a restarted origin replica rehydrates nothing
        instead of double-owning migrated sessions. The adoption fence
        falls with the journal, so a REBORN journal at this path is
        adoptable again."""
        base = str(base_uri).rstrip("/")
        atomic_json_write(
            fs,
            fs.join(base, _STATE_FILE),
            {"saved_at": time.time(), "sessions": {}, "jobs": {}},
        )
        ServeStateJournal.clear_adoption_fence(fs, base)

    # ---- adoption fence (CAS) --------------------------------------------
    @staticmethod
    def acquire_adoption_fence(
        fs: Any, base_uri: str, owner: str, stale_after: float = 30.0
    ) -> Dict[str, Any]:
        """Claim the EXCLUSIVE right to adopt this journal via a
        fail-if-exists fence-token write (``write_file_if_absent`` — the
        same CAS primitive as lake manifest commits). Exactly one of N
        racing adopters wins; every loser raises
        :class:`AdoptionFencedError` carrying the winner's token and
        backs off WITHOUT reading the journal, so two survivors racing
        to adopt a dead replica can never double-own its sessions.

        A fence older than ``stale_after`` seconds is assumed abandoned
        (its holder was hard-killed mid-adoption) and is broken with one
        re-acquisition attempt — adoption is idempotent per session id,
        so re-running a half-landed adoption converges rather than
        duplicating. The fence clears together with the journal
        (:meth:`clear_state`)."""
        base = str(base_uri).rstrip("/")
        uri = fs.join(base, _FENCE_FILE)
        token = {
            "owner": str(owner),
            "claimed_at": time.time(),
            "nonce": uuid4().hex,
        }
        payload = json.dumps(token).encode("utf-8")
        for attempt in (0, 1):
            try:
                fs.write_file_if_absent(uri, lambda fp: fp.write(payload))
                return token
            except FileExistsError:
                holder: Dict[str, Any] = {}
                try:
                    holder = json.loads(fs.read_bytes(uri))
                except Exception:
                    pass
                age = time.time() - float(holder.get("claimed_at", 0.0))
                if attempt == 0 and age > max(0.0, stale_after):
                    # abandoned fence: its writer died mid-adoption.
                    # Break it and race for the slot ONCE — the CAS on
                    # the re-acquire still picks exactly one winner.
                    try:
                        fs.rm(uri)
                    except FileNotFoundError:  # pragma: no cover - raced
                        pass
                    continue
                raise AdoptionFencedError(base, holder)
        raise AdoptionFencedError(base, {})  # pragma: no cover

    @staticmethod
    def clear_adoption_fence(fs: Any, base_uri: str) -> None:
        """Drop the fence token (no-op when absent)."""
        uri = fs.join(str(base_uri).rstrip("/"), _FENCE_FILE)
        try:
            fs.rm(uri)
        except FileNotFoundError:
            pass
        except Exception:  # pragma: no cover - best-effort cleanup
            pass

    def import_session(self, session_id: str, record: Dict[str, Any]) -> None:
        """Adopt a foreign journal's full session record (ttl, times AND
        table catalog) into this journal — fleet failover's bookkeeping
        move; the artifact URIs inside the record stay where the origin
        replica wrote them (shared fs)."""
        with self._lock:
            self._sessions[session_id] = copy.deepcopy(record)
        self.write()

    # ---- session registry ------------------------------------------------
    def record_session(self, session: Any) -> None:
        with self._lock:
            rec = self._sessions.setdefault(
                session.session_id,
                {"tables": {}},
            )
            rec.update(
                {
                    "ttl": session.ttl,
                    "created_at": session.created_at,
                    "last_used": time.time(),
                }
            )
        self.write()

    def touch_session(self, session_id: str) -> None:
        """Refresh a session's journaled last-use WITHOUT a write — the
        journal must not rewrite on every query. The timestamp rides
        along with the next mutation's snapshot, or with the supervisor
        tick's bounded-cadence :meth:`maybe_flush`."""
        with self._lock:
            rec = self._sessions.get(session_id)
            if rec is not None:
                rec["last_used"] = time.time()
                self._dirty = True

    def maybe_flush(self, min_interval: float = 5.0) -> None:
        """Write the snapshot iff touches are pending and the last write
        is older than ``min_interval`` — bounds last_used staleness on a
        read-only workload to ~min_interval without journal churn."""
        with self._lock:
            if (
                not self._dirty
                or time.monotonic() - self._last_write < min_interval
            ):
                return
        self.write()

    def forget_session(self, session_id: str) -> None:
        with self._lock:
            existed = self._sessions.pop(session_id, None) is not None
        if existed:
            self.write()

    def record_table(
        self, session_id: str, name: str, record: Dict[str, Any]
    ) -> None:
        with self._lock:
            rec = self._sessions.get(session_id)
            if rec is None:  # pragma: no cover - session raced away
                return
            rec.setdefault("tables", {})[name] = record
            rec["last_used"] = time.time()
        self.write()

    def forget_table(self, session_id: str, name: str) -> None:
        with self._lock:
            rec = self._sessions.get(session_id)
            existed = (
                rec is not None
                and rec.get("tables", {}).pop(name, None) is not None
            )
        if existed:
            self.write()

    # ---- standing pipelines (materialized views) -------------------------
    def record_pipeline(
        self, session_id: str, name: str, spec: Dict[str, Any]
    ) -> None:
        """Journal a standing pipeline's SPEC under its session record:
        a restarted (or adopting) daemon rebuilds the pipeline object
        from the spec, and the progress manifest the spec points at
        restores its exactly-once state."""
        with self._lock:
            rec = self._sessions.get(session_id)
            if rec is None:  # pragma: no cover - session raced away
                return
            rec.setdefault("pipelines", {})[name] = copy.deepcopy(spec)
            rec["last_used"] = time.time()
        self.write()

    def forget_pipeline(self, session_id: str, name: str) -> None:
        with self._lock:
            rec = self._sessions.get(session_id)
            existed = (
                rec is not None
                and rec.get("pipelines", {}).pop(name, None) is not None
            )
        if existed:
            self.write()

    # ---- async job journal -----------------------------------------------
    def record_job(self, job: Any) -> None:
        with self._lock:
            self._jobs[job.job_id] = {
                "session_id": job.session_id,
                "sql": job.sql,
                "save_as": job.save_as,
                "timeout": job.timeout,
                "collect": job.collect,
                "limit": job.limit,
                "submitted_at": job.submitted_at,
                # correlation id survives the restart: a resubmitted
                # job's logs/spans still tie back to the original
                # X-Request-Id the client holds
                "request_id": job.request_id,
                # a profiled submission stays profiled when a restart
                # or adoption resubmits it
                "profile": bool(getattr(job, "profile_requested", False)),
                # scheduling fields (ISSUE 18): a resubmitted job keeps
                # its priority and its ABSOLUTE deadline — a deadline
                # that lapsed while the daemon was down settles as a
                # structured deadline error, not a silent re-run
                "priority": int(getattr(job, "priority", 0) or 0),
                "deadline": float(getattr(job, "deadline", 0.0) or 0.0),
            }
        self.write()

    def finish_job(self, job_id: str) -> None:
        """A finished job leaves the journal — only interrupted
        queued/running jobs are resume candidates."""
        with self._lock:
            existed = self._jobs.pop(job_id, None) is not None
        if existed:
            self.write()

    # ---- observability ---------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "uri": self.uri,
                "sessions": len(self._sessions),
                "pending_jobs": len(self._jobs),
                "write_failures": self.write_failures,
            }


def make_journal(engine: Any, state_path: str) -> Optional[ServeStateJournal]:
    """The daemon's journal when ``fugue.serve.state_path`` is set; None
    keeps the daemon ephemeral (PR 6 behavior)."""
    base = str(state_path or "").strip()
    if base == "":
        return None
    engine.fs.makedirs(base, exist_ok=True)
    return ServeStateJournal(engine, base)
