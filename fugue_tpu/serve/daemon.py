"""The serving daemon: ONE persistent engine, many concurrent tenants.

``ServeDaemon`` composes the repo's existing parts into a resident
server (ROADMAP open item #2):

- a single long-lived execution engine (default ``"jax"``) entered as a
  context for the daemon's whole lifetime, so per-run context push/pop
  from concurrent job threads never tears it down between requests;
- :class:`~fugue_tpu.serve.session.SessionManager` sessions whose saved
  tables live device-resident in the SQL engine's catalog under a
  per-session namespace (hot across requests, no re-ingest) and are
  claimed as the memory governor's *tenants* for fair-spill accounting;
- :class:`~fugue_tpu.serve.scheduler.JobScheduler` running up to
  ``fugue.serve.max_concurrent`` FugueSQL workflows concurrently against
  the shared engine with the workflow runner's timeout + cancellation
  machinery;
- :class:`~fugue_tpu.serve.http.ServeHTTPServer` exposing the JSON API
  below on the hardened HTTP layer.

HTTP API (all JSON; errors are structured payloads, never tracebacks)::

    POST   /v1/sessions                     {"ttl": seconds?}
    GET    /v1/sessions
    GET    /v1/sessions/<sid>
    POST   /v1/sessions/<sid>/close         (alias: DELETE /v1/sessions/<sid>)
    POST   /v1/sessions/<sid>/sql           {"sql": ..., "save_as"?: name,
                                             "mode"?: "sync"|"async",
                                             "timeout"?: s, "collect"?: bool,
                                             "limit"?: rows}
    GET    /v1/jobs/<jid>                   poll an async submission
    POST   /v1/jobs/<jid>/cancel
    GET    /v1/status                       memory_stats, fault totals,
                                            fallback counters, sessions, jobs
    GET    /v1/health
"""

import threading
import time
from contextlib import nullcontext
from typing import Any, Dict, Optional, Tuple

from fugue_tpu.constants import (
    FUGUE_CONF_SERVE_HOST,
    FUGUE_CONF_SERVE_MAX_CONCURRENT,
    FUGUE_CONF_SERVE_PORT,
    FUGUE_CONF_SERVE_SESSION_TTL,
    FUGUE_CONF_SERVE_SYNC_WAIT,
    typed_conf_get,
)
from fugue_tpu.execution.factory import make_execution_engine
from fugue_tpu.rpc.http import structured_error
from fugue_tpu.serve.http import ServeHTTPServer
from fugue_tpu.serve.scheduler import JobScheduler, ServeJob
from fugue_tpu.serve.session import ServeSession, SessionManager
from fugue_tpu.sql_frontend.workflow_sql import FugueSQLWorkflow
from fugue_tpu.utils.params import ParamDict

_RESULT_YIELD = "serve_result"


class ServeDaemon:
    """A long-lived in-process serving daemon. Usable as a context
    manager; ``start()`` binds the HTTP API and returns the daemon."""

    def __init__(self, conf: Any = None, engine: Any = "jax"):
        self._engine = make_execution_engine(engine, ParamDict(conf))
        econf = self._engine.conf
        self._sessions = SessionManager(
            self._engine,
            default_ttl=typed_conf_get(econf, FUGUE_CONF_SERVE_SESSION_TTL),
        )
        self._scheduler = JobScheduler(
            self._execute_job,
            typed_conf_get(econf, FUGUE_CONF_SERVE_MAX_CONCURRENT),
        )
        http_conf = ParamDict(econf)
        http_conf["fugue.rpc.http_server.host"] = typed_conf_get(
            econf, FUGUE_CONF_SERVE_HOST
        )
        http_conf["fugue.rpc.http_server.port"] = typed_conf_get(
            econf, FUGUE_CONF_SERVE_PORT
        )
        self._http = ServeHTTPServer(self, http_conf)
        self._sync_wait = typed_conf_get(econf, FUGUE_CONF_SERVE_SYNC_WAIT)
        self._started = False
        self._started_at: Optional[float] = None
        self._stats_lock = threading.Lock()
        self._fault_totals: Dict[str, int] = {
            "runs": 0,
            "retries": 0,
            "recoveries": 0,
            "degradations": 0,
            "integrity_rejected": 0,
            "resumed": 0,
        }

    # ---- lifecycle -------------------------------------------------------
    @property
    def engine(self) -> Any:
        return self._engine

    @property
    def sessions(self) -> SessionManager:
        return self._sessions

    @property
    def scheduler(self) -> JobScheduler:
        return self._scheduler

    @property
    def address(self) -> Tuple[str, int]:
        """(host, port) of the bound HTTP API (after ``start``)."""
        return self._http.address

    def start(self) -> "ServeDaemon":
        if self._started:
            return self
        # hold ONE engine context for the daemon's lifetime: concurrent
        # job runs push/pop their own per-thread contexts on top and the
        # count never reaches zero, so the engine stays hot between
        # requests instead of stopping after each run
        self._engine.as_context()
        self._scheduler.start()
        self._http.start()
        self._started = True
        self._started_at = time.time()
        return self

    def stop(self) -> None:
        """Stop serving: HTTP down first (no new requests), then the
        scheduler (cancels queued/running jobs), then the sessions (drops
        their tables), then the daemon's engine context — which stops the
        engine, including one the caller passed in."""
        if not self._started:
            return
        self._started = False
        self._http.stop()
        self._scheduler.stop()
        self._sessions.close_all()
        self._engine.stop_context()

    def __enter__(self) -> "ServeDaemon":
        return self.start()

    def __exit__(self, *args: Any) -> None:
        self.stop()

    # ---- operations (HTTP routes call these; tests/benches may too) ------
    def create_session(self, ttl: Optional[float] = None) -> ServeSession:
        return self._sessions.create(ttl=ttl)

    def close_session(self, session_id: str) -> Dict[str, Any]:
        dropped = self._sessions.close(session_id)
        return {"closed": session_id, "dropped_tables": dropped}

    def submit(
        self,
        session_id: str,
        sql: str,
        save_as: Optional[str] = None,
        wait: bool = True,
        timeout: float = 0.0,
        collect: bool = True,
        limit: int = 10_000,
    ) -> ServeJob:
        self._sessions.get(session_id)  # 404 early + touches the session
        job = ServeJob(
            session_id,
            sql,
            save_as=save_as,
            timeout=timeout,
            collect=collect,
            limit=limit,
        )
        self._scheduler.submit(job)
        if wait:
            # bounded: a wedged job must not pin the caller (an HTTP
            # handler thread) forever — on expiry the live snapshot goes
            # back (status still queued/running) and the client polls
            # /v1/jobs/<id> exactly like an async submission
            job.done_event.wait(
                timeout=self._sync_wait if self._sync_wait > 0 else None
            )
        return job

    def status(self) -> Dict[str, Any]:
        self._sessions.sweep()
        engine_stats: Dict[str, Any] = {
            "type": type(self._engine).__name__,
            "parallelism": self._engine.get_current_parallelism(),
        }
        mem = getattr(self._engine, "memory_stats", None)
        if isinstance(mem, dict):
            engine_stats["memory"] = mem
        fallbacks = getattr(self._engine, "fallbacks", None)
        if isinstance(fallbacks, dict):
            engine_stats["fallbacks"] = fallbacks
        with self._stats_lock:
            fault_totals = dict(self._fault_totals)
        return {
            "uptime_seconds": (
                round(time.time() - self._started_at, 3)
                if self._started_at is not None
                else 0.0
            ),
            "engine": engine_stats,
            "sessions": {
                "count": self._sessions.count(),
                "active": self._sessions.describe(),
            },
            "jobs": self._scheduler.counts(),
            "fault_stats": fault_totals,
        }

    # ---- job execution (scheduler worker threads) ------------------------
    def _execute_job(self, job: ServeJob) -> Dict[str, Any]:
        session = self._sessions.get(job.session_id)
        dag = FugueSQLWorkflow()
        sources = session.table_frames()
        dag._sql(job.sql, {}, **sources)
        has_result = dag.last_df is not None
        if has_result:
            dag.last_df.yield_dataframe_as(_RESULT_YIELD)
        gov = getattr(self._engine, "memory_governor", None)
        # tenant_scope is THREAD-local: it covers the run's serial task
        # execution (the inner runner defaults to concurrency 1, in
        # thread) and this thread's save/collect materializations; a
        # parallel inner runner's worker threads are outside it, which
        # is fine — durable ownership comes from assign_tenant at
        # save_table time, and unsaved frames die with the job anyway
        scope = (
            gov.tenant_scope(job.session_id)
            if gov is not None
            else nullcontext()
        )
        with scope:
            wres = dag.run(self._engine, cancel_token=job.token)
            self._note_fault_stats(wres.fault_stats)
            payload: Dict[str, Any] = {
                "yields": sorted(
                    k for k in dag.yields if k != _RESULT_YIELD
                ),
            }
            if not has_result:
                return payload
            df = wres[_RESULT_YIELD]
            if job.save_as is not None:
                session.save_table(job.save_as, df)
                payload["saved_as"] = job.save_as
            if job.collect:
                from fugue_tpu.workflow.fault import engine_dispatch_guard

                # head() on a device frame reads back through device
                # programs: serialize with concurrent jobs; the job's
                # token makes the wait cancellable
                with engine_dispatch_guard(self._engine, job.token):
                    local = df.head(job.limit + 1)
                rows = local.as_array(type_safe=True)
                truncated = len(rows) > job.limit
                payload["result"] = {
                    "columns": list(df.schema.names),
                    "types": str(df.schema),
                    "rows": rows[: job.limit],
                    "row_count": min(len(rows), job.limit),
                    "truncated": truncated,
                }
        session.touch()
        return payload

    def _note_fault_stats(self, stats: Dict[str, Any]) -> None:
        with self._stats_lock:
            self._fault_totals["runs"] += 1
            for key in (
                "retries", "recoveries", "degradations",
                "integrity_rejected",
            ):
                self._fault_totals[key] += sum(
                    (stats.get(key) or {}).values()
                )
            self._fault_totals["resumed"] += len(stats.get("resumed") or [])

    # ---- HTTP routing ----------------------------------------------------
    def handle_api(
        self, method: str, path: str, payload: Dict[str, Any]
    ) -> Tuple[int, Dict[str, Any]]:
        """Route one API request; returns (status, JSON-safe response).
        Never raises: handler failures become structured error payloads
        (KeyError -> 404, bad input -> 400, the rest -> 500)."""
        try:
            return self._route(method, path, payload)
        except KeyError as ex:
            return 404, {"error": structured_error(ex)}
        except (ValueError, TypeError) as ex:
            return 400, {"error": structured_error(ex)}
        except Exception as ex:  # pragma: no cover - defensive
            return 500, {"error": structured_error(ex)}

    def _route(
        self, method: str, path: str, payload: Dict[str, Any]
    ) -> Tuple[int, Dict[str, Any]]:
        parts = [p for p in path.split("?", 1)[0].split("/") if p]
        if not parts or parts[0] != "v1":
            raise KeyError(f"unknown path {path}")
        route = parts[1:]
        if route == ["health"] and method == "GET":
            return 200, {"ok": True}
        if route == ["status"] and method == "GET":
            return 200, self.status()
        if route == ["sessions"]:
            if method == "POST":
                ttl = payload.get("ttl")
                session = self.create_session(
                    ttl=None if ttl is None else float(ttl)
                )
                return 200, {
                    "session_id": session.session_id,
                    "ttl": session.ttl,
                }
            if method == "GET":
                self._sessions.sweep()
                return 200, {"sessions": self._sessions.describe()}
        if len(route) >= 2 and route[0] == "sessions":
            sid = route[1]
            rest = route[2:]
            if not rest and method == "GET":
                return 200, self._sessions.get(sid).describe()
            if (not rest and method == "DELETE") or (
                rest == ["close"] and method == "POST"
            ):
                return 200, self.close_session(sid)
            if rest == ["sql"] and method == "POST":
                return self._route_sql(sid, payload)
        if len(route) >= 2 and route[0] == "jobs":
            jid = route[1]
            rest = route[2:]
            if not rest and method == "GET":
                return 200, self._scheduler.get(jid).snapshot()
            if rest == ["cancel"] and method == "POST":
                return 200, self._scheduler.cancel(jid).snapshot(
                    include_result=False
                )
        raise KeyError(f"unknown route {method} {path}")

    def _route_sql(
        self, sid: str, payload: Dict[str, Any]
    ) -> Tuple[int, Dict[str, Any]]:
        sql = payload.get("sql")
        if not isinstance(sql, str) or not sql.strip():
            raise ValueError("payload must carry a non-empty 'sql' string")
        mode = str(payload.get("mode", "sync")).lower()
        if mode not in ("sync", "async"):
            raise ValueError(f"mode must be sync|async, got {mode!r}")
        job = self.submit(
            sid,
            sql,
            save_as=payload.get("save_as"),
            wait=mode == "sync",
            timeout=float(payload.get("timeout", 0.0)),
            collect=bool(payload.get("collect", True)),
            limit=int(payload.get("limit", 10_000)),
        )
        if mode == "async":
            return 202, job.snapshot(include_result=False)
        return 200, job.snapshot()
